"""Beam search ops (reference operators/beam_search_op.cc +
beam_search_decode_op.cc, used inside a While loop by
fluid.layers.beam_search for seq2seq decoding).

TPU-native re-design: the reference pruned beams into LoD tensors of
varying width; here every step keeps a FIXED [B, beam] frontier (finished
beams are forced to continue emitting end_id with frozen scores), so the
whole decode loop is static-shape — one top_k over [B, beam*V] per step on
the VPU instead of the reference's per-sequence CPU heap. Backtracking
(beam_search_decode) is a reverse lax.scan over the stacked parent
pointers.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op

NEG_INF = -1e9


@register_op(
    "beam_search",
    inputs=["PreIds", "PreScores", "Scores"],
    outputs=["SelectedIds", "SelectedScores", "ParentIdx"],
    differentiable=False,
)
def _beam_search(ctx, op, ins):
    """One expansion step.

    PreIds/PreScores [B, beam]; Scores [B, beam, V]: accumulated prefix
    scores when is_accumulated=True (fluid default), per-step log-probs
    otherwise. Finished beams (pre_id == end_id) may only continue as
    end_id, keeping their score."""
    pre_ids = ins["PreIds"][0].astype(jnp.int32)
    pre_scores = ins["PreScores"][0]
    logp = ins["Scores"][0]
    beam = op.attr("beam_size")
    end_id = op.attr("end_id", 1)
    first_step = bool(op.attr("first_step", False))
    is_accumulated = bool(op.attr("is_accumulated", True))
    B, K, V = logp.shape

    # a start token that happens to equal end_id must not freeze the whole
    # decode before it begins: pass first_step=True on the bos step (an
    # extension over the fluid signature — fluid had no such hazard because
    # its LoD pruning dropped finished beams out of the frontier)
    finished = (
        jnp.zeros((B, K), bool) if first_step else pre_ids == end_id
    )
    # is_accumulated=True (fluid default): `Scores` already contains the
    # accumulated prefix score; False: per-step log-probs to be added
    total = logp if is_accumulated else pre_scores[..., None] + logp
    # finished beams: only end_id survives, score frozen
    onehot_end = jnp.arange(V)[None, None, :] == end_id
    frozen = jnp.where(onehot_end, pre_scores[..., None], NEG_INF)
    total = jnp.where(finished[..., None], frozen, total)

    flat = total.reshape(B, K * V)
    top_scores, top_idx = lax.top_k(flat, beam)
    # int32 throughout: jax x64 is disabled, and vocab/beam indices fit
    parent = (top_idx // V).astype(jnp.int32)
    ids = (top_idx % V).astype(jnp.int32)
    return {
        "SelectedIds": [ids],
        "SelectedScores": [top_scores],
        "ParentIdx": [parent],
    }


@register_op(
    "beam_search_decode",
    inputs=["Ids", "ParentIdx"],
    outputs=["SentenceIds", "SentenceScores"],
    differentiable=False,
)
def _beam_search_decode(ctx, op, ins):
    """Backtrack stacked per-step selections into full sequences.

    Ids/ParentIdx [T, B, beam] -> SentenceIds [B, beam, T] (each final beam
    k traced back through its parent chain). SentenceScores passes through
    the final step's scores when provided via attr (host keeps them)."""
    ids = ins["Ids"][0].astype(jnp.int32)  # [T, B, beam]
    parents = ins["ParentIdx"][0].astype(jnp.int32)
    T, B, K = ids.shape
    b_idx = jnp.arange(B)[:, None]

    def back(beam_ptr, inp):
        step_ids, step_parents = inp
        tok = step_ids[b_idx, beam_ptr]  # [B, K]
        prev = step_parents[b_idx, beam_ptr]
        return prev, tok

    init = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
    _, toks = lax.scan(back, init, (ids, parents), reverse=True)
    # toks [T, B, K] in forward order after reverse scan
    return {
        "SentenceIds": [jnp.transpose(toks, (1, 2, 0))],
        "SentenceScores": [],
    }
