"""Dense math ops: elementwise, matmul, reductions, scale/sum/mean/clip.

Capability parity with the reference's operators/elementwise/,
operators/reduce_ops/, matmul_op.cc, mul_op.cc, scale_op.cc, sum_op.cc,
mean_op.cc, clip_op.cc — all as XLA emitters (matmul lands on the MXU via
jnp.matmul/dot_general; elementwise ops fuse into neighbors automatically).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from ._helpers import (
    fluid_broadcast,
    register_elementwise,
    register_reduce,
    register_unary,
)

register_elementwise("elementwise_add", jnp.add)
register_elementwise("elementwise_sub", jnp.subtract)
register_elementwise("elementwise_mul", jnp.multiply)
register_elementwise("elementwise_div", jnp.divide)
register_elementwise("elementwise_max", jnp.maximum)
register_elementwise("elementwise_min", jnp.minimum)
register_elementwise("elementwise_pow", jnp.power)
register_elementwise("elementwise_mod", jnp.mod)
register_elementwise("elementwise_floordiv", jnp.floor_divide)

register_unary("sqrt", lambda x, a: jnp.sqrt(x))
register_unary("rsqrt", lambda x, a: lax.rsqrt(x))
register_unary("square", lambda x, a: jnp.square(x))
register_unary("abs", lambda x, a: jnp.abs(x))
register_unary("exp", lambda x, a: jnp.exp(x))
register_unary("log", lambda x, a: jnp.log(x))
register_unary("log2", lambda x, a: jnp.log2(x))
register_unary("log1p", lambda x, a: jnp.log1p(x))
register_unary("floor", lambda x, a: jnp.floor(x))
register_unary("ceil", lambda x, a: jnp.ceil(x))
register_unary("round", lambda x, a: jnp.round(x))
register_unary("reciprocal", lambda x, a: jnp.reciprocal(x))
register_unary("sign", lambda x, a: jnp.sign(x))
register_unary("sin", lambda x, a: jnp.sin(x))
register_unary("cos", lambda x, a: jnp.cos(x))
register_unary("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
register_unary(
    "logical_not", lambda x, a: jnp.logical_not(x), differentiable=False
)
register_unary("isfinite", lambda x, a: jnp.isfinite(x), differentiable=False)

register_reduce("reduce_sum", jnp.sum)
register_reduce("reduce_mean", jnp.mean)
register_reduce("reduce_max", jnp.max)
register_reduce("reduce_min", jnp.min)
register_reduce("reduce_prod", jnp.prod)
register_reduce("reduce_all", jnp.all)
register_reduce("reduce_any", jnp.any)


@register_op("scale", inputs=["X"], outputs=["Out"])
def _scale(ctx, op, ins):
    x = ins["X"][0]
    scale = op.attr("scale", 1.0)
    bias = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    return {"Out": [out.astype(x.dtype)]}


@register_op("sum", inputs=["X"], outputs=["Out"])
def _sum(ctx, op, ins):
    xs = [x for x in ins["X"] if x is not None]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("mean", inputs=["X"], outputs=["Out"])
def _mean(ctx, op, ins):
    return {"Out": [jnp.mean(ins["X"][0]).reshape([1])]}


@register_op("clip", inputs=["X"], outputs=["Out"])
def _clip(ctx, op, ins):
    return {"Out": [jnp.clip(ins["X"][0], op.attr("min"), op.attr("max"))]}


@register_op("clip_by_norm", inputs=["X"], outputs=["Out"])
def _clip_by_norm(ctx, op, ins):
    x = ins["X"][0]
    max_norm = op.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [x * (max_norm / jnp.maximum(norm, max_norm))]}


@register_op("matmul", inputs=["X", "Y"], outputs=["Out"])
def _matmul(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    if op.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if op.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = op.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("mul", inputs=["X", "Y"], outputs=["Out"])
def _mul(ctx, op, ins):
    # fluid mul op (mul_op.cc): flatten x to 2-D at x_num_col_dims, y likewise
    x, y = ins["X"][0], ins["Y"][0]
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((math.prod(xs[:xnc]), -1))
    y2 = y.reshape((math.prod(ys[:ync]), -1))
    out = jnp.matmul(x2, y2)
    return {"Out": [out.reshape(xs[:xnc] + ys[ync:])]}


@register_op("dot", inputs=["X", "Y"], outputs=["Out"])
def _dot(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}


@register_op("bmm", inputs=["X", "Y"], outputs=["Out"])
def _bmm(ctx, op, ins):
    return {"Out": [jnp.matmul(ins["X"][0], ins["Y"][0])]}


for _cmp_type, _cmp_fn in [
    ("equal", jnp.equal),
    ("not_equal", jnp.not_equal),
    ("less_than", jnp.less),
    ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater),
    ("greater_equal", jnp.greater_equal),
]:

    def _make(fn):
        def emit(ctx, op, ins):
            x, y = ins["X"][0], ins["Y"][0]
            x, y = fluid_broadcast(x, y, op.attr("axis", -1))
            return {"Out": [fn(x, y)]}

        return emit

    register_op(_cmp_type, inputs=["X", "Y"], outputs=["Out"], differentiable=False)(
        _make(_cmp_fn)
    )

for _log_type, _log_fn in [
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:

    def _make_l(fn):
        def emit(ctx, op, ins):
            return {"Out": [fn(ins["X"][0], ins["Y"][0])]}

        return emit

    register_op(_log_type, inputs=["X", "Y"], outputs=["Out"], differentiable=False)(
        _make_l(_log_fn)
    )
