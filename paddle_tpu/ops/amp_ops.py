"""AMP support ops (reference: operators/amp/amp_check_finite_and_scale_op.cc
and the update_loss_scaling logic used by contrib/mixed_precision/decorator.py).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.registry import register_op


@register_op(
    "amp_check_finite_and_scale",
    inputs=["X", "Scale"],
    outputs=["Out", "FoundInfinite"],
    differentiable=False,
)
def _amp_check_finite_and_scale(ctx, op, ins):
    scale = ins["Scale"][0].reshape(())
    xs = [x for x in ins["X"] if x is not None]
    found = jnp.zeros((), dtype=bool)
    for x in xs:
        found = found | ~jnp.all(jnp.isfinite(x))
    outs = [x * scale for x in xs]
    return {"Out": outs, "FoundInfinite": [found.reshape([1])]}


@register_op(
    "update_loss_scaling",
    inputs=["X", "FoundInfinite", "PrevLossScaling", "InGoodSteps", "InBadSteps"],
    outputs=["Out", "LossScaling", "OutGoodSteps", "OutBadSteps"],
    differentiable=False,
)
def _update_loss_scaling(ctx, op, ins):
    found = ins["FoundInfinite"][0].reshape(())
    prev = ins["PrevLossScaling"][0].reshape(())
    good = ins["InGoodSteps"][0].reshape(())
    bad = ins["InBadSteps"][0].reshape(())
    incr_every = op.attr("incr_every_n_steps", 1000)
    decr_every = op.attr("decr_every_n_nan_or_inf", 2)
    incr_ratio = op.attr("incr_ratio", 2.0)
    decr_ratio = op.attr("decr_ratio", 0.5)

    new_bad = jnp.where(found, bad + 1, jnp.zeros_like(bad))
    new_good = jnp.where(found, jnp.zeros_like(good), good + 1)
    shrink = new_bad >= decr_every
    grow = new_good >= incr_every
    # shrink floor: never *raise* the scale through the shrink branch — a
    # plain max(.., 1.0) would silently bump a sub-1.0 (static) scale up
    floor = jnp.minimum(prev, 1.0)
    # grow guard: keep the previous scale if doubling overflows to inf
    # (reference update_loss_scaling_op.h keeps prev when the incremented
    # scale is non-finite) — otherwise a long always-finite run saturates
    # the scale at inf and silently zeroes every gradient from then on
    grown = prev * incr_ratio
    grown = jnp.where(jnp.isfinite(grown), grown, prev)
    scale = jnp.where(
        shrink,
        jnp.maximum(prev * decr_ratio, floor),
        jnp.where(grow, grown, prev),
    )
    new_bad = jnp.where(shrink, jnp.zeros_like(new_bad), new_bad)
    new_good = jnp.where(grow, jnp.zeros_like(new_good), new_good)

    # zero out grads on overflow steps so the optimizer update is a no-op
    xs = [x for x in ins["X"] if x is not None]
    outs = [jnp.where(found, jnp.zeros_like(x), x / prev) for x in xs]
    return {
        "Out": outs,
        "LossScaling": [scale.reshape([1])],
        "OutGoodSteps": [new_good.reshape([1]).astype(np.int32)],
        "OutBadSteps": [new_bad.reshape([1]).astype(np.int32)],
    }


@register_op(
    "check_finite_and_unscale",
    inputs=["X", "Scale"],
    outputs=["Out", "FoundInfinite"],
    differentiable=False,
)
def _check_finite_and_unscale(ctx, op, ins):
    scale = ins["Scale"][0].reshape(())
    xs = [x for x in ins["X"] if x is not None]
    found = jnp.zeros((), dtype=bool)
    for x in xs:
        found = found | ~jnp.all(jnp.isfinite(x))
    outs = [x / scale for x in xs]
    return {"Out": outs, "FoundInfinite": [found.reshape([1])]}
