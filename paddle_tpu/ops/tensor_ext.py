"""Tensor-surface long tail: v1 aliases of the *2 ops, crop/diag/unbind,
static-shape unique, scaffolding ops (print/assert/is_empty), and the
SelectedRows utility trio.

Reference files (paddle/fluid/operators/): reshape_op.cc, transpose_op.cc,
squeeze_op.cc, unsqueeze_op.cc, unbind_op.cc, reverse_op.cc, fill_op.cc,
fill_zeros_like_op.cc (fill_zeros_like2), crop_op.cc, crop_tensor_op.cc,
diag_op.cc, is_empty_op.cc, reduce_ops/frobenius_norm_op.cc,
partial_concat_op.cc, partial_sum_op.cc, unfold_op.cc, unique_op.cc,
unique_with_counts_op.cc, scatter_nd_add_op.cc, hash_op.cc, print_op.cc,
assert_op.cc, conv_shift_op.cc, get_tensor_from_selected_rows_op.cc,
merge_selected_rows_op.cc, split_selected_rows_op.cc, py_func_op.cc.

Dynamic-output-shape ops (unique) use jnp.unique's static `size=` form: the
output is padded to the input length and an explicit count is returned —
the TPU-native contract for ops whose reference semantics resize tensors.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtypes import to_numpy_dtype
from ..framework.registry import register_op


def _resolve_shape(shape, x_shape):
    out = []
    for i, s in enumerate(shape):
        out.append(int(x_shape[i]) if s == 0 else int(s))
    return out


# ---------------------------------------------------------------------------
# v1 aliases of the *2 ops (reference keeps both registrations; the v1 form
# has no XShape output — reshape_op.cc vs reshape2 in the same file)
# ---------------------------------------------------------------------------


@register_op("reshape", inputs=["X"], outputs=["Out"])
def _reshape(ctx, op, ins):
    x = ins["X"][0]
    return {"Out": [jnp.reshape(x, _resolve_shape(op.attr("shape"), x.shape))]}


@register_op("transpose", inputs=["X"], outputs=["Out"])
def _transpose(ctx, op, ins):
    return {"Out": [jnp.transpose(ins["X"][0], op.attr("axis"))]}


@register_op("squeeze", inputs=["X"], outputs=["Out"])
def _squeeze(ctx, op, ins):
    x = ins["X"][0]
    axes = op.attr("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    return {"Out": [out]}


@register_op("unsqueeze", inputs=["X"], outputs=["Out"])
def _unsqueeze(ctx, op, ins):
    out = ins["X"][0]
    for a in sorted(op.attr("axes")):
        out = jnp.expand_dims(out, axis=a)
    return {"Out": [out]}


@register_op("unbind", inputs=["X"], outputs=["Out"])
def _unbind(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", 0) % x.ndim
    return {
        "Out": [
            jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, x.shape[axis], axis=axis)
        ]
    }


@register_op("reverse", inputs=["X"], outputs=["Out"])
def _reverse(ctx, op, ins):
    x = ins["X"][0]
    axes = [a % x.ndim for a in op.attr("axis")]
    return {"Out": [jnp.flip(x, axis=axes)]}


@register_op("fill", inputs=[], outputs=["Out"], differentiable=False)
def _fill(ctx, op, ins):
    dt = to_numpy_dtype(op.attr("dtype", "float32"))
    data = np.asarray(op.attr("value"), dtype=np.float64)
    return {"Out": [jnp.asarray(data.reshape(op.attr("shape")), dtype=dt)]}


@register_op(
    "fill_zeros_like2", inputs=["X"], outputs=["Out"], differentiable=False
)
def _fill_zeros_like2(ctx, op, ins):
    x = ins["X"][0]
    dt = op.attr("dtype", None)
    dt = x.dtype if dt in (None, -1) else to_numpy_dtype(dt)
    return {"Out": [jnp.zeros_like(x, dtype=dt)]}


# ---------------------------------------------------------------------------
# crop family (crop_op.cc: offsets attr or Offsets input; crop_tensor_op.cc
# adds Shape/ShapeTensor inputs). Slice sizes are static (output shape comes
# from attrs/graph-build), offsets may be runtime tensors → dynamic_slice.
# ---------------------------------------------------------------------------


def _crop_common(x, out_shape, offsets):
    out_shape = [int(s) for s in out_shape]
    if isinstance(offsets, (list, tuple)) or (
        isinstance(offsets, np.ndarray)
    ):
        start = [int(o) for o in offsets]
        idx = tuple(
            slice(s, s + L) for s, L in zip(start, out_shape)
        )
        return x[idx]
    # runtime offsets tensor → dynamic_slice with static sizes
    starts = [offsets[i] for i in range(len(out_shape))]
    return jax.lax.dynamic_slice(x, starts, out_shape)


@register_op("crop", inputs=["X", "Y", "Offsets"], outputs=["Out"])
def _crop(ctx, op, ins):
    x = ins["X"][0]
    y = ins.get("Y", [None])
    shape = op.attr("shape", None)
    if (not shape) and y and y[0] is not None:
        shape = y[0].shape
    offs = ins.get("Offsets", [None])
    offsets = offs[0] if offs and offs[0] is not None else op.attr(
        "offsets", [0] * x.ndim
    )
    return {"Out": [_crop_common(x, shape, offsets)]}


@register_op(
    "crop_tensor",
    inputs=["X", "Shape", "Offsets"],
    outputs=["Out"],
)
def _crop_tensor(ctx, op, ins):
    x = ins["X"][0]
    # output shape must be static under XLA: take the attr (graph-build
    # value); a runtime Shape tensor only confirms it (crop_tensor_op.cc
    # allows either — the static component is always present in the attr)
    shape = op.attr("shape", None) or list(x.shape)
    shape = _resolve_shape(shape, x.shape)
    offs = ins.get("Offsets", [None])
    offsets = offs[0] if offs and offs[0] is not None else op.attr(
        "offsets", [0] * x.ndim
    )
    return {"Out": [_crop_common(x, shape, offsets)]}


@register_op("diag", inputs=["Diagonal"], outputs=["Out"])
def _diag(ctx, op, ins):
    return {"Out": [jnp.diag(ins["Diagonal"][0])]}


@register_op("is_empty", inputs=["X"], outputs=["Out"], differentiable=False)
def _is_empty(ctx, op, ins):
    return {"Out": [jnp.asarray(ins["X"][0].size == 0)]}


@register_op("frobenius_norm", inputs=["X"], outputs=["Out"])
def _frobenius_norm(ctx, op, ins):
    x = ins["X"][0]
    if op.attr("reduce_all", False):
        axes = None
    else:
        dim = op.attr("dim", [0])
        axes = tuple(d % x.ndim for d in (dim if dim else range(x.ndim)))
    out = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes,
                           keepdims=op.attr("keep_dim", False)))
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# partial concat/sum (CTR models: take a column window of every feature
# matrix — partial_concat_op.cc:147, partial_sum_op.cc:147)
# ---------------------------------------------------------------------------


def _partial_slices(ins, op):
    start = op.attr("start_index", 0)
    length = op.attr("length", -1)
    outs = []
    for x in ins["X"]:
        s = start % x.shape[1] if start < 0 else start
        e = x.shape[1] if length == -1 else s + length
        outs.append(x[:, s:e])
    return outs


@register_op("partial_concat", inputs=["X"], outputs=["Out"])
def _partial_concat(ctx, op, ins):
    return {"Out": [jnp.concatenate(_partial_slices(ins, op), axis=1)]}


@register_op("partial_sum", inputs=["X"], outputs=["Out"])
def _partial_sum(ctx, op, ins):
    parts = _partial_slices(ins, op)
    out = parts[0]
    for p in parts[1:]:
        out = out + p
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# unfold (im2col as an op, unfold_op.cc:23): [N,C,H,W] -> [N, C*kh*kw, L]
# ---------------------------------------------------------------------------


@register_op("unfold", inputs=["X"], outputs=["Y"])
def _unfold(ctx, op, ins):
    x = ins["X"][0]
    kh, kw = op.attr("kernel_sizes")
    sh, sw = op.attr("strides", [1, 1])
    pads = op.attr("paddings", [0, 0, 0, 0])
    dh, dw = op.attr("dilations", [1, 1])
    if len(pads) == 2:
        pads = [pads[0], pads[1], pads[0], pads[1]]
    n, c, h, w = x.shape
    x = jnp.pad(
        x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3]))
    )
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    # extract patches via gather of strided windows: build index grids once
    # (static), one gather per axis — XLA lowers this to an efficient copy
    rows = (np.arange(oh)[:, None] * sh + np.arange(kh)[None, :] * dh)
    cols = (np.arange(ow)[:, None] * sw + np.arange(kw)[None, :] * dw)
    patches = x[:, :, rows, :][:, :, :, :, cols]
    # [n, c, oh, kh, ow, kw] -> [n, c, kh, kw, oh*ow]
    patches = jnp.transpose(patches, (0, 1, 3, 5, 2, 4))
    return {"Y": [patches.reshape(n, c * kh * kw, oh * ow)]}


# ---------------------------------------------------------------------------
# unique (static-size form). The reference resizes Out to the number of
# distinct values (unique_op.cc); under XLA output shapes are static, so Out
# keeps the input length: the first `count` entries are the unique values
# (first-occurrence order is NOT preserved — sorted order, as jnp.unique),
# the tail is padded with the first unique value. Index maps each input
# element to its position in Out, so gather(Out, Index) == X always holds.
# ---------------------------------------------------------------------------


@register_op(
    "unique", inputs=["X"], outputs=["Out", "Index"], differentiable=False
)
def _unique(ctx, op, ins):
    x = ins["X"][0].reshape(-1)
    out, inv = jnp.unique(x, return_inverse=True, size=x.size)
    idx_dt = to_numpy_dtype(op.attr("dtype", "int64"))
    return {"Out": [out], "Index": [inv.astype(idx_dt)]}


@register_op(
    "unique_with_counts",
    inputs=["X"],
    outputs=["Out", "Index", "Count"],
    differentiable=False,
)
def _unique_with_counts(ctx, op, ins):
    x = ins["X"][0].reshape(-1)
    out, inv, counts = jnp.unique(
        x, return_inverse=True, return_counts=True, size=x.size
    )
    idx_dt = to_numpy_dtype(op.attr("dtype", "int64"))
    return {
        "Out": [out],
        "Index": [inv.astype(idx_dt)],
        "Count": [counts.astype(idx_dt)],
    }


@register_op("scatter_nd_add", inputs=["X", "Index", "Updates"], outputs=["Out"])
def _scatter_nd_add(ctx, op, ins):
    x, index, updates = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    idx = tuple(index[..., i] for i in range(index.shape[-1]))
    return {"Out": [x.at[idx].add(updates)]}


# ---------------------------------------------------------------------------
# hash (hash_op.cc:53 num_hash/mod_by; reference uses xxhash over raw bytes).
# TPU-native: a multiply-xorshift integer mix with a distinct odd constant
# per hash slot — same contract (num_hash deterministic hashes mod mod_by),
# vectorized over the id tensor instead of a per-row CPU loop.
# ---------------------------------------------------------------------------


@register_op("hash", inputs=["X"], outputs=["Out"], differentiable=False)
def _hash(ctx, op, ins):
    from ._helpers import hash_mix

    x = ins["X"][0].astype(jnp.uint32)
    num_hash = op.attr("num_hash", 1)
    mod_by = op.attr("mod_by", 100000)
    h = hash_mix(x, num_hash)  # [..., cols, num_hash]
    # combine the id columns of each slot (reference hashes the whole row)
    h = jnp.sum(jnp.swapaxes(h, -1, -2), axis=-1, keepdims=True,
                dtype=jnp.uint32)
    out = (h % jnp.uint32(mod_by)).astype(jnp.int64)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# conv_shift (conv_shift_op.cc: circular correlation, NTM addressing):
# X [B, M], Y [B, N] (N odd, N <= M) -> Out[b, i] = sum_j Y[b,j] *
# X[b, (i + j - N/2) mod M]
# ---------------------------------------------------------------------------


@register_op("conv_shift", inputs=["X", "Y"], outputs=["Out"])
def _conv_shift(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    m, n = x.shape[1], y.shape[1]
    half = n // 2
    # gather the N circularly-shifted copies of X once: [B, N, M]
    shift_idx = (np.arange(m)[None, :] + np.arange(n)[:, None] - half) % m
    shifted = x[:, shift_idx]  # [B, N, M]
    return {"Out": [jnp.einsum("bn,bnm->bm", y, shifted)]}


# ---------------------------------------------------------------------------
# scaffolding: print / assert / delete_var (print_op.cc, assert_op.cc,
# controlflow/op_variant.cc delete_var). print forwards its input and logs
# via jax.debug.print (works inside jit; the reference prints on the host
# between op dispatches — same observable effect).
# ---------------------------------------------------------------------------


@register_op("print", inputs=["In"], outputs=["Out"])
def _print(ctx, op, ins):
    x = ins["In"][0]
    if not ctx.abstract:
        jax.debug.print(
            op.attr("message", "") + " {}", x, ordered=False
        )
    return {"Out": [x]}


@register_op("assert", inputs=["Cond", "Data"], outputs=[], differentiable=False)
def _assert(ctx, op, ins):
    cond = ins["Cond"][0]
    summarize = op.attr("summarize", -1)
    if not ctx.abstract:
        def _check(c):
            if not np.all(np.asarray(c)):
                raise AssertionError(
                    f"assert op failed (summarize={summarize})"
                )

        jax.debug.callback(_check, cond)
    return {}


@register_op("delete_var", inputs=["X"], outputs=[], differentiable=False)
def _delete_var(ctx, op, ins):
    # buffer lifetime is XLA's job here (donation + liveness); the op exists
    # for graph parity and is a no-op at trace time
    return {}


# ---------------------------------------------------------------------------
# SelectedRows utilities. This framework's sparse design keeps gradients
# dense (or row-sharded tables, ops/sparse.py), so a "SelectedRows" at the
# op surface is the (rows, ids) pair the emitters already produce; the
# utility trio operates on the dense form.
# ---------------------------------------------------------------------------


@register_op(
    "get_tensor_from_selected_rows", inputs=["X"], outputs=["Out"]
)
def _get_tensor_from_selected_rows(ctx, op, ins):
    return {"Out": [ins["X"][0]]}


@register_op("merge_selected_rows", inputs=["X"], outputs=["Out"])
def _merge_selected_rows(ctx, op, ins):
    # dense rows are already merged (duplicate ids summed by scatter-add at
    # producer site, merge_selected_rows_op.cc role)
    return {"Out": [ins["X"][0]]}


@register_op("split_selected_rows", inputs=["X"], outputs=["Out"])
def _split_selected_rows(ctx, op, ins):
    x = ins["X"][0]
    height_sections = op.attr("height_sections", [])
    if not height_sections:
        return {"Out": [x]}
    idx = np.cumsum(height_sections[:-1]).tolist()
    return {"Out": list(jnp.split(x, idx, axis=0))}


# ---------------------------------------------------------------------------
# py_func (py_func_op.cc): host python inside the compiled graph via
# jax.pure_callback — the TPU-native replacement for the reference's
# pybind-trampoline kernel. The callable is looked up in a host registry
# by the op's handle attr.
# ---------------------------------------------------------------------------

PY_FUNC_REGISTRY: list = []


def register_py_func(fn) -> int:
    """Register fn; returns the handle to store in the op's attr."""
    PY_FUNC_REGISTRY.append(fn)
    return len(PY_FUNC_REGISTRY) - 1


@register_op("py_func", inputs=["X"], outputs=["Out"], differentiable=False)
def _py_func(ctx, op, ins):
    fn = PY_FUNC_REGISTRY[int(op.attr("forward_callable_id"))]
    xs = ins["X"]
    out_block_shapes = op.attr("out_shapes", None)
    out_dtypes = op.attr("out_dtypes", None)
    if out_block_shapes is None:
        # same-shape contract when undeclared
        result_shape = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs]
    else:
        result_shape = [
            jax.ShapeDtypeStruct(tuple(s), to_numpy_dtype(d))
            for s, d in zip(out_block_shapes, out_dtypes)
        ]

    def host_fn(*arrays):
        res = fn(*arrays)
        if not isinstance(res, (list, tuple)):
            res = [res]
        return [np.asarray(r) for r in res]

    outs = jax.pure_callback(host_fn, result_shape, *xs)
    return {"Out": list(outs)}
