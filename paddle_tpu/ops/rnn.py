"""Recurrent ops: LSTM / GRU over padded batches.

Reference: operators/cudnn_lstm_op.cu / lstm_op.cc / gru_op.cc and the
LoD-aware dynamic_lstm/dynamic_gru (fluid.layers). TPU-native: one op per
layer, a lax.scan over the time axis of dense [B, T, D] input — XLA fuses
the gate matmuls per step and the generic __vjp__ provides BPTT (the
reference hand-wrote lstm_grad kernels). Variable lengths are handled by
masking: steps beyond a row's length carry the previous state through, so
LastH/LastC equal the state at each row's true end (LoD semantics).

Gate layout (i, f, g, o for LSTM; u, r, c for GRU) over stacked weights:
W_ih [G*H, D], W_hh [G*H, H], bias [G*H].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _seq_mask(lengths, B, T):
    if lengths is None:
        return jnp.ones((T, B, 1), jnp.float32)
    r = jnp.arange(T)[:, None]
    return (r < lengths.astype(jnp.int32)[None, :]).astype(jnp.float32)[
        ..., None
    ]


@register_op(
    "lstm",
    inputs=["X", "WIH", "WHH", "Bias", "H0", "C0", "SeqLen"],
    outputs=["Out", "LastH", "LastC"],
)
def _lstm(ctx, op, ins):
    x = ins["X"][0]  # [B, T, D]
    wih, whh = ins["WIH"][0], ins["WHH"][0]  # [4H, D], [4H, H]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    B, T, D = x.shape
    H = whh.shape[1]
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((B, H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") and ins["C0"][0] is not None else jnp.zeros((B, H), x.dtype)
    lens = ins["SeqLen"][0] if ins.get("SeqLen") and ins["SeqLen"][0] is not None else None

    xs = jnp.swapaxes(x, 0, 1)  # [T, B, D]
    # pre-compute input projections for every step in one big matmul (MXU)
    xproj = jnp.einsum("tbd,gd->tbg", xs, wih)
    if bias is not None:
        xproj = xproj + bias
    mask = _seq_mask(lens, B, T)

    def step(carry, inp):
        h, c = carry
        xp, m = inp
        gates = xp + h @ whh.T
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        # masked carry-through: padded steps keep the old state
        h_out = m * h_new + (1 - m) * h
        c_out = m * c_new + (1 - m) * c
        return (h_out, c_out), h_out

    (h_last, c_last), hs = lax.scan(step, (h0, c0), (xproj, mask))
    return {
        "Out": [jnp.swapaxes(hs, 0, 1)],
        "LastH": [h_last],
        "LastC": [c_last],
    }


@register_op(
    "gru",
    inputs=["X", "WIH", "WHH", "Bias", "H0", "SeqLen"],
    outputs=["Out", "LastH"],
)
def _gru(ctx, op, ins):
    x = ins["X"][0]
    wih, whh = ins["WIH"][0], ins["WHH"][0]  # [3H, D], [3H, H]
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    B, T, D = x.shape
    H = whh.shape[1]
    h0 = ins["H0"][0] if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((B, H), x.dtype)
    lens = ins["SeqLen"][0] if ins.get("SeqLen") and ins["SeqLen"][0] is not None else None

    xs = jnp.swapaxes(x, 0, 1)
    xproj = jnp.einsum("tbd,gd->tbg", xs, wih)
    if bias is not None:
        xproj = xproj + bias
    mask = _seq_mask(lens, B, T)
    w_u, w_r, w_c = jnp.split(whh, 3, axis=0)  # each [H, H]

    def step(h, inp):
        xp, m = inp
        xu, xr, xc = jnp.split(xp, 3, axis=-1)
        u = jax.nn.sigmoid(xu + h @ w_u.T)
        r = jax.nn.sigmoid(xr + h @ w_r.T)
        cand = jnp.tanh(xc + (r * h) @ w_c.T)
        h_new = u * h + (1 - u) * cand
        h_out = m * h_new + (1 - m) * h
        return h_out, h_out

    h_last, hs = lax.scan(step, h0, (xproj, mask))
    return {"Out": [jnp.swapaxes(hs, 0, 1)], "LastH": [h_last]}
