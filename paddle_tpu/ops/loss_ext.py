"""Sampled/structured losses and sequence-eval metrics.

Reference files (paddle/fluid/operators/): nce_op.cc,
hierarchical_sigmoid_op.cc, sample_logits_op.cc,
teacher_student_sigmoid_loss_op.h, center_loss_op.cc, warpctc_op.cc,
ctc_align_op.cc, edit_distance_op.cc, chunk_eval_op.cc,
cross_entropy_op.cc (cross_entropy2), metrics/precision_recall_op.cc,
positive_negative_pair_op.cc, detection/detection_map_op.cc.

TPU-native formulations: class sampling uses the counter-based ctx RNG;
CTC is a log-space alpha recursion under lax.scan (replacing the warpctc
CUDA library); edit distance is a static Levenshtein DP scanned row-wise;
dynamic-size outputs (ctc_align) left-pack into the input-length frame
with -1 padding.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


from ._helpers import op_key as _key
from ._helpers import stable_sigmoid_ce


def _log_uniform_probs(n):
    # P(c) = (log(c+2) - log(c+1)) / log(n+1)  (sample_logits_op.cc sampler)
    c = np.arange(n, dtype=np.float64)
    return jnp.asarray(
        (np.log(c + 2) - np.log(c + 1)) / np.log(n + 1.0), jnp.float32
    )


# ---------------------------------------------------------------------------
# NCE (nce_op.cc): binary logistic loss on true vs k sampled noise classes
# with the noise-corrected logit  s(x,c) - log(k * P_noise(c)).
# ---------------------------------------------------------------------------


@register_op(
    "nce",
    inputs=["Input", "Label", "Weight", "Bias", "SampleWeight"],
    outputs=["Cost", "SampleLogits", "SampleLabels"],
)
def _nce(ctx, op, ins):
    x = ins["Input"][0]  # [B, D]
    label = ins["Label"][0].astype(jnp.int32)  # [B, num_true]
    w = ins["Weight"][0]  # [C, D]
    bias = ins.get("Bias", [None])[0]
    num_classes = op.attr("num_total_classes", w.shape[0])
    k = op.attr("num_neg_samples", 10)
    sampler = op.attr("sampler", 0)  # 0 uniform, 1 log_uniform
    B = x.shape[0]
    num_true = label.shape[1] if label.ndim > 1 else 1
    label = label.reshape(B, num_true)

    if sampler == 1:
        probs = _log_uniform_probs(num_classes)
        neg = jax.random.categorical(
            _key(ctx, op), jnp.log(probs)[None, :], shape=(B, k)
        )
    else:
        probs = jnp.full((num_classes,), 1.0 / num_classes)
        neg = jax.random.randint(_key(ctx, op), (B, k), 0, num_classes)

    classes = jnp.concatenate([label, neg], axis=1)  # [B, num_true + k]
    logits = jnp.einsum("bd,bcd->bc", x, w[classes])
    if bias is not None:
        logits = logits + bias[classes]
    corrected = logits - jnp.log(k * probs[classes] + 1e-20)
    labels01 = jnp.concatenate(
        [jnp.ones((B, num_true)), jnp.zeros((B, k))], axis=1
    )
    ce = stable_sigmoid_ce(corrected, labels01)
    cost = ce[:, :num_true].mean(axis=1, keepdims=True) + ce[:, num_true:].sum(
        axis=1, keepdims=True
    ) / num_true
    return {
        "Cost": [cost],
        "SampleLogits": [logits],
        "SampleLabels": [classes],
    }


# ---------------------------------------------------------------------------
# hierarchical sigmoid (hierarchical_sigmoid_op.cc default tree: the
# word2vec complete-binary-heap code — node for class c at depth i is
# (c + num_classes) >> (i+1), code bit ((c + num_classes) >> i) & 1).
# ---------------------------------------------------------------------------


@register_op(
    "hierarchical_sigmoid",
    inputs=["X", "Label", "W", "Bias", "PathTable", "PathCode"],
    outputs=["Out", "PreOut"],
)
def _hierarchical_sigmoid(ctx, op, ins):
    x = ins["X"][0]  # [B, D]
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)  # [B]
    w = ins["W"][0]  # [num_classes-1, D]
    bias = ins.get("Bias", [None])[0]
    path_table = ins.get("PathTable", [None])[0]
    path_code = ins.get("PathCode", [None])[0]
    num_classes = op.attr("num_classes")

    if path_table is not None and path_code is not None:
        nodes = path_table.astype(jnp.int32)  # [B, L], -1 padded
        codes = path_code.astype(jnp.float32)
        valid = (nodes >= 0).astype(x.dtype)
        nodes = jnp.maximum(nodes, 0)
    else:
        depth = max(int(math.ceil(math.log2(num_classes))), 1)
        heap = label + num_classes  # 1-based heap position of the leaf
        levels = jnp.arange(1, depth + 1)
        anc = heap[:, None] >> levels[None, :]  # ancestors bottom-up
        codes = ((heap[:, None] >> (levels - 1)[None, :]) & 1).astype(
            jnp.float32
        )
        valid = (anc >= 1).astype(x.dtype)
        nodes = jnp.maximum(anc - 1, 0)  # heap pos -> row in W

    pre = jnp.einsum("bd,bld->bl", x, w[nodes])
    if bias is not None:
        pre = pre + bias.reshape(-1)[nodes]
    # binary CE per node: code bit is the target
    ce = stable_sigmoid_ce(pre, codes)
    out = jnp.sum(ce * valid, axis=1, keepdims=True)
    return {"Out": [out], "PreOut": [pre]}


# ---------------------------------------------------------------------------
# sampled softmax helper (sample_logits_op.cc)
# ---------------------------------------------------------------------------


@register_op(
    "sample_logits",
    inputs=["Logits", "Labels"],
    outputs=["Samples", "Probabilities", "SampledLogits", "SampledLabel"],
)
def _sample_logits(ctx, op, ins):
    logits = ins["Logits"][0]  # [B, C]
    labels = ins["Labels"][0].astype(jnp.int32)  # [B, num_true]
    B, C = logits.shape
    num_true = labels.shape[1]
    S = op.attr("num_samples", 10)
    probs = _log_uniform_probs(C)
    if op.attr("uniq", True):
        # one shared sample set per batch (the reference samples per-row but
        # dedups; a shared set is the standard TPU-friendly variant)
        neg = jax.random.categorical(
            _key(ctx, op), jnp.log(probs)[None, :], shape=(1, S)
        )
        neg = jnp.broadcast_to(neg, (B, S))
    else:
        neg = jax.random.categorical(
            _key(ctx, op), jnp.log(probs)[None, :], shape=(B, S)
        )
    samples = jnp.concatenate([labels, neg], axis=1)  # [B, num_true + S]
    q = probs[samples]
    sampled = jnp.take_along_axis(logits, samples, axis=1)
    if op.attr("remove_accidental_hits", True):
        hit = (neg[:, None, :] == labels[:, :, None]).any(axis=1)  # [B, S]
        pad = jnp.zeros((B, num_true), bool)
        sampled = sampled - jnp.concatenate([pad, hit], axis=1) * 1e20
    sampled = sampled - jnp.log(q + 1e-20)
    new_label = jnp.broadcast_to(
        jnp.arange(num_true, dtype=jnp.int32)[None, :], (B, num_true)
    )
    return {
        "Samples": [samples],
        "Probabilities": [q],
        "SampledLogits": [sampled],
        "SampledLabel": [new_label],
    }


@register_op(
    "teacher_student_sigmoid_loss", inputs=["X", "Label"], outputs=["Y"]
)
def _teacher_student_sigmoid_loss(ctx, op, ins):
    """Exact piecewise form of teacher_student_sigmoid_loss_op.h:57-94:
    label<-1: CE(x,0); -1<=label<0: CE(x,1); 0<=label<1: CE(x,0)+CE(x,label);
    label>=1: CE(x,1)+CE(x,label-1), with CE the stable sigmoid CE."""
    x = ins["X"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)

    def ce(z):
        return stable_sigmoid_ce(x, z)

    y = jnp.where(
        label < -1.0,
        ce(0.0),
        jnp.where(
            label < 0.0,
            ce(1.0),
            jnp.where(
                label < 1.0,
                ce(0.0) + ce(label),
                ce(1.0) + ce(label - 1.0),
            ),
        ),
    )
    return {"Y": [y.reshape(-1, 1)]}


@register_op(
    "center_loss",
    inputs=["X", "Label", "Centers", "CenterUpdateRate"],
    outputs=["Loss", "SampleCenterDiff", "CentersOut"],
    mutates=(("CentersOut", "Centers"),),
)
def _center_loss(ctx, op, ins):
    x = ins["X"][0]  # [B, D]
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)
    centers = ins["Centers"][0]  # [C, D]
    alpha = ins["CenterUpdateRate"][0].reshape(())
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if op.attr("need_update", True):
        # center update: c_j += alpha * sum_{i: y_i=j} diff_i / (1 + n_j)
        upd = jnp.zeros_like(centers).at[label].add(diff)
        cnt = jnp.zeros((centers.shape[0],)).at[label].add(1.0)
        centers_out = centers + alpha * upd / (1.0 + cnt)[:, None]
    else:
        centers_out = centers
    return {
        "Loss": [loss],
        "SampleCenterDiff": [diff],
        "CentersOut": [centers_out],
    }


# ---------------------------------------------------------------------------
# CTC family. warpctc_op.cc wraps the warp-ctc CUDA library; here the
# standard log-space alpha recursion runs under lax.scan over time with the
# padded-label frame [B, L] — fully differentiable through the scan, so the
# gradient the reference gets from warpctc's backward comes from the
# generic vjp.
# ---------------------------------------------------------------------------


@register_op(
    "warpctc",
    inputs=["Logits", "Label", "LogitsLength", "LabelLength"],
    outputs=["Loss", "WarpCTCGrad"],
)
def _warpctc(ctx, op, ins):
    logits = ins["Logits"][0]  # [B, T, C] padded dense (TPU contract)
    label = ins["Label"][0].astype(jnp.int32)  # [B, L]
    B, T, C = logits.shape
    L = label.shape[1]
    logit_len = ins.get("LogitsLength", [None])[0]
    label_len = ins.get("LabelLength", [None])[0]
    logit_len = (
        jnp.full((B,), T, jnp.int32)
        if logit_len is None
        else logit_len.reshape(-1).astype(jnp.int32)
    )
    label_len = (
        jnp.full((B,), L, jnp.int32)
        if label_len is None
        else label_len.reshape(-1).astype(jnp.int32)
    )
    blank = op.attr("blank", 0)
    norm_by_times = op.attr("norm_by_times", False)

    logp = jax.nn.log_softmax(logits, axis=-1)
    # extended label sequence: blank l1 blank l2 ... lL blank (S = 2L+1)
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    # transition allowed from s-2: ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    skip_ok = (ext != blank) & (ext != ext_m2)

    neg_inf = -1e30
    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_len > 0, logp[jnp.arange(B), 0, ext[:, 1]], neg_inf)
    )

    def lse(a, b):
        m = jnp.maximum(a, b)
        return m + jnp.log(
            jnp.exp(a - m) + jnp.exp(b - m)
        )

    def step(alpha, t):
        a_shift1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=neg_inf)[
            :, :S
        ]
        a_shift2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=neg_inf)[
            :, :S
        ]
        a = lse(alpha, a_shift1)
        a = jnp.where(skip_ok, lse(a, a_shift2), a)
        emit = jnp.take_along_axis(logp[:, t], ext, axis=1)
        new = a + emit
        # frozen past the per-sample logit length
        new = jnp.where((t < logit_len)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    last = 2 * label_len  # index of final blank
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1
    )[:, 0]
    ll = lse(a_last, jnp.where(label_len > 0, a_prev, neg_inf))
    loss = -ll
    if norm_by_times:
        loss = loss / logit_len.astype(loss.dtype)
    return {"Loss": [loss.reshape(B, 1)], "WarpCTCGrad": []}


@register_op(
    "ctc_align", inputs=["Input", "InputLength"], outputs=["Output", "OutputLength"],
    differentiable=False,
)
def _ctc_align(ctx, op, ins):
    """ctc_align_op.cc: merge repeats then drop blanks. Static-shape form:
    left-packed into the input frame, -1 padded, plus lengths."""
    x = ins["Input"][0].astype(jnp.int32)  # [B, T]
    blank = op.attr("blank", 0)
    merge = op.attr("merge_repeated", True)
    B, T = x.shape
    prev = jnp.pad(x, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    keep = x != blank
    if merge:
        keep = keep & (x != prev)
    pos = jnp.cumsum(keep, axis=1) - 1  # target slot per kept element
    out = jnp.full((B, T), -1, jnp.int32)
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    out = out.at[b_idx, jnp.where(keep, pos, T - 1)].set(
        jnp.where(keep, x, -1), mode="drop"
    )
    # rows where nothing was kept must stay -1; scatter of -1 handles it
    out_len = keep.sum(axis=1).astype(jnp.int64)
    return {"Output": [out], "OutputLength": [out_len.reshape(B, 1)]}


@register_op(
    "edit_distance",
    inputs=["Hyps", "Refs", "HypsLength", "RefsLength"],
    outputs=["Out", "SequenceNum"],
    differentiable=False,
)
def _edit_distance(ctx, op, ins):
    """edit_distance_op.cc: Levenshtein DP. The [L1+1, L2+1] table rolls
    over a lax.scan across hypothesis positions; per-sample lengths mask
    the padded tail."""
    hyp = ins["Hyps"][0].astype(jnp.int32)  # [B, L1]
    ref = ins["Refs"][0].astype(jnp.int32)  # [B, L2]
    B, L1 = hyp.shape
    L2 = ref.shape[1]
    hyp_len = ins.get("HypsLength", [None])[0]
    ref_len = ins.get("RefsLength", [None])[0]
    hyp_len = (
        jnp.full((B,), L1, jnp.int32)
        if hyp_len is None
        else hyp_len.reshape(-1).astype(jnp.int32)
    )
    ref_len = (
        jnp.full((B,), L2, jnp.int32)
        if ref_len is None
        else ref_len.reshape(-1).astype(jnp.int32)
    )

    js = jnp.arange(L2 + 1, dtype=jnp.float32)
    row0 = jnp.broadcast_to(js, (B, L2 + 1))  # dp[0, j] = j

    def step(row, i):
        # row = dp[i-1, :]; compute dp[i, :]
        sub_cost = (hyp[:, i - 1, None] != ref).astype(jnp.float32)
        diag = row[:, :-1] + sub_cost  # substitution
        up = row[:, 1:] + 1.0  # deletion from hyp

        def inner(carry, j):
            left = carry  # dp[i, j-1]
            val = jnp.minimum(jnp.minimum(diag[:, j], up[:, j]), left + 1.0)
            return val, val

        first = jnp.full((B,), i, jnp.float32)  # dp[i, 0] = i
        _, rest = lax.scan(inner, first, jnp.arange(L2))
        new_row = jnp.concatenate(
            [first[:, None], jnp.swapaxes(rest, 0, 1)], axis=1
        )
        # freeze rows past each sample's hyp length
        return jnp.where((i <= hyp_len)[:, None], new_row, row), None

    row, _ = lax.scan(step, row0, jnp.arange(1, L1 + 1))
    dist = jnp.take_along_axis(row, ref_len[:, None], axis=1)[:, 0]
    # empty-ref convention (edit_distance_op.h): distance = hyp_len
    dist = jnp.where(ref_len == 0, hyp_len.astype(dist.dtype), dist)
    if op.attr("normalized", True):
        dist = dist / jnp.maximum(ref_len.astype(dist.dtype), 1.0)
    return {
        "Out": [dist.reshape(B, 1)],
        "SequenceNum": [jnp.asarray(B, jnp.int64)],
    }


# ---------------------------------------------------------------------------
# chunk_eval (chunk_eval_op.cc): chunk-level precision/recall/F1 for
# sequence labeling. Supports the IOB/IOE/IOBES/plain schemes via the same
# (type, tag) encoding the reference uses: tag = label % num_tag_types,
# type = label / num_tag_types.
# ---------------------------------------------------------------------------


def _chunk_bounds(labels, lengths, scheme, num_types):
    """Returns (is_begin, is_end, chunk_type, inside) maps [B, T]. Labels
    >= num_types * num_tag_types are "outside" (the O tag in IOB — the
    reference's other_chunk_type, chunk_eval_op.h GetSegments skips them);
    outside positions belong to no chunk."""
    B, T = labels.shape
    tag_types = {"iob": 2, "ioe": 2, "iobes": 4, "plain": 1}[scheme]
    tag = labels % tag_types
    typ = labels // tag_types
    valid = jnp.arange(T)[None, :] < lengths[:, None]
    inside = valid & (labels < num_types * tag_types) & (labels >= 0)
    # outside/invalid positions get type -1 so any neighbor comparison
    # against them reads as a type change (chunk boundary)
    typ = jnp.where(inside, typ, -1)
    prev_tag = jnp.pad(tag, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    prev_typ = jnp.pad(typ, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    next_tag = jnp.pad(tag, ((0, 0), (0, 1)), constant_values=-1)[:, 1:]
    next_typ = jnp.pad(typ, ((0, 0), (0, 1)), constant_values=-1)[:, 1:]
    if scheme == "iob":  # tag 0 = B, 1 = I
        begin = (tag == 0) | ((tag == 1) & (prev_typ != typ))
        end = (next_typ != typ) | (next_tag == 0)
    elif scheme == "ioe":  # tag 0 = I, 1 = E
        end = (tag == 1) | (next_typ != typ)
        begin = (prev_typ != typ) | (prev_tag == 1)
    elif scheme == "iobes":  # 0=B 1=I 2=E 3=S
        begin = (tag == 0) | (tag == 3)
        end = (tag == 2) | (tag == 3)
    else:  # plain: every maximal same-type run is a chunk
        begin = prev_typ != typ
        end = next_typ != typ
    return begin & inside, end & inside, typ, inside


@register_op(
    "chunk_eval",
    inputs=["Inference", "Label", "SeqLength"],
    outputs=[
        "Precision", "Recall", "F1-Score",
        "NumInferChunks", "NumLabelChunks", "NumCorrectChunks",
    ],
    differentiable=False,
)
def _chunk_eval(ctx, op, ins):
    inf = ins["Inference"][0].astype(jnp.int32)
    lab = ins["Label"][0].astype(jnp.int32)
    if inf.ndim > 2:
        inf = inf.reshape(inf.shape[0], -1)
        lab = lab.reshape(lab.shape[0], -1)
    B, T = inf.shape
    lens = ins.get("SeqLength", [None])[0]
    lens = (
        jnp.full((B,), T, jnp.int32)
        if lens is None
        else lens.reshape(-1).astype(jnp.int32)
    )
    scheme = op.attr("chunk_scheme", "IOB").lower()
    num_types = op.attr("num_chunk_types", 1)
    excluded = op.attr("excluded_chunk_types", []) or []

    ib, ie, ityp, _ = _chunk_bounds(inf, lens, scheme, num_types)
    lb, le, ltyp, _ = _chunk_bounds(lab, lens, scheme, num_types)

    def count(beg, end, typ):
        ok = beg
        for ex in excluded:
            ok = ok & (typ != ex)
        return ok.sum()

    # a chunk matches when begin, end and type all coincide; compare via
    # begin-aligned segment ids: same begin position + same end position.
    # end position of the chunk starting at t: the first end >= t. Compute
    # via segment scan: chunk id = cumsum(begin); chunks match if for the
    # same begin position both sequences end at the same place with same
    # type.
    def chunk_sig(beg, end, typ):
        # for every position where beg: find its end index
        idx = jnp.arange(T)[None, :]
        # next end at or after t: min over j>=t of j where end[j]
        endpos = jnp.where(end, idx, T + 1)
        # suffix min
        endpos = lax.cummin(endpos[:, ::-1], axis=1)[:, ::-1]
        return jnp.where(beg, endpos, -1), jnp.where(beg, typ, -1)

    iend, ityp_s = chunk_sig(ib, ie, ityp)
    lend, ltyp_s = chunk_sig(lb, le, ltyp)
    correct = (
        (iend >= 0) & (iend == lend) & (ityp_s == ltyp_s)
    )
    for ex in excluded:
        correct = correct & (ityp_s != ex)
    n_inf = count(ib, ie, ityp).astype(jnp.int64)
    n_lab = count(lb, le, ltyp).astype(jnp.int64)
    n_cor = correct.sum().astype(jnp.int64)
    p = jnp.where(n_inf > 0, n_cor / jnp.maximum(n_inf, 1), 0.0).astype(
        jnp.float32
    )
    r = jnp.where(n_lab > 0, n_cor / jnp.maximum(n_lab, 1), 0.0).astype(
        jnp.float32
    )
    f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    return {
        "Precision": [p],
        "Recall": [r],
        "F1-Score": [f1],
        "NumInferChunks": [n_inf],
        "NumLabelChunks": [n_lab],
        "NumCorrectChunks": [n_cor],
    }


@register_op("cross_entropy2", inputs=["X", "Label"], outputs=["Y", "XShape", "MatchX"])
def _cross_entropy2(ctx, op, ins):
    """cross_entropy_op.cc v2 kernel: hard-label -log(x[label]) without
    soft-label support; MatchX saves the matched prob for the grad."""
    x = ins["X"][0]
    label = ins["Label"][0].astype(jnp.int32)
    ignore_index = op.attr("ignore_index", -100)
    lab = label.reshape(*x.shape[:-1])
    match = jnp.take_along_axis(
        x, jnp.maximum(lab, 0)[..., None], axis=-1
    )[..., 0]
    y = -jnp.log(jnp.maximum(match, 1e-20))
    y = jnp.where(lab == ignore_index, 0.0, y)
    return {"Y": [y[..., None]], "XShape": [], "MatchX": [match[..., None]]}


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


@register_op(
    "precision_recall",
    inputs=["MaxProbs", "Indices", "Labels", "Weights", "StatesInfo"],
    outputs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
    differentiable=False,
)
def _precision_recall(ctx, op, ins):
    """metrics/precision_recall_op.cc: per-class TP/FP/TN/FN accumulation +
    macro/micro-averaged P/R/F1 (6 metrics)."""
    idx = ins["Indices"][0].astype(jnp.int32).reshape(-1)  # predicted class
    labels = ins["Labels"][0].astype(jnp.int32).reshape(-1)
    weights = ins.get("Weights", [None])[0]
    states = ins.get("StatesInfo", [None])[0]
    C = op.attr("class_number")
    w = (
        jnp.ones_like(idx, jnp.float32)
        if weights is None
        else weights.reshape(-1).astype(jnp.float32)
    )
    correct = idx == labels
    tp = jnp.zeros((C,)).at[labels].add(w * correct)
    fn = jnp.zeros((C,)).at[labels].add(w * (~correct))
    fp = jnp.zeros((C,)).at[idx].add(w * (~correct))
    total = w.sum()
    tn_all = total - tp - fp - fn  # per class
    batch_states = jnp.stack([tp, fp, tn_all, fn], axis=1)  # [C, 4]
    accum_states = (
        batch_states if states is None else batch_states + states
    )

    def metrics(st):
        tp_, fp_, tn_, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12), 0.0)
        f1 = jnp.where(
            prec + rec > 0, 2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0
        )
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        tps, fps, fns = tp_.sum(), fp_.sum(), fn_.sum()
        mp = jnp.where(tps + fps > 0, tps / jnp.maximum(tps + fps, 1e-12), 0.0)
        mr = jnp.where(tps + fns > 0, tps / jnp.maximum(tps + fns, 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / jnp.maximum(mp + mr, 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    return {
        "BatchMetrics": [metrics(batch_states)],
        "AccumMetrics": [metrics(accum_states)],
        "AccumStatesInfo": [accum_states],
    }


@register_op(
    "positive_negative_pair",
    inputs=["Score", "Label", "QueryID", "Weight",
            "AccumulatePositivePair", "AccumulateNegativePair",
            "AccumulateNeutralPair"],
    outputs=["PositivePair", "NegativePair", "NeutralPair"],
    differentiable=False,
)
def _positive_negative_pair(ctx, op, ins):
    """positive_negative_pair_op.cc (LTR PN-pair metric): over all
    same-query item pairs with different labels, count score-order
    agreement (positive), disagreement (negative), ties (neutral)."""
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    qid = ins["QueryID"][0].reshape(-1)
    weight = ins.get("Weight", [None])[0]
    w = (
        jnp.ones_like(score)
        if weight is None
        else weight.reshape(-1).astype(score.dtype)
    )
    same_q = qid[:, None] == qid[None, :]
    diff_label = label[:, None] != label[None, :]
    upper = jnp.triu(jnp.ones_like(same_q, dtype=bool), k=1)
    pair_mask = same_q & diff_label & upper
    pw = 0.5 * (w[:, None] + w[None, :])
    s_diff = score[:, None] - score[None, :]
    l_diff = label[:, None] - label[None, :]
    agree = (s_diff * l_diff) > 0
    tie = s_diff == 0
    pos = jnp.sum(pair_mask * agree * ~tie * pw)
    neu = jnp.sum(pair_mask * tie * pw)
    neg = jnp.sum(pair_mask * ~agree * ~tie * pw)
    ap = ins.get("AccumulatePositivePair", [None])[0]
    an = ins.get("AccumulateNegativePair", [None])[0]
    au = ins.get("AccumulateNeutralPair", [None])[0]
    if ap is not None:
        pos = pos + ap.reshape(())
        neg = neg + an.reshape(())
        neu = neu + au.reshape(())
    one = lambda v: v.reshape(1)
    return {
        "PositivePair": [one(pos)],
        "NegativePair": [one(neg)],
        "NeutralPair": [one(neu)],
    }


@register_op(
    "detection_map",
    inputs=["DetectRes", "Label", "HasState", "PosCount", "TruePos", "FalsePos"],
    outputs=["MAP", "AccumPosCount", "AccumTruePos", "AccumFalsePos"],
    differentiable=False,
)
def _detection_map(ctx, op, ins):
    """detection/detection_map_op.cc re-derived for dense tensors:
    detections [N, 6] (label, score, x1, y1, x2, y2), gt [M, 5]
    (label, x1, y1, x2, y2) for ONE image batch (the reference walks LoD
    images; the dense form matches our multiclass_nms output). 11-point or
    integral AP averaged over classes present in gt.

    Cross-batch accumulation (the reference's PosCount/TruePos/FalsePos
    LoD state): static-shape form keeps per-class fixed-capacity
    (score, flag) buffers [C, K, 2] sorted by score desc, padded with
    score = -1; PosCount is [C, 1]. Feed Accum* outputs back as the next
    step's inputs — MAP is then the running dataset mAP. Per-class
    matching is one lax.scan vmapped over the class axis (not C unrolled
    scans)."""
    from .detection import _iou_matrix

    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    thresh = op.attr("overlap_threshold", 0.5)
    C = op.attr("class_num")
    ap_type = op.attr("ap_type", "11point")
    det_label = det[:, 0]
    det_score = det[:, 1]
    det_box = det[:, 2:6]
    gt_label = gt[:, 0]
    gt_box = gt[:, 1:5]
    valid_det = det_label >= 0
    valid_gt = gt_label >= 0
    iou = _iou_matrix(det_box, gt_box)  # [N, M]
    N = det.shape[0]
    order = jnp.argsort(-det_score)

    det_c = (det_label[None, :] == jnp.arange(C)[:, None]) & valid_det  # [C,N]
    gt_c = (gt_label[None, :] == jnp.arange(C)[:, None]) & valid_gt  # [C,M]
    n_gt_batch = gt_c.sum(axis=1)  # [C]

    def one_class(det_mask, gt_mask):
        def body(carry, i):
            used, tp = carry
            d = order[i]
            is_c = det_mask[d]
            ious = jnp.where(gt_mask & ~used, iou[d], -1.0)
            best = jnp.argmax(ious)
            hit = (ious[best] >= thresh) & is_c
            used = used.at[best].set(used[best] | hit)
            tp = tp.at[d].set(jnp.where(is_c, hit.astype(jnp.float32), 0.0))
            return (used, tp), None

        (used, tp), _ = lax.scan(
            body, (jnp.zeros_like(gt_mask), jnp.zeros((N,))), jnp.arange(N)
        )
        return tp  # [N] tp flag per detection slot (this class only)

    tp_flags = jax.vmap(one_class)(det_c, gt_c)  # [C, N]

    # per-class (score, flag) rows for this batch; non-class slots padded out
    batch_scores = jnp.where(det_c, det_score[None, :], -1.0)  # [C, N]

    prev_tp = ins.get("TruePos", [None])[0]
    prev_fp = ins.get("FalsePos", [None])[0]
    prev_pos = ins.get("PosCount", [None])[0]
    has_state = ins.get("HasState", [None])[0]

    if prev_tp is not None and prev_tp.ndim == 3:
        keep = (
            jnp.ones((), bool)
            if has_state is None
            else (has_state.reshape(()) != 0)
        )
        K = prev_tp.shape[1]
        prev_scores = jnp.where(keep, prev_tp[:, :, 0], -1.0)
        prev_flags = jnp.where(keep, prev_tp[:, :, 1], 0.0)
        # fp buffer rows mirror tp rows with flag 0 at the same scores —
        # one merged (score, tp-flag) list is sufficient for AP, so the
        # fp buffer contributes its scores with flag 0
        fp_scores = (
            jnp.where(keep, prev_fp[:, :, 0], -1.0)
            if prev_fp is not None and prev_fp.ndim == 3
            else jnp.full((C, 0), -1.0)
        )
        scores = jnp.concatenate(
            [batch_scores, prev_scores, fp_scores], axis=1
        )
        flags = jnp.concatenate(
            [tp_flags, prev_flags, jnp.zeros_like(fp_scores)], axis=1
        )
        n_gt = n_gt_batch.astype(jnp.float32) + jnp.where(
            keep, prev_pos.reshape(C).astype(jnp.float32), 0.0
        )
        out_k = K
    else:
        scores = batch_scores
        flags = tp_flags
        n_gt = n_gt_batch.astype(jnp.float32)
        out_k = N

    # sort each class's rows by score desc; padded rows (score -1) sink
    sort_idx = jnp.argsort(-scores, axis=1)
    scores = jnp.take_along_axis(scores, sort_idx, axis=1)
    flags = jnp.take_along_axis(flags, sort_idx, axis=1)

    live = scores >= 0
    tp_cum = jnp.cumsum(flags * live, axis=1)
    fp_cum = jnp.cumsum((1.0 - flags) * live, axis=1)
    rec = tp_cum / jnp.maximum(n_gt[:, None], 1.0)
    prec = tp_cum / jnp.maximum(tp_cum + fp_cum, 1e-12)

    if ap_type == "integral":
        d_rec = jnp.diff(
            jnp.concatenate([jnp.zeros((C, 1)), rec], axis=1), axis=1
        )
        aps = jnp.sum(jnp.where(live, prec * d_rec, 0.0), axis=1)
    else:  # 11point
        pts = jnp.linspace(0, 1, 11)
        p_at = jax.vmap(
            lambda r: jnp.max(
                jnp.where(live & (rec >= r), prec, 0.0), axis=1
            )
        )(pts)  # [11, C]
        aps = p_at.mean(axis=0)

    present = n_gt > 0
    mean_ap = jnp.sum(jnp.where(present, aps, 0.0)) / jnp.maximum(
        present.sum(), 1
    )

    # publish the merged state truncated to the carry capacity
    out_scores = scores[:, :out_k]
    out_flags = flags[:, :out_k]
    accum_tp = jnp.stack(
        [jnp.where(out_flags > 0, out_scores, -1.0),
         out_flags], axis=2
    )
    accum_fp = jnp.stack(
        [jnp.where((out_flags == 0) & (out_scores >= 0), out_scores, -1.0),
         jnp.zeros_like(out_flags)], axis=2
    )
    return {
        "MAP": [mean_ap.reshape(1)],
        "AccumPosCount": [n_gt.reshape(C, 1)],
        "AccumTruePos": [accum_tp],
        "AccumFalsePos": [accum_fp],
    }
