"""Linear-chain CRF ops (reference operators/linear_chain_crf_op.h and
crf_decoding_op.h — the sequence-labeling head of the
label_semantic_roles book workload).

TPU-native re-design: the reference works on LoD-packed sequences in
probability space with hand-written gradients (ExpSum/Alpha/Beta buffers);
here sequences are padded [B, T, D] + Length [B], the forward algorithm is
a `lax.scan` in LOG space (numerically stable, MXU-friendly
[B, D, D] broadcasts), and the gradient falls out of autodiff through the
scan — no Alpha/Beta plumbing at all.

Transition parameter layout matches fluid: [D+2, D], row 0 = start
weights, row 1 = end weights, rows 2.. = transition matrix w[i, j]
(score of moving FROM tag i TO tag j).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _unpack(transition):
    return transition[0], transition[1], transition[2:]


def _crf_logz_and_score(emission, transition, label, length):
    """(logZ [B], gold score [B]) for padded [B, T, D] emissions."""
    B, T, D = emission.shape
    start, end, w = _unpack(transition)
    em = emission.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    t_idx = jnp.arange(T)
    valid = t_idx[None, :] < length[:, None]  # [B, T]

    # ---- partition function: log-space forward algorithm ----
    alpha0 = start.astype(jnp.float32)[None, :] + em[:, 0]  # [B, D]

    def step(alpha, inputs):
        e_t, valid_t = inputs  # [B, D], [B]
        nxt = (
            jax.nn.logsumexp(alpha[:, :, None] + wf[None], axis=1) + e_t
        )
        alpha = jnp.where(valid_t[:, None], nxt, alpha)
        return alpha, None

    alpha, _ = lax.scan(
        step,
        alpha0,
        (em[:, 1:].swapaxes(0, 1), valid[:, 1:].swapaxes(0, 1)),
    )
    logz = jax.nn.logsumexp(alpha + end.astype(jnp.float32)[None], axis=1)

    # ---- gold path score ----
    lab = label.astype(jnp.int32)
    b_idx = jnp.arange(B)[:, None]
    em_score = jnp.sum(
        jnp.where(valid, em[b_idx, t_idx[None, :], lab], 0.0), axis=1
    )
    trans_score = jnp.sum(
        jnp.where(
            valid[:, 1:], wf[lab[:, :-1], lab[:, 1:]], 0.0
        ),
        axis=1,
    )
    last = jnp.clip(length - 1, 0, T - 1)
    score = (
        em_score
        + trans_score
        + start.astype(jnp.float32)[lab[:, 0]]
        + end.astype(jnp.float32)[lab[jnp.arange(B), last]]
    )
    return logz, score


@register_op(
    "linear_chain_crf",
    inputs=["Emission", "Transition", "Label", "Length"],
    outputs=["LogLikelihood"],
)
def _linear_chain_crf(ctx, op, ins):
    """Per-sequence NEGATIVE log likelihood [B, 1] (the reference's
    LogLikelihood output is the cost the book models feed to mean())."""
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    if label.ndim == 3 and label.shape[-1] == 1:
        label = label[..., 0]
    B, T, D = emission.shape
    length = (
        ins["Length"][0].astype(jnp.int32)
        if ins.get("Length") and ins["Length"][0] is not None
        else jnp.full((B,), T, jnp.int32)
    )
    logz, score = _crf_logz_and_score(emission, transition, label, length)
    return {"LogLikelihood": [(logz - score)[:, None]]}


@register_op(
    "crf_decoding",
    inputs=["Emission", "Transition", "Label", "Length"],
    outputs=["ViterbiPath"],
    differentiable=False,
)
def _crf_decoding(ctx, op, ins):
    """Viterbi decode [B, T] (padded positions 0). With Label given,
    returns the reference's correctness mask instead: 1 where the decoded
    tag equals the label (crf_decoding_op.h behavior)."""
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    B, T, D = emission.shape
    length = (
        ins["Length"][0].astype(jnp.int32)
        if ins.get("Length") and ins["Length"][0] is not None
        else jnp.full((B,), T, jnp.int32)
    )
    start, end, w = _unpack(transition)
    em = emission.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    t_idx = jnp.arange(T)
    valid = t_idx[None, :] < length[:, None]

    delta0 = start.astype(jnp.float32)[None, :] + em[:, 0]

    def step(delta, inputs):
        e_t, valid_t = inputs
        cand = delta[:, :, None] + wf[None]  # [B, D(from), D(to)]
        best = jnp.argmax(cand, axis=1).astype(jnp.int32)  # [B, D]
        nxt = jnp.max(cand, axis=1) + e_t
        delta_new = jnp.where(valid_t[:, None], nxt, delta)
        # padded steps record identity backpointers
        best = jnp.where(
            valid_t[:, None], best, jnp.arange(D, dtype=jnp.int32)[None]
        )
        return delta_new, best

    delta, back = lax.scan(
        step,
        delta0,
        (em[:, 1:].swapaxes(0, 1), valid[:, 1:].swapaxes(0, 1)),
    )  # back: [T-1, B, D]
    last_tag = jnp.argmax(
        delta + end.astype(jnp.float32)[None], axis=1
    ).astype(jnp.int32)

    def backtrack(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # tags_rev[t] is the tag at position t+1; the scan's final carry is
    # the tag at position 0
    first_tag, tags_rev = lax.scan(backtrack, last_tag, back, reverse=True)
    path = jnp.concatenate(
        [first_tag[None], tags_rev.astype(jnp.int32)], axis=0
    ).swapaxes(0, 1)  # [B, T]
    path = jnp.where(valid, path, 0).astype(jnp.int64)
    if ins.get("Label") and ins["Label"][0] is not None:
        label = ins["Label"][0]
        if label.ndim == 3 and label.shape[-1] == 1:
            label = label[..., 0]
        return {
            "ViterbiPath": [
                (jnp.where(valid, path == label.astype(jnp.int64), False)
                 ).astype(jnp.int64)
            ]
        }
    return {"ViterbiPath": [path]}
