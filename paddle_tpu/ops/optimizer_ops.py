"""Optimizer update ops.

Reference parity: operators/optimizers/ (sgd_op, momentum_op, adam_op,
lamb_op, lars_momentum_op, adagrad_op, rmsprop_op, adadelta_op, ftrl_op,
adamax_op, decayed_adagrad_op, dpsgd_op, ~5.5k LoC of CUDA kernels). Here each
is a few jnp lines; ParamOut/MomentOut reuse the *same variable names* as
their inputs, so the Executor's write-back + XLA buffer donation makes the
update in-place at the HBM level (the reference relied on Scope mutation).

All are differentiable=False: they sit after append_backward's grad ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _lr(ins):
    lr = ins["LearningRate"][0]
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register_op(
    "sgd",
    inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"],
    differentiable=False,
)
def _sgd(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    return {"ParamOut": [(p - _lr(ins) * g.astype(p.dtype)).astype(p.dtype)]}


@register_op(
    "momentum",
    inputs=["Param", "Grad", "Velocity", "LearningRate"],
    outputs=["ParamOut", "VelocityOut"],
    differentiable=False,
)
def _momentum(ctx, op, ins):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = op.attr("mu", 0.9)
    lr = _lr(ins)
    v_out = mu * v + g
    if op.attr("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out.astype(p.dtype)], "VelocityOut": [v_out]}


@register_op(
    "adam",
    inputs=[
        "Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    differentiable=False,
)
def _adam(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    beta1 = op.attr("beta1", 0.9)
    beta2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(ins)
    g = g.astype(m1.dtype)
    m1_out = beta1 * m1 + (1 - beta1) * g
    m2_out = beta2 * m2 + (1 - beta2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m1_out],
        "Moment2Out": [m2_out],
        "Beta1PowOut": [b1p * beta1],
        "Beta2PowOut": [b2p * beta2],
    }


@register_op(
    "adamw",
    inputs=[
        "Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    differentiable=False,
)
def _adamw(ctx, op, ins):
    wd = op.attr("weight_decay", 0.01)
    out = _adam(ctx, op, ins)
    p = ins["Param"][0]
    lr = _lr(ins)
    out["ParamOut"] = [(out["ParamOut"][0] - lr * wd * p).astype(p.dtype)]
    return out


@register_op(
    "lamb",
    inputs=[
        "Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    differentiable=False,
)
def _lamb(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    beta1 = op.attr("beta1", 0.9)
    beta2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    lr = _lr(ins)
    g = g.astype(m1.dtype)
    m1_out = beta1 * m1 + (1 - beta1) * g
    m2_out = beta2 * m2 + (1 - beta2) * g * g
    m1_hat = m1_out / (1 - b1p.reshape(()))
    m2_hat = m2_out / (1 - b2p.reshape(()))
    update = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(update.astype(jnp.float32))))
    ratio = jnp.where(
        (w_norm > 0) & (u_norm > 0), w_norm / u_norm, jnp.array(1.0, jnp.float32)
    )
    p_out = p - (lr * ratio) * update
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m1_out],
        "Moment2Out": [m2_out],
        "Beta1PowOut": [b1p * beta1],
        "Beta2PowOut": [b2p * beta2],
    }


@register_op(
    "lars_momentum",
    inputs=["Param", "Grad", "Velocity", "LearningRate"],
    outputs=["ParamOut", "VelocityOut"],
    differentiable=False,
)
def _lars_momentum(ctx, op, ins):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = op.attr("mu", 0.9)
    coeff = op.attr("lars_coeff", 0.001)
    wd = op.attr("lars_weight_decay", 0.0005)
    lr = _lr(ins)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm),
        lr,
    )
    v_out = mu * v + local_lr * (g + wd * p)
    return {"ParamOut": [(p - v_out).astype(p.dtype)], "VelocityOut": [v_out]}


@register_op(
    "adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    differentiable=False,
)
def _adagrad(ctx, op, ins):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = op.attr("epsilon", 1e-6)
    m_out = m + g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out]}


@register_op(
    "decayed_adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    differentiable=False,
)
def _decayed_adagrad(ctx, op, ins):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out]}


@register_op(
    "rmsprop",
    inputs=["Param", "Grad", "Moment", "MeanSquare", "MeanGrad", "LearningRate"],
    outputs=["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
    differentiable=False,
)
def _rmsprop(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    mom, ms = ins["Moment"][0], ins["MeanSquare"][0]
    mg = ins["MeanGrad"][0] if ins.get("MeanGrad") and ins["MeanGrad"][0] is not None else None
    rho = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    momentum = op.attr("momentum", 0.0)
    lr = _lr(ins)
    ms_out = rho * ms + (1 - rho) * g * g
    if op.attr("centered", False) and mg is not None:
        mg_out = rho * mg + (1 - rho) * g
        denom = jnp.sqrt(ms_out - mg_out * mg_out + eps)
    else:
        mg_out = mg if mg is not None else jnp.zeros_like(g)
        denom = jnp.sqrt(ms_out + eps)
    mom_out = momentum * mom + lr * g / denom
    return {
        "ParamOut": [(p - mom_out).astype(p.dtype)],
        "MomentOut": [mom_out],
        "MeanSquareOut": [ms_out],
        "MeanGradOut": [mg_out],
    }


@register_op(
    "adadelta",
    inputs=["Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"],
    outputs=["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
    differentiable=False,
)
def _adadelta(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    asg, asu = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    asg_out = rho * asg + (1 - rho) * g * g
    update = -jnp.sqrt((asu + eps) / (asg_out + eps)) * g
    asu_out = rho * asu + (1 - rho) * update * update
    return {
        "ParamOut": [(p + update).astype(p.dtype)],
        "AvgSquaredGradOut": [asg_out],
        "AvgSquaredUpdateOut": [asu_out],
    }


@register_op(
    "adamax",
    inputs=["Param", "Grad", "LearningRate", "Moment", "InfNorm", "Beta1Pow"],
    outputs=["ParamOut", "MomentOut", "InfNormOut", "Beta1PowOut"],
    differentiable=False,
)
def _adamax(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    beta1 = op.attr("beta1", 0.9)
    beta2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    m_out = beta1 * m + (1 - beta1) * g
    inf_out = jnp.maximum(beta2 * inf, jnp.abs(g))
    lr_t = _lr(ins) / (1 - b1p.reshape(()))
    p_out = p - lr_t * m_out / (inf_out + eps)
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "MomentOut": [m_out],
        "InfNormOut": [inf_out],
        # reference updates beta1_pow in a separate _finish_update scale op
        # (fluid/optimizer.py:2094); folding it in here keeps one op per param
        "Beta1PowOut": [b1p * beta1],
    }


@register_op(
    "ftrl",
    inputs=["Param", "Grad", "SquaredAccumulator", "LinearAccumulator", "LearningRate"],
    outputs=["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
    differentiable=False,
)
def _ftrl(ctx, op, ins):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = op.attr("l1", 0.0) + 1e-10
    l2 = op.attr("l2", 0.0) + 1e-10
    power = op.attr("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + g * g
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    lin_out = lin + g - sigma * p
    if power == -0.5:
        x = l2 + jnp.sqrt(new_sq) / lr
    else:
        x = l2 + jnp.power(new_sq, -power) / lr
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = pre / x
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "SquaredAccumOut": [new_sq],
        "LinearAccumOut": [lin_out],
    }


@register_op(
    "dpsgd",
    inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"],
    differentiable=False,
)
def _dpsgd(ctx, op, ins):
    # differentially-private SGD (dpsgd_op.cc): clip grad, add gaussian noise
    p, g = ins["Param"][0], ins["Grad"][0]
    clip = op.attr("clip", 10.0)
    sigma = op.attr("sigma", 1.0)
    batch_size = op.attr("batch_size", 16.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-10))
    noise = sigma * clip * jax.random.normal(ctx.key_for(op.uid, op.type), g.shape, g.dtype)
    update = (g + noise) / batch_size
    return {"ParamOut": [(p - _lr(ins) * update).astype(p.dtype)]}


@register_op(
    "proximal_gd",
    inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"],
    differentiable=False,
)
def _proximal_gd(ctx, op, ins):
    """optimizers/proximal_gd_op.cc: prox = p - lr*g, then soft-threshold
    by lr*l1 and shrink by 1/(1 + lr*l2)."""
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins)
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    prox = p - lr * g
    out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (
        1.0 + lr * l2
    )
    return {"ParamOut": [out.astype(p.dtype)]}


@register_op(
    "proximal_adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    differentiable=False,
)
def _proximal_adagrad(ctx, op, ins):
    """optimizers/proximal_adagrad_op.cc: adagrad-scaled lr feeding the
    proximal_gd update."""
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    m_out = m + g * g
    lr_eff = _lr(ins) / jnp.sqrt(m_out + 1e-10)
    prox = p - lr_eff * g
    out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_eff * l1, 0.0) / (
        1.0 + lr_eff * l2
    )
    return {"ParamOut": [out.astype(p.dtype)], "MomentOut": [m_out]}


@register_op(
    "average_accumulates",
    inputs=[
        "param", "in_sum_1", "in_sum_2", "in_sum_3",
        "in_num_accumulates", "in_old_num_accumulates", "in_num_updates",
    ],
    outputs=[
        "out_sum_1", "out_sum_2", "out_sum_3",
        "out_num_accumulates", "out_old_num_accumulates", "out_num_updates",
    ],
    differentiable=False,
    mutates=(
        ("out_sum_1", "in_sum_1"), ("out_sum_2", "in_sum_2"),
        ("out_sum_3", "in_sum_3"),
        ("out_num_accumulates", "in_num_accumulates"),
        ("out_old_num_accumulates", "in_old_num_accumulates"),
        ("out_num_updates", "in_num_updates"),
    ),
)
def _average_accumulates(ctx, op, ins):
    """average_accumulates_op.h (ModelAverage state machine): sum_1
    accumulates params; every 16384 updates sum_1 spills into sum_2
    (precision); when the window outgrows max(min_window,
    min(max_window, num_updates*ratio)) the current sums retire into
    sum_3. All three branches become jnp.where selects — identical state
    trajectory, no host control flow."""
    kmax = 16384
    p = ins["param"][0]
    s1, s2, s3 = ins["in_sum_1"][0], ins["in_sum_2"][0], ins["in_sum_3"][0]
    num_acc = ins["in_num_accumulates"][0].reshape(()).astype(jnp.int64)
    old_acc = ins["in_old_num_accumulates"][0].reshape(()).astype(jnp.int64)
    num_upd = ins["in_num_updates"][0].reshape(()).astype(jnp.int64)
    ratio = op.attr("average_window", 0.0)
    max_w = op.attr("max_average_window", kmax)
    min_w = op.attr("min_average_window", 10000)

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p
    spill = (num_upd % kmax) == 0
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.asarray(max_w, jnp.float64).astype(jnp.float32),
        num_upd.astype(jnp.float32) * ratio,
    )
    retire = (num_acc >= min_w) & (num_acc.astype(jnp.float32) >= window)
    s3 = jnp.where(retire, s1 + s2, s3)
    s1 = jnp.where(retire, jnp.zeros_like(s1), s1)
    s2 = jnp.where(retire, jnp.zeros_like(s2), s2)
    old_acc = jnp.where(retire, num_acc, old_acc)
    num_acc = jnp.where(retire, jnp.zeros_like(num_acc), num_acc)
    i64 = lambda v: v.reshape([1]).astype(jnp.int64)
    return {
        "out_sum_1": [s1],
        "out_sum_2": [s2],
        "out_sum_3": [s3],
        "out_num_accumulates": [i64(num_acc)],
        "out_old_num_accumulates": [i64(old_acc)],
        "out_num_updates": [i64(num_upd)],
    }


@register_op(
    "dgc_momentum_step",
    inputs=["Param", "Grad", "U", "V", "LearningRate", "CurrentStep"],
    outputs=["ParamOut", "UOut", "VOut", "SentRatio"],
    differentiable=False,
    mutates=(("ParamOut", "Param"), ("UOut", "U"), ("VOut", "V")),
)
def _dgc_momentum_step(ctx, op, ins):
    """Deep Gradient Compression (reference DGCMomentumOptimizer,
    optimizer.py:1071 + operators/dgc_op.cc; Lin et al. 2017): local
    momentum correction (u = m*u + g), error accumulation (v += u), top-k
    selection on |v|, momentum-factor masking, and a SPARSE exchange.

    TPU-native exchange: instead of NCCL sparse allreduce, each rank
    all_gathers its (values, indices) pair over the data axis — 2k*nranks
    words on the wire vs numel for dense — and scatter-adds every rank's
    contribution into the dense update. Before rampup_begin_step the dense
    psum path runs (the reference's warmup). Static shapes: k is fixed
    from the FINAL sparsity; the rampup sparsity schedule selects by
    masking within the top-k window."""
    p = ins["Param"][0]
    g = ins["Grad"][0]
    u = ins["U"][0]
    v = ins["V"][0]
    lr = _lr(ins)
    step_in = ins.get("CurrentStep", [None])[0]
    step = (
        step_in.reshape(()).astype(jnp.float32)
        if step_in is not None else jnp.asarray(1e9, jnp.float32)
    )
    m = op.attr("momentum", 0.9)
    sparsity = float(op.attr("sparsity", 0.999))
    rampup_begin = float(op.attr("rampup_begin_step", 0.0))
    axis = op.attr("axis_name", "dp")
    nranks = int(op.attr("nranks", 1))
    numel = int(p.size)
    k = max(1, int(round((1.0 - sparsity) * numel)))

    in_mesh = axis in ctx.mesh_axes
    if in_mesh and nranks > 1:
        mesh_n = ctx.axis_sizes.get(axis)
        if mesh_n is not None and mesh_n != nranks:
            raise ValueError(
                f"DGC num_trainers={nranks} but mesh axis {axis!r} has "
                f"{mesh_n} shards; the exchange uses the mesh size — fix "
                "num_trainers or the mesh"
            )

    # momentum correction + error accumulation on the local gradient
    u_new = m * u + g
    v_new = v + u_new

    def sparse_branch(operands):
        u_n, v_n = operands
        flat_v = v_n.reshape(-1)
        _, idx = jax.lax.top_k(jnp.abs(flat_v), k)
        send_vals = flat_v[idx]
        if in_mesh:
            all_vals = jax.lax.all_gather(send_vals, axis)  # [n, k]
            all_idx = jax.lax.all_gather(idx, axis)
            merged = (
                jnp.zeros((numel,), flat_v.dtype)
                .at[all_idx.reshape(-1)]
                .add(all_vals.reshape(-1))
            )
            denom = jnp.asarray(all_vals.shape[0], flat_v.dtype)
        else:
            # no exchange happened: local semantics, no division
            merged = jnp.zeros((numel,), flat_v.dtype).at[idx].add(send_vals)
            denom = jnp.asarray(1.0, flat_v.dtype)
        update = (merged / denom).reshape(p.shape)
        # momentum-factor masking: selected coordinates reset locally
        keep = jnp.ones((numel,), flat_v.dtype).at[idx].set(0.0)
        u_m = (u_n.reshape(-1) * keep).reshape(p.shape)
        v_m = (flat_v * keep).reshape(p.shape)
        ratio = jnp.full((1,), k / numel, jnp.float32)
        return update, u_m, v_m, ratio

    def dense_branch(operands):
        u_n, v_n = operands
        # warmup: dense mean-allreduce, no compression, plain accumulation
        update = jax.lax.pmean(u_n, axis) if in_mesh else u_n
        return (update, u_n, jnp.zeros_like(v_n),
                jnp.ones((1,), jnp.float32))

    if rampup_begin <= 0.0 and step_in is None:
        # static fast path: no warmup branch in the graph at all
        update, u_out, v_out, ratio = sparse_branch((u_new, v_new))
    else:
        # the step counter is replicated, so the predicate is uniform
        # across ranks and lax.cond executes ONE branch — a jnp.where
        # would run the dense pmean every step forever, costing the full
        # numel on the wire on top of the sparse exchange
        update, u_out, v_out, ratio = jax.lax.cond(
            step >= rampup_begin, sparse_branch, dense_branch,
            (u_new, v_new),
        )

    p_out = p - lr * update
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "UOut": [u_out],
        "VOut": [v_out],
        "SentRatio": [ratio],
    }
