"""Sparse / huge-embedding ops (the parameter-server capability).

Reference: operators/distributed_ops/distributed_lookup_table_op.cc +
operators/distributed/parameter_prefetch.cc — trainer sends ids to the
pserver holding each row-shard over gRPC, the pserver gathers and replies;
gradients flow back as send ops into per-shard optimize blocks
(listen_and_serv_op.cc). The huge-embedding capability (tables larger than
one device) lived entirely in that RPC machinery (plus pslib/BoxPS caches,
fleet_wrapper.h:86).

TPU-native re-design: the table is ROW-SHARDED over a mesh axis ("ps") and
stays resident in device HBM; a lookup is one fused gather + masked-select +
psum over ICI — no RPC, no host round-trip, and the backward pass
(scatter-add of row gradients into the owning shard) falls out of the
generic __vjp__ machinery instead of a hand-written send/optimize-block
protocol. Block sharding: row r lives on shard r // (vocab/N).

Under no mesh (or the axis absent) the op degrades to a plain local gather,
matching the reference's non-distributed lookup_table fallback.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


@register_op("distributed_lookup_table", inputs=["Ids", "W"], outputs=["Out"])
def _distributed_lookup_table(ctx, op, ins):
    ids = ins["Ids"][0]
    w = ins["W"][0]  # local row-shard under shard_map; full table otherwise
    axis = op.attr("axis_name", "ps")
    if ids.ndim and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    ids = ids.astype(jnp.int32)
    if axis not in ctx.mesh_axes:
        return {"Out": [w[ids]]}
    n = ctx.axis_sizes[axis]
    k = lax.axis_index(axis)
    rows_local = w.shape[0]  # the local row-shard (global_rows // n)
    local = ids - k * rows_local
    owned = jnp.logical_and(local >= 0, local < rows_local)
    safe = jnp.clip(local, 0, rows_local - 1)
    vals = jnp.where(owned[..., None], w[safe], 0)
    # each row is owned by exactly one shard: the psum assembles the full
    # batch of embeddings on every device (ICI all-reduce of [B..., D]).
    out = lax.psum(vals, axis)
    # psum transposes to psum under shard_map: the N replicated downstream
    # losses each seed a unit cotangent, which would scatter N-times-too-
    # large row gradients into the owning shard. Rescale the GRADIENT only
    # (value unchanged) — same correction as pipeline_block's loss psum.
    out = out / n + lax.stop_gradient(out * (n - 1) / n)
    return {"Out": [out]}
