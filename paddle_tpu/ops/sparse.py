"""Sparse / huge-embedding ops (the parameter-server capability).

Reference: operators/distributed_ops/distributed_lookup_table_op.cc +
operators/distributed/parameter_prefetch.cc — trainer sends ids to the
pserver holding each row-shard over gRPC, the pserver gathers and replies;
gradients flow back as send ops into per-shard optimize blocks
(listen_and_serv_op.cc). The huge-embedding capability (tables larger than
one device) lived entirely in that RPC machinery (plus pslib/BoxPS caches,
fleet_wrapper.h:86).

TPU-native re-design (PR 11 embedding engine):

* ``distributed_lookup_table`` — one table, row- or column-sharded over a
  mesh axis ("ps"). Row sharding: the table stays resident in device HBM as
  [V/n, D] shards; a lookup is batch-dedup (unique) -> fused gather ->
  masked-select -> psum over ICI -> scatter-back by the unique inverse. The
  backward (scatter-add of row gradients into the owning shard) falls out of
  the generic __vjp__ machinery — the vjp of gather-by-unique-inverse IS the
  segment-sum the reference hand-wrote in its send/optimize-block protocol.
  Column sharding ([V, D/n] shards): local gather of every row's column
  slice + an all-gather over the feature dim.

* ``fused_lookup_table`` — N same-width lookups coalesced into ONE op
  (``embedding.fuse_lookups`` builds these): the id tensors concatenate into
  a single key space (table t's id i -> table_offset_t + i), batch-unique
  ids dedup ONCE across every slot, one gather against the row-concatenated
  tables serves all N lookups, and the unique inverse scatters each slot's
  rows back. DeepFM's 26 sparse slots become one gather instead of 26+1
  dispatch sites. Backward: one segment-sum scatter per table (vjp of
  concat splits the row grads back to their tables).

* opt-in quantized gradient exchange (``quant="int8"``): the row-sharded
  backward's psum of [ids, D] row cotangents is replaced by the PR-9 EQuARX
  wire format (ops/collective.py ``_block_quantize``) — int8 blocks + fp32
  per-block abs-max scales, all_to_all reduce-scatter with fp32
  accumulation, int8 all-gather back. ``quant="none"`` (default) keeps the
  plain psum and is BITWISE identical to the pre-engine path.

Under no mesh (or the axis absent) every path degrades to a plain local
gather, matching the reference's non-distributed lookup_table fallback.
Batch dedup is on by default (``dedup`` attr): repeated ids in a batch
gather their row once, and the backward becomes a true segment-sum instead
of N colliding scatter-adds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _squeeze_ids(ids):
    """fluid id layout: a trailing [.., 1] dim is squeezed (ids [B, 1]
    gather to [B, D], not [B, 1, D])."""
    if ids.ndim and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    return ids.astype(jnp.int32)


def _quant_psum_rows(ax, n, qblock):
    """psum whose BACKWARD ships the cotangent in the PR-9 int8 block-quant
    wire format: quantize -> all_to_all reduce-scatter -> fp32 accumulate ->
    int8 all-gather (exactly zero_reduce_scatter + zero_all_gather's wire,
    applied to the embedding row-gradient exchange). The forward psum is
    exact: each unique row is owned by one shard, every other contribution
    is a true zero."""
    from .collective import _block_dequantize, _block_quantize

    def fwd(vals):
        return lax.psum(vals, ax), vals.shape

    def bwd(shape, g):
        flat = g.reshape(-1)
        numel = flat.shape[0]
        align = n * qblock
        pad = (numel + align - 1) // align * align
        if pad > numel:
            flat = jnp.pad(flat, (0, pad - numel))
        shards = flat.reshape(n, pad // n)
        q, scales = _block_quantize(shards, qblock)
        q = lax.all_to_all(q, ax, split_axis=0, concat_axis=0, tiled=False)
        scales = lax.all_to_all(
            scales, ax, split_axis=0, concat_axis=0, tiled=False
        )
        own = jnp.sum(_block_dequantize(q, scales, qblock), axis=0)
        q2, s2 = _block_quantize(own, qblock)
        q2 = lax.all_gather(q2, ax, tiled=True)
        s2 = lax.all_gather(s2, ax, tiled=True)
        full = _block_dequantize(q2, s2, qblock)[:numel]
        return (full.reshape(shape).astype(g.dtype),)

    @jax.custom_vjp
    def exchange(vals):
        return lax.psum(vals, ax)

    exchange.defvjp(fwd, bwd)
    return exchange


def _record_embed_wire(op, kind, payload_elems, dtype, ax, n):
    """Trace-time wire-byte accounting for the embedding exchanges, in the
    PR-9 counter shape (collective.bytes.<kind>_<precision>)."""
    if ax is None or n <= 1:
        return
    from .. import observability as _obs
    from ..resilience.faults import fault_point

    fault_point("collective.dispatch")
    quant = op.attr("quant", "none") or "none"
    block = int(op.attr("quant_block", 256) or 256)
    if quant != "none":
        per_elem = 1.0 + 4.0 / block
        precision = quant
    else:
        per_elem = float(jnp.dtype(dtype).itemsize)
        precision = "fp32" if jnp.dtype(dtype).itemsize == 4 else str(dtype)
    wire = int(payload_elems * per_elem * (n - 1) / n)
    _obs.add(f"collective.{kind}")
    _obs.add(f"collective.bytes.{kind}_{precision}", wire)


def _lookup_core(ctx, op, ids_list, tables):
    """Shared kernel of both lookup ops: dedup once over the concatenated
    id/key space, one gather against the row-concatenated tables, scatter
    back per slot. Returns one [ids_shape..., D] value per ids tensor."""
    from .. import observability as _obs

    axis = op.attr("axis_name", "ps")
    partition = op.attr("partition", "row")
    dedup = bool(op.attr("dedup", True))
    quant = op.attr("quant", "none") or "none"
    qblock = int(op.attr("quant_block", 256) or 256)
    sharded = axis in ctx.mesh_axes
    n = int(ctx.axis_sizes[axis]) if sharded else 1

    ids_list = [_squeeze_ids(i) for i in ids_list]
    # slot -> table segment: fused slots sharing one table share ONE
    # key-space segment (the W slot carries each table once), so the same
    # id in two slots dedups to one gathered row; default = one table per
    # slot (single-table op, or a hand-built fused op with distinct tables)
    slot_idx = op.attr("slot_table_idx")
    if slot_idx is None:
        if len(tables) == len(ids_list):
            slot_idx = list(range(len(ids_list)))
        elif len(tables) == 1:
            slot_idx = [0] * len(ids_list)
        else:
            from ..errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"{op.type}: {len(ids_list)} id slots over {len(tables)} "
                "tables needs a slot_table_idx attr"
            )
    # under a column shard_map the local table is [V, D/n]; the assembled
    # output rows are full-width D
    dim = int(tables[0].shape[-1])
    if sharded and partition == "col":
        dim *= n
    # per-table GLOBAL row counts: under a row shard_map each rank sees its
    # [V/n, D] slice; column sharding and the meshless path see all rows
    rows_local = [int(w.shape[0]) for w in tables]
    if sharded and partition == "row":
        rows_global = [r * n for r in rows_local]
    else:
        rows_global = list(rows_local)
    goff = [0]
    for r in rows_global[:-1]:
        goff.append(goff[-1] + r)
    loff = [0]
    for r in rows_local[:-1]:
        loff.append(loff[-1] + r)

    flat_ids, spans = [], []
    start = 0
    for i, ids in enumerate(ids_list):
        f = ids.reshape(-1) + goff[slot_idx[i]]
        flat_ids.append(f)
        spans.append((start, start + f.shape[0], ids.shape))
        start += f.shape[0]
    keys = jnp.concatenate(flat_ids) if len(flat_ids) > 1 else flat_ids[0]

    if dedup:
        u, inv = jnp.unique(
            keys, size=keys.shape[0], fill_value=0, return_inverse=True
        )
        inv = inv.reshape(-1)
    else:
        u, inv = keys, jnp.arange(keys.shape[0])

    combined = (
        jnp.concatenate(tables, axis=0) if len(tables) > 1 else tables[0]
    )

    if not sharded:
        # local tier: tables fully resident (or the engine's hot tier);
        # the global key space IS the concat row space (goff == loff)
        rows = combined[jnp.clip(u, 0, combined.shape[0] - 1)]
    elif partition == "col":
        # every rank holds all rows' [D/n] column slice: local gather of
        # the full key set, then one all-gather over the feature dim
        rows_part = combined[jnp.clip(u, 0, combined.shape[0] - 1)]
        _obs.add("collective.fused_lookup_allgather")
        rows = lax.all_gather(rows_part, axis, axis=rows_part.ndim - 1,
                              tiled=True)
        rows = rows / n + lax.stop_gradient(rows * (n - 1) / n)
    else:
        # row sharding: mask to the owned segment of each table, gather
        # from the local concat, and exchange (each row owned by exactly
        # one shard, so the sum is exact)
        k = lax.axis_index(axis)
        idx = jnp.zeros_like(u)
        owned = jnp.zeros(u.shape, bool)
        for t in range(len(tables)):
            in_seg = jnp.logical_and(
                u >= goff[t], u < goff[t] + rows_global[t]
            )
            local = u - goff[t] - k * rows_local[t]
            own_t = jnp.logical_and(
                in_seg,
                jnp.logical_and(local >= 0, local < rows_local[t]),
            )
            idx = jnp.where(own_t, loff[t] + local, idx)
            owned = jnp.logical_or(owned, own_t)
        vals = jnp.where(
            owned[..., None],
            combined[jnp.clip(idx, 0, combined.shape[0] - 1)],
            0,
        )
        _record_embed_wire(
            op, "embed_grad_exchange", vals.size, vals.dtype, axis, n
        )
        if quant == "int8":
            rows = _quant_psum_rows(axis, n, qblock)(vals)
        else:
            rows = lax.psum(vals, axis)
        # psum transposes to psum under shard_map: the N replicated
        # downstream losses each seed a unit cotangent, which would scatter
        # N-times-too-large row gradients into the owning shard. Rescale
        # the GRADIENT only (value unchanged) — same correction as
        # pipeline_block's loss psum.
        rows = rows / n + lax.stop_gradient(rows * (n - 1) / n)

    gathered = rows[inv]
    outs = []
    for s, e, shape in spans:
        outs.append(gathered[s:e].reshape(tuple(shape) + (dim,)))
    return outs


@register_op("distributed_lookup_table", inputs=["Ids", "W"], outputs=["Out"])
def _distributed_lookup_table(ctx, op, ins):
    (out,) = _lookup_core(ctx, op, [ins["Ids"][0]], [ins["W"][0]])
    return {"Out": [out]}


@register_op("fused_lookup_table", inputs=["Ids", "W"], outputs=["Out"])
def _fused_lookup_table(ctx, op, ins):
    from .. import observability as _obs

    tables = ins["W"]
    _obs.add("embedding.fused_lookup_sites")
    _obs.add("embedding.fused_lookup_tables", len(tables))
    outs = _lookup_core(ctx, op, ins["Ids"], tables)
    return {"Out": outs}
