"""Fused ops backed by hand-written Pallas kernels (paddle_tpu.kernels).

Reference parity: paddle/fluid/operators/fused/ — the reference fuses its
transformer hot path by hand in CUDA (multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm). The TPU analogue keeps most fusion in
XLA (whole-block jit), and drops to Pallas only where the fusion changes
HBM-traffic complexity: attention. See kernels/flash_attention.py.
"""

from __future__ import annotations

from ..framework.registry import register_op


@register_op(
    "fused_multihead_attention",
    inputs=["Q", "K", "V", "KeyBias"],
    outputs=["Out"],
)
def _fused_multihead_attention(ctx, op, ins):
    """softmax(QK^T * scale + KeyBias) V with fused attention-prob dropout.

    Q/K/V: [B, H, S, D]; KeyBias (optional): additive [B, S] fp32. On TPU
    this lowers to the Pallas flash kernel; elsewhere (CPU tests, or shapes
    the kernel does not support) it falls back to the jnp reference with
    identical semantics. Under gspmd-mode SPMD (mesh annotations without
    shard_map) the reference path is forced: GSPMD cannot partition a
    pallas_call, while inside shard_map the kernel sees local shards and is
    safe.
    """
    from ..kernels.flash_attention import fused_attention

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("KeyBias", [None])[0] if ins.get("KeyBias") else None
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    rate = float(op.attr("dropout_prob", 0.0))
    rng_key = None
    if rate > 0.0 and not is_test:
        rng_key = ctx.key_for(op.uid, op.type)
    gspmd_mode = (
        not ctx.mesh_axes
        and ctx.program is not None
        and getattr(ctx.program, "_mesh", None) is not None
    )
    out = fused_attention(
        q,
        k,
        v,
        key_bias=bias,
        scale=op.attr("scale", None),
        dropout_rate=rate,
        is_test=is_test,
        dropout_implementation=op.attr(
            "dropout_implementation", "downgrade_in_infer"
        ),
        causal=bool(op.attr("causal", False)),
        rng_key=rng_key,
        force_reference=gspmd_mode,
    )
    return {"Out": [out]}
