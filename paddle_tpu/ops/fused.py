"""Fused ops backed by hand-written Pallas kernels (paddle_tpu.kernels).

Reference parity: paddle/fluid/operators/fused/ — the reference fuses its
transformer hot path by hand in CUDA (multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm). The TPU analogue keeps most fusion in
XLA (whole-block jit), and drops to Pallas only where the fusion changes
HBM-traffic complexity: attention. See kernels/flash_attention.py.
"""

from __future__ import annotations

from ..framework.registry import register_op


def _make_attention_grad_maker(grad_op_type, primal_slots):
    """Grad makers that emit a dedicated grad op instead of the generic
    __vjp__ replay.

    The flash backward kernel needs only the primal inputs and d_out — no
    forward residuals — but the generic __vjp__ re-traces the forward
    emitter under jax.vjp, and XLA does NOT CSE the two identical Mosaic
    custom-calls, so every training step would run the forward attention
    kernel twice. A custom grad op calls the backward kernel directly.
    Gradient outputs are emitted only for inputs in `needs_grad` (the
    generic path's per-input filter, backward.py)."""

    def maker(op, block, contribs, finalize, needs_grad=None):
        from ..framework import unique_name
        from ..framework.backward import _ensure_var
        from ..framework.program import grad_var_name

        g_out = finalize(op.outputs["Out"][0])
        if g_out is None:
            return
        has_bias = bool(op.inputs.get("KeyBias"))
        inputs = {slot: op.inputs[slot] for slot in primal_slots}
        inputs["OutGrad"] = [g_out]
        if has_bias:
            inputs["KeyBias"] = op.inputs["KeyBias"]
        if op.outputs.get("Lse") and _qkv_tiled_at_build(op, block):
            # the forward saved (Out, Lse): hand both to the grad op so the
            # tiled backward skips its forward re-run (flash_tiled_outs)
            inputs["Out"] = op.outputs["Out"]
            inputs["Lse"] = op.outputs["Lse"]
        outs = {}
        for slot in primal_slots + (("KeyBias",) if has_bias else ()):
            n = op.inputs[slot][0]
            if needs_grad is not None and n not in needs_grad:
                continue
            gname = unique_name.generate(grad_var_name(n) + "@RENAME")
            _ensure_var(block, gname, n)
            outs[slot + "Grad"] = [gname]
            contribs.setdefault(n, []).append(gname)
        if not outs:
            return
        attrs = {
            k: v
            for k, v in op.attrs.items()
            if k not in ("__uid__", "__loc__")
        }
        # dropout masks must regenerate from the FORWARD op's RNG stream
        attrs["__fwd_uid__"] = op.uid
        block.append_op(grad_op_type, inputs, outs, attrs)

    return maker


def _qkv_tiled_at_build(op, block):
    """Build-time mirror of fused_attention_qkv's tiled-path dispatch (the
    AMP rewrite runs before backward, so the declared qkv dtype here is
    the runtime dtype; jax backend is the same process)."""
    from ..core.dtypes import to_numpy_dtype
    from ..kernels.flash_attention import uses_tiled_path

    v = block._find_var_recursive(op.inputs["QKV"][0])
    if v is None or not v.shape or len(v.shape) != 3:
        return False
    H = int(op.attr("num_heads"))
    S = int(v.shape[1])
    D = int(v.shape[2]) // 3 // H
    try:
        dt = to_numpy_dtype(v.dtype)
    except Exception:
        return False
    return uses_tiled_path(S, H, D, dt)


def _attn_ctx(ctx, op):
    """(is_test, dropout rate, gspmd-mode?) shared by the fused attention
    emitters. Under gspmd-mode SPMD (mesh annotations without shard_map)
    the jnp reference path is forced: GSPMD cannot partition a pallas_call,
    while inside shard_map the kernel sees local shards and is safe."""
    is_test = bool(op.attr("is_test", False)) or ctx.is_test
    rate = float(op.attr("dropout_prob", 0.0))
    gspmd_mode = (
        not ctx.mesh_axes
        and ctx.program is not None
        and getattr(ctx.program, "_mesh", None) is not None
    )
    return is_test, rate, gspmd_mode


@register_op(
    "fused_multihead_attention",
    inputs=["Q", "K", "V", "KeyBias"],
    outputs=["Out"],
    grad_maker=_make_attention_grad_maker(
        "fused_multihead_attention_grad", ("Q", "K", "V")
    ),
)
def _fused_multihead_attention(ctx, op, ins):
    """softmax(QK^T * scale + KeyBias) V with fused attention-prob dropout.

    Q/K/V: [B, H, S, D]; KeyBias (optional): additive [B, S] fp32. On TPU
    this lowers to the Pallas flash kernel; elsewhere (CPU tests, or shapes
    the kernel does not support) it falls back to the jnp reference with
    identical semantics (see _attn_ctx for the SPMD rule).
    """
    from ..kernels.flash_attention import fused_attention

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("KeyBias", [None])[0] if ins.get("KeyBias") else None
    is_test, rate, gspmd_mode = _attn_ctx(ctx, op)
    rng_key = None
    if rate > 0.0 and not is_test:
        rng_key = ctx.key_for(op.uid, op.type)
    out = fused_attention(
        q,
        k,
        v,
        key_bias=bias,
        scale=op.attr("scale", None),
        dropout_rate=rate,
        is_test=is_test,
        dropout_implementation=op.attr(
            "dropout_implementation", "downgrade_in_infer"
        ),
        causal=bool(op.attr("causal", False)),
        rng_key=rng_key,
        force_reference=gspmd_mode,
    )
    return {"Out": [out]}


@register_op(
    "fused_multihead_attention_grad",
    inputs=["Q", "K", "V", "KeyBias", "OutGrad"],
    outputs=["QGrad", "KGrad", "VGrad", "KeyBiasGrad"],
    differentiable=False,
)
def _fused_multihead_attention_grad(ctx, op, ins):
    """Backward of the fused attention op via the flash backward kernel —
    no forward replay (see _fused_mha_grad_maker). Dropout masks regenerate
    from the forward op's RNG stream (__fwd_uid__)."""
    from ..kernels.flash_attention import attention_grads

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("KeyBias", [None])[0] if ins.get("KeyBias") else None
    d_out = ins["OutGrad"][0]
    is_test, rate, gspmd_mode = _attn_ctx(ctx, op)
    rng_key = None
    if rate > 0.0 and not is_test:
        rng_key = ctx.key_for(
            int(op.attr("__fwd_uid__", 0)), "fused_multihead_attention"
        )
    dq, dk, dv, dbias = attention_grads(
        q, k, v, bias, d_out, rng_key,
        scale=op.attr("scale", None),
        dropout_rate=rate,
        is_test=is_test,
        dropout_implementation=op.attr(
            "dropout_implementation", "downgrade_in_infer"
        ),
        causal=bool(op.attr("causal", False)),
        force_reference=gspmd_mode,
    )
    outs = {}
    for slot, g in (("QGrad", dq), ("KGrad", dk), ("VGrad", dv)):
        if op.outputs.get(slot):
            outs[slot] = [g]
    if op.outputs.get("KeyBiasGrad"):
        outs["KeyBiasGrad"] = [dbias.astype(bias.dtype)]
    return outs


@register_op(
    "fused_qkv_attention",
    inputs=["QKV", "KeyBias"],
    outputs=["Out", "Lse"],
    grad_maker=_make_attention_grad_maker(
        "fused_qkv_attention_grad", ("QKV",)
    ),
)
def _fused_qkv_attention(ctx, op, ins):
    """Attention over the packed qkv projection [B, S, 3*H*D] -> [B, S,
    H*D] (attr num_heads). On TPU the Pallas kernel indexes the projection
    in place — no head-split transposes ever materialize (the 4-D op above
    costs 8 layout copies of [B,S,H] per layer per step). The TILED path
    (S beyond the whole-row cap) also emits the row logsumexp as `Lse` so
    the dedicated grad op runs the backward without re-running the
    forward kernel."""
    import jax.numpy as jnp

    from ..kernels.flash_attention import fused_attention_qkv

    qkv = ins["QKV"][0]
    bias = ins.get("KeyBias", [None])[0] if ins.get("KeyBias") else None
    is_test, rate, gspmd_mode = _attn_ctx(ctx, op)
    rng_key = None
    if rate > 0.0 and not is_test:
        rng_key = ctx.key_for(op.uid, op.type)
    out, lse = fused_attention_qkv(
        qkv,
        int(op.attr("num_heads")),
        key_bias=bias,
        scale=op.attr("scale", None),
        dropout_rate=rate,
        is_test=is_test,
        dropout_implementation=op.attr(
            "dropout_implementation", "downgrade_in_infer"
        ),
        causal=bool(op.attr("causal", False)),
        rng_key=rng_key,
        force_reference=gspmd_mode,
        return_lse=True,
    )
    if lse is None and getattr(op, "block", None) is not None \
            and _qkv_tiled_at_build(op, op.block):
        # the grad maker predicted the tiled path and wired Lse as a grad
        # input, but a runtime condition (e.g. gspmd fallback) routed
        # elsewhere: emit a placeholder so the graph stays runnable; the
        # grad emitter ignores it on non-tiled paths
        lse = jnp.zeros(out.shape, jnp.float32)
    if lse is None:
        return {"Out": [out], "Lse": []}
    return {"Out": [out], "Lse": [lse]}


@register_op(
    "fused_qkv_attention_grad",
    inputs=["QKV", "KeyBias", "OutGrad", "Out", "Lse"],
    outputs=["QKVGrad", "KeyBiasGrad"],
    differentiable=False,
)
def _fused_qkv_attention_grad(ctx, op, ins):
    from ..kernels.flash_attention import attention_grads_qkv

    qkv = ins["QKV"][0]
    bias = ins.get("KeyBias", [None])[0] if ins.get("KeyBias") else None
    d_out = ins["OutGrad"][0]
    is_test, rate, gspmd_mode = _attn_ctx(ctx, op)
    rng_key = None
    if rate > 0.0 and not is_test:
        rng_key = ctx.key_for(
            int(op.attr("__fwd_uid__", 0)), "fused_qkv_attention"
        )
    saved_out = ins["Out"][0] if ins.get("Out") else None
    saved_lse = ins["Lse"][0] if ins.get("Lse") else None
    dqkv, dbias = attention_grads_qkv(
        qkv, int(op.attr("num_heads")), bias, d_out, rng_key,
        scale=op.attr("scale", None),
        dropout_rate=rate,
        is_test=is_test,
        dropout_implementation=op.attr(
            "dropout_implementation", "downgrade_in_infer"
        ),
        causal=bool(op.attr("causal", False)),
        force_reference=gspmd_mode,
        saved_out=saved_out, saved_lse=saved_lse,
    )
    outs = {}
    if op.outputs.get("QKVGrad"):
        outs["QKVGrad"] = [dqkv]
    if op.outputs.get("KeyBiasGrad"):
        outs["KeyBiasGrad"] = [dbias.astype(bias.dtype)]
    return outs


# ---------------------------------------------------------------------------
# fused dropout + residual add + LayerNorm (kernels/fused_residual.py)
# ---------------------------------------------------------------------------


def _fdal_grad_maker(op, block, contribs, finalize, needs_grad=None):
    """Dedicated grad op (same rationale as the attention grad makers: the
    backward kernel needs no forward residuals, and a __vjp__ replay would
    run the forward Mosaic call a second time)."""
    from ..framework import unique_name
    from ..framework.backward import _ensure_var
    from ..framework.program import grad_var_name

    g_out = finalize(op.outputs["Out"][0])
    if g_out is None:
        return
    inputs = {"X": op.inputs["X"], "Y": op.inputs["Y"]}
    for slot in ("Scale", "LnBias"):
        if op.inputs.get(slot):
            inputs[slot] = op.inputs[slot]
    inputs["OutGrad"] = [g_out]
    outs = {}
    for slot in ("X", "Y", "Scale", "LnBias"):
        if not op.inputs.get(slot):
            continue
        n = op.inputs[slot][0]
        if needs_grad is not None and n not in needs_grad:
            continue
        gname = unique_name.generate(grad_var_name(n) + "@RENAME")
        _ensure_var(block, gname, n)
        outs[slot + "Grad"] = [gname]
        contribs.setdefault(n, []).append(gname)
    if not outs:
        return
    attrs = {
        k: v for k, v in op.attrs.items() if k not in ("__uid__", "__loc__")
    }
    attrs["__fwd_uid__"] = op.uid
    block.append_op("fused_dropout_add_ln_grad", inputs, outs, attrs)


def _fdal_statics(op, is_test):
    return dict(
        rate=float(op.attr("dropout_prob", 0.0)),
        is_test=bool(is_test),
        upscale=op.attr("dropout_implementation", "downgrade_in_infer")
        == "upscale_in_train",
        eps=float(op.attr("epsilon", 1e-5)),
    )


def _fdal_use_kernel(ctx, x2d, gspmd_mode):
    import jax

    from ..kernels import fused_residual as frk

    return (
        not gspmd_mode
        and jax.default_backend() == "tpu"
        and frk.supports(x2d.shape[0], x2d.shape[1], x2d.dtype)
    )


@register_op(
    "fused_dropout_add_ln",
    inputs=["X", "Y", "Scale", "LnBias"],
    outputs=["Out"],
    grad_maker=_fdal_grad_maker,
)
def _fused_dropout_add_ln(ctx, op, ins):
    """Out = LayerNorm(X + dropout(Y)) over the last axis — the transformer
    residual tail as ONE kernel (reference role: the add+LN CUDA fusions of
    math/bert_encoder_functor.cu and operators/fused/
    fused_embedding_eltwise_layernorm_op.cu). X is the residual, Y the
    branch output; Scale/LnBias the LN affine params."""
    import jax.numpy as jnp

    from ..kernels import fused_residual as frk
    from ..kernels.flash_attention import _seed_words

    x, y = ins["X"][0], ins["Y"][0]
    g = ins["Scale"][0] if ins.get("Scale") else None
    c = ins["LnBias"][0] if ins.get("LnBias") else None
    is_test, rate, gspmd_mode = _attn_ctx(ctx, op)
    st = _fdal_statics(op, is_test)
    N = x.shape[-1]
    x2, y2 = x.reshape(-1, N), y.reshape(-1, N)
    if _fdal_use_kernel(ctx, x2, gspmd_mode):
        if rate > 0.0 and not is_test:
            seed = _seed_words(ctx.key_for(op.uid, op.type))
        else:
            seed = jnp.zeros(2, jnp.uint32)
        gg = g if g is not None else jnp.ones((N,), jnp.float32)
        cc = c if c is not None else jnp.zeros((N,), jnp.float32)
        out2 = frk.fused_dropout_add_ln(
            x2, y2, gg, cc, seed, tuple(st.items()), False
        )
    else:
        key = (
            ctx.key_for(op.uid, op.type)
            if rate > 0.0 and not is_test
            else None
        )
        out2 = frk.reference_fwd(x2, y2, g, c, key, **st)
    return {"Out": [out2.reshape(x.shape)]}


@register_op(
    "fused_dropout_add_ln_grad",
    inputs=["X", "Y", "Scale", "LnBias", "OutGrad"],
    outputs=["XGrad", "YGrad", "ScaleGrad", "LnBiasGrad"],
    differentiable=False,
)
def _fused_dropout_add_ln_grad(ctx, op, ins):
    import jax
    import jax.numpy as jnp

    from ..kernels import fused_residual as frk
    from ..kernels.flash_attention import _seed_words

    x, y, dout = ins["X"][0], ins["Y"][0], ins["OutGrad"][0]
    g = ins["Scale"][0] if ins.get("Scale") else None
    c = ins["LnBias"][0] if ins.get("LnBias") else None
    is_test, rate, gspmd_mode = _attn_ctx(ctx, op)
    st = _fdal_statics(op, is_test)
    N = x.shape[-1]
    x2, y2, do2 = x.reshape(-1, N), y.reshape(-1, N), dout.reshape(-1, N)
    fwd_uid = int(op.attr("__fwd_uid__", 0))
    if _fdal_use_kernel(ctx, x2, gspmd_mode):
        if rate > 0.0 and not is_test:
            seed = _seed_words(ctx.key_for(fwd_uid, "fused_dropout_add_ln"))
        else:
            seed = jnp.zeros(2, jnp.uint32)
        gg = g if g is not None else jnp.ones((N,), jnp.float32)
        dx, dy, dg, dc = frk.fused_dropout_add_ln_bwd(
            x2, y2, gg, seed, do2, st["rate"], st["is_test"],
            st["upscale"], st["eps"], False,
        )
    else:
        key = (
            ctx.key_for(fwd_uid, "fused_dropout_add_ln")
            if rate > 0.0 and not is_test
            else None
        )

        def f(x_, y_, g_, c_):
            return frk.reference_fwd(x_, y_, g_, c_, key, **st)

        ones = jnp.ones((N,), jnp.float32)
        zeros = jnp.zeros((N,), jnp.float32)
        _, vjp = jax.vjp(
            f, x2, y2, g if g is not None else ones,
            c if c is not None else zeros,
        )
        dx, dy, dg, dc = vjp(do2)
    outs = {}
    if op.outputs.get("XGrad"):
        outs["XGrad"] = [dx.reshape(x.shape)]
    if op.outputs.get("YGrad"):
        outs["YGrad"] = [dy.reshape(y.shape)]
    if op.outputs.get("ScaleGrad") and g is not None:
        outs["ScaleGrad"] = [dg.astype(g.dtype)]
    if op.outputs.get("LnBiasGrad") and c is not None:
        outs["LnBiasGrad"] = [dc.astype(c.dtype)]
    return outs
