"""Quantization op family (reference operators/fake_quantize_op.cc,
fake_dequantize_op.cc, mkldnn quantize_op.cc/dequantize_op.cc/
requantize_op.cc, dequantize_abs_max_op.cc, dequantize_log_op.cc,
lookup_table_dequant_op.cc).

All fake-quant training ops use the straight-through estimator: forward
carries the quantization error, backward is identity via
x + stop_gradient(q - x) — the reference gets the same STE from its
hand-written grad kernels. Scale observers (range / moving-average) keep
their state as explicit outputs so the executor's persistable write-back
updates them in place (no mutable buffers inside jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _bound(op):
    return float(2 ** (op.attr("bit_length", 8) - 1) - 1)


def _ste(x, q):
    return x + jax.lax.stop_gradient(q - x)


@register_op(
    "fake_quantize_abs_max", inputs=["X"], outputs=["Out", "OutScale"]
)
def _fake_quantize_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bound = _bound(op)
    scale = jnp.max(jnp.abs(x)) + 1e-9
    q = jnp.clip(jnp.round(x / scale * bound), -bound, bound)
    return {"Out": [_ste(x, q)], "OutScale": [scale.reshape([1])]}


@register_op(
    "fake_channel_wise_quantize_abs_max",
    inputs=["X"],
    outputs=["Out", "OutScale"],
)
def _fake_channel_wise_quantize_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bound = _bound(op)
    axis = op.attr("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True) + 1e-9
    q = jnp.clip(jnp.round(x / scale * bound), -bound, bound)
    return {"Out": [_ste(x, q)], "OutScale": [scale.reshape(-1)]}


@register_op(
    "fake_quantize_range_abs_max",
    inputs=["X", "InScale", "Iter"],
    outputs=["Out", "OutScale", "OutScales"],
    mutates=(("OutScale", "InScale"),),
)
def _fake_quantize_range_abs_max(ctx, op, ins):
    """Range observer (fake_quantize_op.cc FindRangeAbsMax): at train time
    the running scale is max(cur_abs_max, in_scale); is_test freezes it.
    The reference's window-of-scales ring buffer becomes the single running
    max (window eviction is a CPU-side bookkeeping detail; the max over the
    window is what the quantizer consumes)."""
    x = ins["X"][0]
    bound = _bound(op)
    in_scale = ins["InScale"][0].reshape(())
    if op.attr("is_test", False) or ctx.is_test:
        scale = in_scale
    else:
        scale = jnp.maximum(jnp.max(jnp.abs(x)), in_scale) + 1e-9
    q = jnp.clip(jnp.round(x / scale * bound), -bound, bound)
    out_scale = scale.reshape([1])
    return {"Out": [_ste(x, q)], "OutScale": [out_scale], "OutScales": []}


def _moving_scale(ctx, op, x, ins):
    in_accum = ins["InAccum"][0].reshape(())
    in_state = ins["InState"][0].reshape(())
    rate = op.attr("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    state = rate * in_state + 1.0
    accum = rate * in_accum + cur
    scale = accum / state + 1e-9
    return scale, accum.reshape([1]), state.reshape([1])


@register_op(
    "fake_quantize_moving_average_abs_max",
    inputs=["X", "InScale", "InAccum", "InState"],
    outputs=["Out", "OutScale", "OutAccum", "OutState"],
    mutates=(("OutAccum", "InAccum"), ("OutState", "InState")),
)
def _fake_quantize_moving_average_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bound = _bound(op)
    if op.attr("is_test", False) or ctx.is_test:
        scale = ins["InScale"][0].reshape(())
        accum = ins["InAccum"][0].reshape([1])
        state = ins["InState"][0].reshape([1])
    else:
        scale, accum, state = _moving_scale(ctx, op, x, ins)
    q = jnp.clip(jnp.round(x / scale * bound), -bound, bound)
    return {
        "Out": [_ste(x, q)],
        "OutScale": [scale.reshape([1])],
        "OutAccum": [accum],
        "OutState": [state],
    }


@register_op(
    "fake_quantize_dequantize_moving_average_abs_max",
    inputs=["X", "InScale", "InAccum", "InState"],
    outputs=["Out", "OutScale", "OutAccum", "OutState"],
    mutates=(("OutAccum", "InAccum"), ("OutState", "InState")),
)
def _fake_qdq_moving_average_abs_max(ctx, op, ins):
    x = ins["X"][0]
    bound = _bound(op)
    if op.attr("is_test", False) or ctx.is_test:
        scale = ins["InScale"][0].reshape(())
        accum = ins["InAccum"][0].reshape([1])
        state = ins["InState"][0].reshape([1])
    else:
        scale, accum, state = _moving_scale(ctx, op, x, ins)
    q = jnp.clip(jnp.round(x / scale * bound), -bound, bound) * scale / bound
    return {
        "Out": [_ste(x, q)],
        "OutScale": [scale.reshape([1])],
        "OutAccum": [accum],
        "OutState": [state],
    }


@register_op(
    "moving_average_abs_max_scale",
    inputs=["X", "InAccum", "InState"],
    outputs=["Out", "OutScale", "OutAccum", "OutState"],
    mutates=(("OutAccum", "InAccum"), ("OutState", "InState")),
)
def _moving_average_abs_max_scale(ctx, op, ins):
    """Scale observer only — X passes through untouched (used to record
    activation ranges for PTQ)."""
    x = ins["X"][0]
    if ctx.is_test:
        accum = ins["InAccum"][0].reshape([1])
        state = ins["InState"][0].reshape([1])
        scale = accum / jnp.maximum(state, 1e-9)
    else:
        scale, accum, state = _moving_scale(ctx, op, x, ins)
    return {
        "Out": [x],
        "OutScale": [jnp.reshape(scale, [1])],
        "OutAccum": [accum],
        "OutState": [state],
    }


@register_op(
    "fake_dequantize_max_abs", inputs=["X", "Scale"], outputs=["Out"]
)
def _fake_dequantize_max_abs(ctx, op, ins):
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = op.attr("max_range", 127.0)
    return {"Out": [x.astype(jnp.float32) * scale.reshape(()) / max_range]}


@register_op(
    "fake_channel_wise_dequantize_max_abs",
    inputs=["X", "Scales"],
    outputs=["Out"],
)
def _fake_channel_wise_dequantize_max_abs(ctx, op, ins):
    x = ins["X"][0].astype(jnp.float32)
    scales = ins["Scales"]
    bits = op.attr("quant_bits", [8])
    axis = op.attr("quant_axis", 0)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = x * scales[0].reshape(shape) / float(2 ** (bits[0] - 1) - 1)
    if len(scales) > 1 and scales[1] is not None:
        # second-level (whole-tensor) scale from the producing matmul
        out = out * scales[1].reshape(()) / float(2 ** (bits[1] - 1) - 1)
    return {"Out": [out]}


# --- mkldnn-style int8 pipeline ops: XLA handles int8 natively ---


@register_op("quantize", inputs=["Input"], outputs=["Output"])
def _quantize(ctx, op, ins):
    x = ins["Input"][0]
    scale = op.attr("Scale", 1.0)
    shift = op.attr("Shift", 0.0)
    q = jnp.round(x * scale + shift)
    if op.attr("is_negative_input", True) and not shift:
        q = jnp.clip(q, -128, 127).astype(jnp.int8)
    else:
        q = jnp.clip(q, 0, 255).astype(jnp.uint8)
    return {"Output": [q]}


@register_op("dequantize", inputs=["Input"], outputs=["Output"])
def _dequantize(ctx, op, ins):
    x = ins["Input"][0]
    scale = op.attr("Scale", 1.0)
    shift = op.attr("Shift", 0.0)
    return {"Output": [(x.astype(jnp.float32) - shift) / scale]}


@register_op("requantize", inputs=["Input"], outputs=["Output"])
def _requantize(ctx, op, ins):
    x = ins["Input"][0]
    s_in = op.attr("Scale_in", 1.0)
    s_out = op.attr("Scale_out", 1.0)
    q = jnp.round(x.astype(jnp.float32) * (s_out / s_in))
    return {"Output": [jnp.clip(q, -128, 127).astype(x.dtype)]}


@register_op("dequantize_abs_max", inputs=["X", "Scale"], outputs=["Out"])
def _dequantize_abs_max(ctx, op, ins):
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = op.attr("max_range", 127.0)
    return {"Out": [x.astype(jnp.float32) * scale.reshape(()) / max_range]}


@register_op("dequantize_log", inputs=["X", "Dict"], outputs=["Out"])
def _dequantize_log(ctx, op, ins):
    """dequantize_log_op.cc: int8 codes index a 128-entry log table;
    negative codes mirror with sign (x<0 -> -dict[x+128])."""
    x = ins["X"][0].astype(jnp.int32)
    table = ins["Dict"][0]
    out = jnp.where(
        x < 0, -table[jnp.clip(x + 128, 0, 127)], table[jnp.clip(x, 0, 127)]
    )
    return {"Out": [out]}


# --- quantized embedding variants ---


@register_op("lookup_table_dequant", inputs=["W", "Ids"], outputs=["Out"])
def _lookup_table_dequant(ctx, op, ins):
    """lookup_table_dequant_op.cc: rows store [min, max, uint8 codes];
    out = min + (max - min) * code / 255, gathered then dequantized (one
    fused gather+affine here)."""
    w, ids = ins["W"][0], ins["Ids"][0].astype(jnp.int32)
    ids_flat = ids.reshape(-1)
    rows = w[ids_flat]  # [N, 2 + D_packed]
    lo = rows[:, 0:1]
    hi = rows[:, 1:2]
    codes = rows[:, 2:].astype(jnp.float32)
    out = lo + (hi - lo) * codes / 255.0
    return {"Out": [out.reshape(*ids.shape[:-1], -1)]}


@register_op("lookup_sparse_table", inputs=["W", "Ids"], outputs=["Out"])
def _lookup_sparse_table(ctx, op, ins):
    """lookup_sparse_table_op.cc (auto-growing PS table): the sharded-table
    design (ops/sparse.py) pre-sizes tables, so this is a plain row gather;
    unseen ids map to the init rows already materialized."""
    w, ids = ins["W"][0], ins["Ids"][0].astype(jnp.int32)
    out = w[ids.reshape(-1)]
    if ids.ndim > 1 and ids.shape[-1] == 1:
        out = out.reshape(*ids.shape[:-1], -1)
    return {"Out": [out]}
