"""KV-cache ops for the serving decode path (serving/generate.py).

Autoregressive generation re-running the full context every token is
O(S^2) recompute per sequence; the serving decode path instead keeps each
transformer layer's key/value tensors in persistable scope vars (the same
donation/write-back aliasing the optimizer uses for parameters, so the
cache update is an in-place HBM dynamic-update-slice) and runs a
single-token program per step:

* ``kv_cache_write`` — write the current step's K/V rows into the cache at
  a runtime position (``jax.lax.dynamic_update_slice_in_dim``; the output
  aliases the cache input, which the Executor donates).
* ``kv_cache_attention`` — one fused emitter for masked decode attention:
  Q for the current token against the full cache, positions beyond ``Pos``
  masked out. XLA sees one [B, nh, T, S] score tensor per layer instead of
  a chain of mask/where/softmax ops (the PR-6 "one wide op" argument).

Neither op is differentiable: they exist only in frozen inference graphs
(serving/freeze.py verifies no training op survives next to them).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


def _pos_scalar(pos):
    """Feeds arrive as [1]-shaped arrays; indices must be 0-d."""
    return jnp.reshape(pos, ()).astype(jnp.int32)


@register_op(
    "kv_cache_write",
    inputs=["Cache", "X", "Pos"],
    outputs=["Out"],
    differentiable=False,
    mutates=(("Out", "Cache"),),
)
def _kv_cache_write(ctx, op, ins):
    cache = ins["Cache"][0]
    x = ins["X"][0]
    pos = _pos_scalar(ins["Pos"][0])
    out = jax.lax.dynamic_update_slice_in_dim(
        cache, x.astype(cache.dtype), pos, axis=1
    )
    return {"Out": [out]}


@register_op(
    "kv_cache_attention",
    inputs=["Q", "CacheK", "CacheV", "Pos"],
    outputs=["Out"],
    differentiable=False,
)
def _kv_cache_attention(ctx, op, ins):
    q = ins["Q"][0]
    k = ins["CacheK"][0]
    v = ins["CacheV"][0]
    pos = _pos_scalar(ins["Pos"][0])
    nh = int(op.attr("num_heads"))
    scale = float(op.attr("scale", 1.0))
    # inference residue of fluid's downgrade_in_infer attention dropout:
    # probs scale by (1 - dropout_prob) so cached decode matches the
    # training graph's test-mode numerics exactly
    prob_scale = float(op.attr("prob_scale", 1.0))
    b, t, h = q.shape
    s = k.shape[1]
    dh = h // nh
    qh = q.reshape(b, t, nh, dh).transpose(0, 2, 1, 3)  # [B, nh, T, dh]
    kh = k.reshape(b, s, nh, dh).transpose(0, 2, 3, 1)  # [B, nh, dh, S]
    scores = jnp.matmul(qh, kh).astype(jnp.float32) * scale
    # Pos is the cache position of the LAST query row; query row i sits at
    # position Pos - (T-1) + i and may attend keys 0..that position
    # (causal within a prefill window, the single current slot in decode;
    # later cache slots hold garbage or future rows)
    qpos = pos - (t - 1) + jnp.arange(t, dtype=jnp.int32)
    valid = (
        jnp.arange(s, dtype=jnp.int32)[None, None, None, :]
        <= qpos[None, None, :, None]
    )
    scores = jnp.where(valid, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if prob_scale != 1.0:
        probs = probs * jnp.asarray(prob_scale, q.dtype)
    vh = v.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)  # [B, nh, S, dh]
    out = jnp.matmul(probs, vh)  # [B, nh, T, dh]
    return {"Out": [out.transpose(0, 2, 1, 3).reshape(b, t, h)]}
