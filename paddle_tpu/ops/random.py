"""Random ops. TPU-native RNG: counter-based stateless keys derived from
(program seed, step, op uid) — see registry.EmitContext. This replaces the
reference's per-device curand generators (gaussian_random_op.cu) and makes
every run bitwise reproducible when program.random_seed is set.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtypes import to_numpy_dtype
from ..framework.registry import register_op, BATCH_SENTINEL


def _shape_attr(op, ctx=None):
    shape = [int(s) for s in op.attr("shape")]
    if any(s == -1 for s in shape):
        if ctx is not None and ctx.abstract:
            return [BATCH_SENTINEL if s == -1 else s for s in shape]
        raise ValueError(
            f"{op.type} with -1 (batch) dims cannot execute; pass a concrete shape"
        )
    return shape


def _key(ctx, op):
    seed = op.attr("seed", 0)
    if seed:
        return jax.random.key(seed + op.uid)
    return ctx.key_for(op.uid, op.type)


@register_op("gaussian_random", inputs=[], outputs=["Out"], differentiable=False)
def _gaussian_random(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.normal(
        _key(ctx, op), _shape_attr(op, ctx), dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op(
    "truncated_gaussian_random", inputs=[], outputs=["Out"], differentiable=False
)
def _truncated_gaussian_random(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.truncated_normal(
        _key(ctx, op), -2.0, 2.0, _shape_attr(op, ctx), dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op("uniform_random", inputs=[], outputs=["Out"], differentiable=False)
def _uniform_random(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = jax.random.uniform(
        _key(ctx, op),
        _shape_attr(op, ctx),
        minval=op.attr("min", -1.0),
        maxval=op.attr("max", 1.0),
        dtype=jnp.float32,
    )
    return {"Out": [out.astype(dtype)]}


@register_op("randint", inputs=[], outputs=["Out"], differentiable=False)
def _randint(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "int64"))
    out = jax.random.randint(
        _key(ctx, op), _shape_attr(op, ctx), op.attr("low", 0), op.attr("high")
    )
    return {"Out": [out.astype(dtype)]}


@register_op("randperm", inputs=[], outputs=["Out"], differentiable=False)
def _randperm(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "int64"))
    return {"Out": [jax.random.permutation(_key(ctx, op), op.attr("n")).astype(dtype)]}


@register_op("shuffle_batch", inputs=["X"], outputs=["Out"], differentiable=False)
def _shuffle_batch(ctx, op, ins):
    x = ins["X"][0]
    perm = jax.random.permutation(_key(ctx, op), x.shape[0])
    return {"Out": [jnp.take(x, perm, axis=0)]}
