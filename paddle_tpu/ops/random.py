"""Random ops. TPU-native RNG: counter-based stateless keys derived from
(program seed, step, op uid) — see registry.EmitContext. This replaces the
reference's per-device curand generators (gaussian_random_op.cu) and makes
every run bitwise reproducible when program.random_seed is set.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtypes import to_numpy_dtype
from ..framework.registry import register_op, BATCH_SENTINEL


def _shape_attr(op, ctx=None):
    shape = [int(s) for s in op.attr("shape")]
    if any(s == -1 for s in shape):
        if ctx is not None and ctx.abstract:
            return [BATCH_SENTINEL if s == -1 else s for s in shape]
        raise ValueError(
            f"{op.type} with -1 (batch) dims cannot execute; pass a concrete shape"
        )
    return shape


from ._helpers import op_key as _key


@register_op("gaussian_random", inputs=[], outputs=["Out"], differentiable=False)
def _gaussian_random(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.normal(
        _key(ctx, op), _shape_attr(op, ctx), dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op(
    "truncated_gaussian_random", inputs=[], outputs=["Out"], differentiable=False
)
def _truncated_gaussian_random(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.truncated_normal(
        _key(ctx, op), -2.0, 2.0, _shape_attr(op, ctx), dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op("uniform_random", inputs=[], outputs=["Out"], differentiable=False)
def _uniform_random(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = jax.random.uniform(
        _key(ctx, op),
        _shape_attr(op, ctx),
        minval=op.attr("min", -1.0),
        maxval=op.attr("max", 1.0),
        dtype=jnp.float32,
    )
    return {"Out": [out.astype(dtype)]}


@register_op("randint", inputs=[], outputs=["Out"], differentiable=False)
def _randint(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "int64"))
    out = jax.random.randint(
        _key(ctx, op), _shape_attr(op, ctx), op.attr("low", 0), op.attr("high")
    )
    return {"Out": [out.astype(dtype)]}


@register_op("randperm", inputs=[], outputs=["Out"], differentiable=False)
def _randperm(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "int64"))
    return {"Out": [jax.random.permutation(_key(ctx, op), op.attr("n")).astype(dtype)]}


@register_op("shuffle_batch", inputs=["X"], outputs=["Out"], differentiable=False)
def _shuffle_batch(ctx, op, ins):
    x = ins["X"][0]
    perm = jax.random.permutation(_key(ctx, op), x.shape[0])
    return {"Out": [jnp.take(x, perm, axis=0)]}


# --- batch-size-like random fills (operators/uniform_random_batch_size_like
# _op.cc, gaussian_random_batch_size_like_op.cc): shape attr with the batch
# dim copied from Input at runtime-build time ---


def _bsl_shape(op, ins):
    x = ins["Input"][0]
    shape = [int(s) for s in op.attr("shape")]
    shape[op.attr("output_dim_idx", 0)] = x.shape[op.attr("input_dim_idx", 0)]
    return shape


@register_op(
    "uniform_random_batch_size_like",
    inputs=["Input"],
    outputs=["Out"],
    differentiable=False,
)
def _uniform_random_bsl(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = jax.random.uniform(
        _key(ctx, op),
        _bsl_shape(op, ins),
        minval=op.attr("min", -1.0),
        maxval=op.attr("max", 1.0),
        dtype=jnp.float32,
    )
    return {"Out": [out.astype(dtype)]}


@register_op(
    "gaussian_random_batch_size_like",
    inputs=["Input"],
    outputs=["Out"],
    differentiable=False,
)
def _gaussian_random_bsl(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    out = op.attr("mean", 0.0) + op.attr("std", 1.0) * jax.random.normal(
        _key(ctx, op), _bsl_shape(op, ins), dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op("sampling_id", inputs=["X"], outputs=["Out"], differentiable=False)
def _sampling_id(ctx, op, ins):
    # X rows are probabilities (sampling_id_op.cc draws u~U(min,max) and
    # walks the cumsum); categorical over log-probs is the vectorized form
    x = ins["X"][0]
    ids = jax.random.categorical(_key(ctx, op), jnp.log(x + 1e-20), axis=-1)
    return {"Out": [ids.astype(jnp.int64)]}


@register_op(
    "random_crop", inputs=["X", "Seed"], outputs=["Out", "SeedOut"],
    differentiable=False,
)
def _random_crop(ctx, op, ins):
    x = ins["X"][0]
    shape = [int(s) for s in op.attr("shape")]
    # leading dims (batch etc.) are kept; the trailing len(shape) dims crop
    # at a random offset (random_crop_op.h ComputeRandomCrop)
    lead = x.ndim - len(shape)
    key = _key(ctx, op)
    keys = jax.random.split(key, len(shape))
    starts = [jnp.zeros((), jnp.int32)] * lead + [
        jax.random.randint(k, (), 0, x.shape[lead + i] - s + 1)
        for i, (k, s) in enumerate(zip(keys, shape))
    ]
    sizes = list(x.shape[:lead]) + shape
    out = jax.lax.dynamic_slice(x, starts, sizes)
    seed = ins.get("Seed", [None])[0]
    seed_out = seed if seed is not None else jnp.zeros((1,), jnp.int64)
    return {"Out": [out], "SeedOut": [seed_out]}


@register_op("seed", inputs=[], outputs=["Out"], differentiable=False)
def _seed(ctx, op, ins):
    # emits the derived per-op seed (seed_op.cc feeds dropout determinism in
    # the reference; here RNG is counter-based so this is informational)
    s = op.attr("seed", 0)
    if not s:
        s = op.uid
    return {"Out": [jnp.asarray([s], dtype=jnp.int32)]}
