"""Activation ops (reference: operators/activation_op.cc — 30+ activations).

All are single jnp expressions; XLA fuses them into producer matmuls/convs,
which is why there is no fused-activation pass here (the reference needed
fuse_elewise_add_act_pass / fuse_bn_act_pass in ir/)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ._helpers import register_unary

register_unary("relu", lambda x, a: jax.nn.relu(x))
register_unary("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
register_unary("sigmoid", lambda x, a: jax.nn.sigmoid(x))
register_unary("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
register_unary("tanh", lambda x, a: jnp.tanh(x))
register_unary("gelu", lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)))
register_unary("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)))
register_unary("elu", lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)))
register_unary("selu", lambda x, a: jax.nn.selu(x))
register_unary("softplus", lambda x, a: jax.nn.softplus(x))
register_unary("softsign", lambda x, a: jax.nn.soft_sign(x))
register_unary("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
register_unary("hard_swish", lambda x, a: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)
register_unary(
    "hard_sigmoid",
    lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
)
register_unary(
    "hard_shrink",
    lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
)
register_unary(
    "soft_shrink",
    lambda x, a: jnp.sign(x) * jax.nn.relu(jnp.abs(x) - a.get("lambda", 0.5)),
)
register_unary(
    "thresholded_relu",
    lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
)
register_unary("tanh_shrink", lambda x, a: x - jnp.tanh(x))
register_unary("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
register_unary("silu", lambda x, a: jax.nn.silu(x))
register_unary("erf", lambda x, a: jax.scipy.special.erf(x))
register_unary(
    "softmax", lambda x, a: jax.nn.softmax(x, axis=a.get("axis", -1))
)
register_unary(
    "log_softmax", lambda x, a: jax.nn.log_softmax(x, axis=a.get("axis", -1))
)
