"""Extended math/tensor op surface (reference operators/ root: the long
tail of small ops — addmm_op.cc, cos_sim_op.cc, kron_op.cc, one_hot_op.cc,
pixel_shuffle_op.cc, ...). Each is a direct XLA emitter; gradients come
from the generic vjp. Static-shape-friendly subset only — ops whose
reference semantics require dynamic output shapes (unique, where_index,
edit_distance with LoD) stay with their subsystem re-designs.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from ._helpers import fluid_broadcast


# ---------------------------------------------------------------------------
# linalg / tensor construction
# ---------------------------------------------------------------------------


@register_op("addmm", inputs=["Input", "X", "Y"], outputs=["Out"])
def _addmm(ctx, op, ins):
    inp, x, y = ins["Input"][0], ins["X"][0], ins["Y"][0]
    return {
        "Out": [
            op.attr("Beta", 1.0) * inp
            + op.attr("Alpha", 1.0) * (x @ y)
        ]
    }


@register_op("cholesky", inputs=["X"], outputs=["Out"])
def _cholesky(ctx, op, ins):
    c = jnp.linalg.cholesky(ins["X"][0])
    if op.attr("upper", False):
        c = jnp.swapaxes(c, -1, -2)
    return {"Out": [c]}


@register_op("inverse", inputs=["Input"], outputs=["Output"])
def _inverse(ctx, op, ins):
    return {"Output": [jnp.linalg.inv(ins["Input"][0])]}


@register_op("kron", inputs=["X", "Y"], outputs=["Out"])
def _kron(ctx, op, ins):
    return {"Out": [jnp.kron(ins["X"][0], ins["Y"][0])]}


@register_op("cross", inputs=["X", "Y"], outputs=["Out"])
def _cross(ctx, op, ins):
    x = ins["X"][0]
    # reference sentinel for "auto-pick the first size-3 axis" is
    # DDim::kMaxRank (9), NOT -1 — negative dims are valid explicit axes
    # (cross_op.cc:54 accepts dim >= -rank)
    dim = op.attr("dim", 9)
    if dim is None or dim >= 9:
        dim = next(
            (i for i, s in enumerate(x.shape) if s == 3), len(x.shape) - 1
        )
    elif dim < 0:
        dim += x.ndim
    return {"Out": [jnp.cross(x, ins["Y"][0], axis=dim)]}


@register_op("eye", inputs=[], outputs=["Out"])
def _eye(ctx, op, ins):
    from ..core.dtypes import to_numpy_dtype

    n = int(op.attr("num_rows"))
    m = int(op.attr("num_columns", -1))
    dt = to_numpy_dtype(op.attr("dtype", "float32"))
    return {"Out": [jnp.eye(n, n if m in (-1, None) else m, dtype=dt)]}


@register_op("meshgrid", inputs=["X"], outputs=["Out"])
def _meshgrid(ctx, op, ins):
    return {"Out": list(jnp.meshgrid(*ins["X"], indexing="ij"))}


@register_op("diag_v2", inputs=["X"], outputs=["Out"])
def _diag_v2(ctx, op, ins):
    x = ins["X"][0]
    k = int(op.attr("offset", 0))
    if x.ndim == 1:
        out = jnp.diag(x, k=k)
        pad = op.attr("padding_value", 0.0)
        if pad:
            mask = jnp.diag(jnp.ones_like(x), k=k)
            out = out + (1 - mask) * pad
        return {"Out": [out]}
    return {"Out": [jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)]}


@register_op("diag_embed", inputs=["Input"], outputs=["Out"])
def _diag_embed(ctx, op, ins):
    x = ins["Input"][0]
    k = int(op.attr("offset", 0))
    n = x.shape[-1] + abs(k)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-k, 0)
    c = idx + max(k, 0)
    return {"Out": [out.at[..., r, c].set(x)]}


@register_op("trace", inputs=["Input"], outputs=["Out"])
def _trace(ctx, op, ins):
    return {
        "Out": [
            jnp.trace(
                ins["Input"][0],
                offset=op.attr("offset", 0),
                axis1=op.attr("axis1", 0),
                axis2=op.attr("axis2", 1),
            )
        ]
    }


@register_op("flatten", inputs=["X"], outputs=["Out"])
def _flatten(ctx, op, ins):
    x = ins["X"][0]
    ax = int(op.attr("axis", 1))
    lead = int(np.prod(x.shape[:ax])) if ax else 1
    return {"Out": [x.reshape(lead, -1)]}


@register_op("one_hot", inputs=["X"], outputs=["Out"])
def _one_hot(ctx, op, ins):
    x = ins["X"][0]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return {"Out": [jax.nn.one_hot(x, int(op.attr("depth")))]}


@register_op("fill_zeros_like", inputs=["X"], outputs=["Out"])
def _fill_zeros_like(ctx, op, ins):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register_op("shard_index", inputs=["X"], outputs=["Out"])
def _shard_index(ctx, op, ins):
    x = ins["X"][0]
    index_num = int(op.attr("index_num"))
    nshards = int(op.attr("nshards"))
    shard_id = int(op.attr("shard_id"))
    ignore = int(op.attr("ignore_value", -1))
    size = (index_num + nshards - 1) // nshards
    inside = (x // size) == shard_id
    return {"Out": [jnp.where(inside, x % size, ignore)]}


@register_op("size", inputs=["Input"], outputs=["Out"])
def _size(ctx, op, ins):
    return {
        "Out": [jnp.asarray(int(np.prod(ins["Input"][0].shape)), jnp.int64)]
    }


# ---------------------------------------------------------------------------
# elementwise / comparison extras
# ---------------------------------------------------------------------------


@register_op("allclose", inputs=["Input", "Other"], outputs=["Out"])
def _allclose(ctx, op, ins):
    return {
        "Out": [
            jnp.allclose(
                ins["Input"][0],
                ins["Other"][0],
                rtol=float(op.attr("rtol", 1e-5)),
                atol=float(op.attr("atol", 1e-8)),
                equal_nan=bool(op.attr("equal_nan", False)),
            )
        ]
    }


@register_op("minus", inputs=["X", "Y"], outputs=["Out"])
def _minus(ctx, op, ins):
    x, y = fluid_broadcast(ins["X"][0], ins["Y"][0], -1)
    return {"Out": [x - y]}


@register_op("label_smooth", inputs=["X", "PriorDist"], outputs=["Out"])
def _label_smooth(ctx, op, ins):
    x = ins["X"][0]
    eps = float(op.attr("epsilon", 0.0))
    prior = (
        ins["PriorDist"][0]
        if ins.get("PriorDist") and ins["PriorDist"][0] is not None
        else 1.0 / x.shape[-1]
    )
    return {"Out": [(1.0 - eps) * x + eps * prior]}


@register_op("multiplex", inputs=["X", "Ids"], outputs=["Out"])
def _multiplex(ctx, op, ins):
    stacked = jnp.stack(ins["X"], axis=0)  # [K, B, ...]
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    return {"Out": [stacked[ids, jnp.arange(ids.shape[0])]]}


# ---------------------------------------------------------------------------
# norms / similarity
# ---------------------------------------------------------------------------


@register_op("cos_sim", inputs=["X", "Y"], outputs=["Out"])
def _cos_sim(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    num = jnp.sum(x * y, axis=-1, keepdims=True)
    return {"Out": [num / jnp.maximum(xn * yn, 1e-12)]}


@register_op("l1_norm", inputs=["X"], outputs=["Out"])
def _l1_norm(ctx, op, ins):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0]))]}


@register_op("norm", inputs=["X"], outputs=["Out", "Norm"])
def _norm(ctx, op, ins):
    x = ins["X"][0]
    axis = int(op.attr("axis", -1))
    eps = float(op.attr("epsilon", 1e-10))
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / n], "Norm": [n]}


@register_op("p_norm", inputs=["X"], outputs=["Out"])
def _p_norm(ctx, op, ins):
    x = ins["X"][0]
    p = float(op.attr("porder", 2.0))
    axis = int(op.attr("axis", -1))
    keep = bool(op.attr("keepdim", False))
    eps = float(op.attr("epsilon", 1e-12))
    if op.attr("asvector", False):
        # p_norm_op.cc asvector: reduce over the FLATTENED tensor
        x = x.reshape(-1)
        axis = 0
    out = (jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keep) + eps) ** (
        1.0 / p
    )
    return {"Out": [out]}


@register_op("squared_l2_distance", inputs=["X", "Y"], outputs=["Out", "sub_result"])
def _squared_l2_distance(ctx, op, ins):
    d = ins["X"][0] - ins["Y"][0]
    return {
        "Out": [jnp.sum(d * d, axis=-1, keepdims=True)],
        "sub_result": [d],
    }


@register_op("dist", inputs=["X", "Y"], outputs=["Out"])
def _dist(ctx, op, ins):
    d = jnp.abs(ins["X"][0] - ins["Y"][0])
    p = float(op.attr("p", 2.0))
    if p == float("inf"):
        return {"Out": [jnp.max(d)]}
    if p == 0.0:
        return {"Out": [jnp.sum((d != 0).astype(d.dtype))]}
    return {"Out": [jnp.sum(d ** p) ** (1.0 / p)]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("bce_loss", inputs=["X", "Label"], outputs=["Out"])
def _bce_loss(ctx, op, ins):
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-12
    return {
        "Out": [
            -(label * jnp.log(jnp.maximum(x, eps))
              + (1 - label) * jnp.log(jnp.maximum(1 - x, eps)))
        ]
    }


@register_op("nll_loss", inputs=["X", "Label", "Weight"], outputs=["Out", "Total_weight"])
def _nll_loss(ctx, op, ins):
    x, label = ins["X"][0], ins["Label"][0].astype(jnp.int32)
    w = (
        ins["Weight"][0]
        if ins.get("Weight") and ins["Weight"][0] is not None
        else jnp.ones((x.shape[1],), x.dtype)
    )
    ignore = int(op.attr("ignore_index", -100))
    picked = -x[jnp.arange(x.shape[0]), label] * w[label]
    valid = label != ignore
    picked = jnp.where(valid, picked, 0.0)
    tw = jnp.sum(jnp.where(valid, w[label], 0.0))
    red = op.attr("reduction", "mean")
    if red == "mean":
        out = jnp.sum(picked) / jnp.maximum(tw, 1e-12)
    elif red == "sum":
        out = jnp.sum(picked)
    else:
        out = picked
    return {"Out": [out], "Total_weight": [tw]}


@register_op("hinge_loss", inputs=["Logits", "Labels"], outputs=["Loss"])
def _hinge_loss(ctx, op, ins):
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0)]}


@register_op("modified_huber_loss", inputs=["X", "Y"],
             outputs=["Out", "IntermediateVal"])
def _modified_huber_loss(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(
        z < -1.0, -4.0 * z, jnp.square(jnp.maximum(1.0 - z, 0.0))
    )
    return {"Out": [loss], "IntermediateVal": [z]}


@register_op("margin_rank_loss", inputs=["X1", "X2", "Label"],
             outputs=["Out", "Activated"])
def _margin_rank_loss(ctx, op, ins):
    x1, x2, label = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    margin = float(op.attr("margin", 0.0))
    out = jnp.maximum(-label * (x1 - x2) + margin, 0.0)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register_op("rank_loss", inputs=["Left", "Right", "Label"], outputs=["Out"])
def _rank_loss(ctx, op, ins):
    left, right, label = ins["Left"][0], ins["Right"][0], ins["Label"][0]
    d = left - right
    return {"Out": [jnp.maximum(d, 0.0) - d * label + jnp.log1p(jnp.exp(-jnp.abs(d)))]}


@register_op("bpr_loss", inputs=["X", "Label"], outputs=["Y"])
def _bpr_loss(ctx, op, ins):
    x, label = ins["X"][0], ins["Label"][0].astype(jnp.int32)
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, 0]
    pos = x[jnp.arange(x.shape[0]), label][:, None]
    # mean over negative items of -log sigmoid(pos - neg), excluding pos
    diff = pos - x
    lo = -jax.nn.log_sigmoid(diff)
    mask = jax.nn.one_hot(label, x.shape[1], dtype=x.dtype)
    out = jnp.sum(lo * (1 - mask), axis=1, keepdims=True) / (x.shape[1] - 1)
    return {"Y": [out]}


# ---------------------------------------------------------------------------
# vision extras
# ---------------------------------------------------------------------------


@register_op("pixel_shuffle", inputs=["X"], outputs=["Out"])
def _pixel_shuffle(ctx, op, ins):
    x = ins["X"][0]
    r = int(op.attr("upscale_factor", 1))
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = x.transpose(0, 1, 4, 2, 5, 3)
    return {"Out": [x.reshape(n, c // (r * r), h * r, w * r)]}


@register_op("affine_channel", inputs=["X", "Scale", "Bias"], outputs=["Out"])
def _affine_channel(ctx, op, ins):
    x, scale, bias = ins["X"][0], ins["Scale"][0], ins["Bias"][0]
    shape = [1, -1] + [1] * (x.ndim - 2)
    return {"Out": [x * scale.reshape(shape) + bias.reshape(shape)]}


@register_op("maxout", inputs=["X"], outputs=["Out"])
def _maxout(ctx, op, ins):
    x = ins["X"][0]
    groups = int(op.attr("groups"))
    n, c, h, w = x.shape
    return {"Out": [jnp.max(x.reshape(n, c // groups, groups, h, w), axis=2)]}


@register_op("max_pool2d_with_index", inputs=["X"], outputs=["Out", "Mask"])
def _max_pool2d_with_index(ctx, op, ins):
    """Mask holds flat indices into the UNPADDED input, matching
    pool_with_index_op.cc (padded window positions can never win: they
    read -inf)."""
    x = ins["X"][0]
    k = [int(v) for v in op.attr("ksize")]
    s = [int(v) for v in op.attr("strides", k)]
    p = [int(v) for v in op.attr("paddings", [0, 0])]
    if op.attr("global_pooling", False):
        k = [int(x.shape[2]), int(x.shape[3])]
        s, p = [1, 1], [0, 0]
    n, c, h, w = x.shape
    oh = (h + 2 * p[0] - k[0]) // s[0] + 1
    ow = (w + 2 * p[1] - k[1]) // s[1] + 1
    rows = (jnp.arange(oh) * s[0] - p[0])[:, None] + jnp.arange(k[0])[None, :]
    cols = (jnp.arange(ow) * s[1] - p[1])[:, None] + jnp.arange(k[1])[None, :]
    rvalid = (rows >= 0) & (rows < h)
    cvalid = (cols >= 0) & (cols < w)
    rc = jnp.clip(rows, 0, h - 1)
    cc = jnp.clip(cols, 0, w - 1)
    win = x[:, :, rc[:, None, :, None], cc[None, :, None, :]]
    valid = rvalid[:, None, :, None] & cvalid[None, :, None, :]
    win = jnp.where(valid[None, None], win, -jnp.inf)
    flat = win.reshape(n, c, oh, ow, k[0] * k[1])
    arg = jnp.argmax(flat, axis=-1)
    out = jnp.max(flat, axis=-1)
    ky, kx = arg // k[1], arg % k[1]
    gy = jnp.clip(jnp.arange(oh)[None, None, :, None] * s[0] - p[0] + ky, 0, h - 1)
    gx = jnp.clip(jnp.arange(ow)[None, None, None, :] * s[1] - p[1] + kx, 0, w - 1)
    return {"Out": [out], "Mask": [(gy * w + gx).astype(jnp.int32)]}


@register_op("lrn", inputs=["X"], outputs=["Out", "MidOut"])
def _lrn(ctx, op, ins):
    x = ins["X"][0]
    n = int(op.attr("n", 5))
    alpha = float(op.attr("alpha", 1e-4))
    beta = float(op.attr("beta", 0.75))
    k = float(op.attr("k", 1.0))
    half = n // 2
    sq = jnp.square(x)
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(
        pad[:, i:i + x.shape[1]] for i in range(n)
    )
    mid = k + alpha * acc
    return {"Out": [x / mid ** beta], "MidOut": [mid]}


@register_op("grid_sampler", inputs=["X", "Grid"], outputs=["Output"])
def _grid_sampler(ctx, op, ins):
    """Bilinear grid sampling with zero padding and align_corners=True
    (grid_sampler_op.cc defaults); grid values in [-1, 1]."""
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0  # [N, Ho, Wo]
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0

    def corner(yi, xi):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1)
        xc = jnp.clip(xi, 0, w - 1)
        v = x[jnp.arange(n)[:, None, None], :, yc, xc]  # [N, Ho, Wo, C]
        return jnp.where(inb[..., None], v, 0.0)

    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    lx = gx - x0
    ly = gy - y0
    out = (
        corner(y0, x0) * ((1 - ly) * (1 - lx))[..., None]
        + corner(y0, x0 + 1) * ((1 - ly) * lx)[..., None]
        + corner(y0 + 1, x0) * (ly * (1 - lx))[..., None]
        + corner(y0 + 1, x0 + 1) * (ly * lx)[..., None]
    )
    return {"Output": [out.transpose(0, 3, 1, 2)]}


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


@register_op("index_select", inputs=["X", "Index"], outputs=["Out"])
def _index_select(ctx, op, ins):
    return {
        "Out": [
            jnp.take(
                ins["X"][0],
                ins["Index"][0].astype(jnp.int32),
                axis=int(op.attr("dim", 0)),
            )
        ]
    }


@register_op("index_sample", inputs=["X", "Index"], outputs=["Out"])
def _index_sample(ctx, op, ins):
    x = ins["X"][0]
    idx = ins["Index"][0].astype(jnp.int32)
    return {"Out": [jnp.take_along_axis(x, idx, axis=1)]}


@register_op("histogram", inputs=["X"], outputs=["Out"])
def _histogram(ctx, op, ins):
    x = ins["X"][0].ravel()
    bins = int(op.attr("bins", 100))
    lo = float(op.attr("min", 0))
    hi = float(op.attr("max", 0))
    if lo == 0 and hi == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return {"Out": [hist.astype(jnp.int64)]}


@register_op("gather_tree", inputs=["Ids", "Parents"], outputs=["Out"])
def _gather_tree(ctx, op, ins):
    """Beam-search ancestry walk (gather_tree_op.cc): ids/parents
    [T, B, beam] -> full sequences via reverse backtrack."""
    ids, parents = ins["Ids"][0], ins["Parents"][0].astype(jnp.int32)
    T, B, K = ids.shape
    b_idx = jnp.arange(B)[:, None]

    def step(beam, t):
        out = ids[t, b_idx, beam]
        prev = parents[t, b_idx, beam]
        return prev, out

    k0 = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None], (B, K))
    _, outs = lax.scan(step, k0, jnp.arange(T - 1, -1, -1))
    return {"Out": [outs[::-1]]}


# ---------------------------------------------------------------------------
# batch 2: 3-D conv, RNN cells, misc vision/sequence extras
# ---------------------------------------------------------------------------


@register_op("conv3d", inputs=["Input", "Filter"], outputs=["Output"])
def _conv3d(ctx, op, ins):
    """NCDHW conv (conv3d variant of conv_op.cc); computed in NDHWC
    internally like the 2-D emitters."""
    x, w = ins["Input"][0], ins["Filter"][0]

    def trip(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    strides = trip(op.attr("strides", [1, 1, 1]))
    pads = [(p, p) for p in trip(op.attr("paddings", [0, 0, 0]))]
    dil = trip(op.attr("dilations", [1, 1, 1]))
    g = op.attr("groups", 1) or 1
    out = lax.conv_general_dilated(
        jnp.transpose(x, (0, 2, 3, 4, 1)),
        jnp.transpose(w, (2, 3, 4, 1, 0)),
        window_strides=strides,
        padding=pads,
        rhs_dilation=dil,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        feature_group_count=g,
    )
    return {"Output": [jnp.transpose(out, (0, 4, 1, 2, 3))]}


@register_op(
    "gru_unit",
    inputs=["Input", "HiddenPrev", "Weight", "Bias"],
    outputs=["Gate", "ResetHiddenPrev", "Hidden"],
)
def _gru_unit(ctx, op, ins):
    """Single GRU step (gru_unit_op.cc): Input [B, 3D] = x projections,
    Weight [D, 3D] (update/reset gates in the first 2D columns, candidate
    in the last D), gate_activation sigmoid, activation tanh."""
    x = ins["Input"][0]
    hp = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    b = (
        ins["Bias"][0]
        if ins.get("Bias") and ins["Bias"][0] is not None
        else jnp.zeros((x.shape[-1],), x.dtype)
    )
    d = hp.shape[-1]
    g = x + b.reshape(1, -1)
    uh = hp @ w[:, : 2 * d]
    u = jax.nn.sigmoid(g[:, :d] + uh[:, :d])
    r = jax.nn.sigmoid(g[:, d:2 * d] + uh[:, d:])
    rh = r * hp
    c = jnp.tanh(g[:, 2 * d:] + rh @ w[:, 2 * d:])
    h = u * c + (1.0 - u) * hp
    gate = jnp.concatenate([u, r, c], axis=-1)
    return {"Gate": [gate], "ResetHiddenPrev": [rh], "Hidden": [h]}


@register_op(
    "lstm_unit", inputs=["X", "C_prev"], outputs=["C", "H"]
)
def _lstm_unit(ctx, op, ins):
    """Single LSTM cell step (lstm_unit_op.cc): X [B, 4D] pre-activations
    in i,f,c,o order; forget_bias added to f."""
    x, c_prev = ins["X"][0], ins["C_prev"][0]
    d = c_prev.shape[-1]
    fb = float(op.attr("forget_bias", 0.0))
    i = jax.nn.sigmoid(x[:, :d])
    f = jax.nn.sigmoid(x[:, d:2 * d] + fb)
    g = jnp.tanh(x[:, 2 * d:3 * d])
    o = jax.nn.sigmoid(x[:, 3 * d:])
    c = f * c_prev + i * g
    return {"C": [c], "H": [o * jnp.tanh(c)]}


@register_op("bilinear_tensor_product", inputs=["X", "Y", "Weight", "Bias"],
             outputs=["Out"])
def _bilinear_tensor_product(ctx, op, ins):
    """out[b, k] = x[b] @ W[k] @ y[b] + bias (bilinear_tensor_product_op.cc)."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        out = out + ins["Bias"][0].reshape(1, -1)
    return {"Out": [out]}


@register_op("pad_constant_like", inputs=["X", "Y"], outputs=["Out"])
def _pad_constant_like(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    val = float(op.attr("pad_value", 0.0))
    pads = [(0, xa - ya) for xa, ya in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads, constant_values=val)]}


@register_op("mean_iou", inputs=["Predictions", "Labels"],
             outputs=["OutMeanIou", "OutWrong", "OutCorrect"],
             differentiable=False)
def _mean_iou(ctx, op, ins):
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    lab = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    n = int(op.attr("num_classes"))
    oh_p = jax.nn.one_hot(pred, n, dtype=jnp.float32)
    oh_l = jax.nn.one_hot(lab, n, dtype=jnp.float32)
    inter = jnp.sum(oh_p * oh_l, axis=0)
    union = jnp.sum(oh_p, axis=0) + jnp.sum(oh_l, axis=0) - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    wrong = jnp.sum(oh_p, axis=0) - inter
    return {
        "OutMeanIou": [miou.astype(jnp.float32)],
        "OutWrong": [wrong.astype(jnp.int32)],
        "OutCorrect": [inter.astype(jnp.int32)],
    }


@register_op("temporal_shift", inputs=["X"], outputs=["Out"])
def _temporal_shift(ctx, op, ins):
    """TSM shift (temporal_shift_op.cc): x [N*T, C, H, W]; first C/4
    channels shift t-1, next C/4 shift t+1, rest stay."""
    x = ins["X"][0]
    t = int(op.attr("seg_num"))
    ratio = float(op.attr("shift_ratio", 0.25))
    nt, c, h, w = x.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    xr = x.reshape(n, t, c, h, w)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, :c1]), xr[:, :-1, :c1]], axis=1
    )
    bwd = jnp.concatenate(
        [xr[:, 1:, c1:c2], jnp.zeros_like(xr[:, :1, c1:c2])], axis=1
    )
    out = jnp.concatenate([fwd, bwd, xr[:, :, c2:]], axis=2)
    return {"Out": [out.reshape(nt, c, h, w)]}


@register_op("space_to_depth", inputs=["X"], outputs=["Out"])
def _space_to_depth(ctx, op, ins):
    x = ins["X"][0]
    bs = int(op.attr("blocksize"))
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": [x.reshape(n, c * bs * bs, h // bs, w // bs)]}


@register_op("shuffle_channel", inputs=["X"], outputs=["Out"])
def _shuffle_channel(ctx, op, ins):
    x = ins["X"][0]
    g = int(op.attr("group", 1))
    n, c, h, w = x.shape
    return {
        "Out": [
            x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4)
            .reshape(n, c, h, w)
        ]
    }


@register_op("add_position_encoding", inputs=["X"], outputs=["Out"])
def _add_position_encoding(ctx, op, ins):
    """Sinusoidal position encoding added in place
    (add_position_encoding_op.cc): out = alpha*x + beta*pe."""
    x = ins["X"][0]
    alpha = float(op.attr("alpha", 1.0))
    beta = float(op.attr("beta", 1.0))
    b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    pe = jnp.concatenate(
        [jnp.sin(pos / div), jnp.cos(pos / div)], axis=-1
    )
    return {"Out": [alpha * x + beta * pe[None].astype(x.dtype)]}


@register_op("squared_l2_norm", inputs=["X"], outputs=["Out"])
def _squared_l2_norm(ctx, op, ins):
    x = ins["X"][0]
    return {"Out": [jnp.sum(x * x)]}


@register_op("cvm", inputs=["X", "CVM"], outputs=["Y"])
def _cvm(ctx, op, ins):
    """Click-value model feature adjust (cvm_op.cc): the first two columns
    are show/click counts; use_cvm=True keeps log-adjusted counts,
    False drops them."""
    x = ins["X"][0]
    use_cvm = bool(op.attr("use_cvm", True))
    show = jnp.log(x[:, :1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - show
    if use_cvm:
        return {"Y": [jnp.concatenate([show, click, x[:, 2:]], axis=1)]}
    return {"Y": [x[:, 2:]]}


@register_op("einsum", inputs=["Operands"], outputs=["Out"])
def _einsum(ctx, op, ins):
    """paddle.einsum (2.0 namespace; XLA contracts directly on the MXU)."""
    return {"Out": [jnp.einsum(op.attr("equation"), *ins["Operands"])]}
