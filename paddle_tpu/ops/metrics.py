"""Metric ops (reference: operators/metrics/ — accuracy_op.cc, auc_op.cc)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.registry import register_op


@register_op(
    "accuracy",
    inputs=["Out", "Indices", "Label"],
    outputs=["Accuracy", "Correct", "Total"],
    differentiable=False,
)
def _accuracy(ctx, op, ins):
    idx, label = ins["Indices"][0], ins["Label"][0]
    if label.ndim == 1:
        label = label[:, None]
    hit = jnp.any(idx == label.astype(idx.dtype), axis=-1)
    n = idx.shape[0]
    correct = jnp.sum(hit.astype(np.int32))
    return {
        "Accuracy": [(correct.astype(np.float32) / n).reshape([1])],
        "Correct": [correct.reshape([1])],
        "Total": [jnp.full([1], n, dtype=np.int32)],
    }


@register_op(
    "auc",
    inputs=["Predict", "Label", "StatPos", "StatNeg"],
    outputs=["AUC", "StatPosOut", "StatNegOut"],
    differentiable=False,
)
def _auc(ctx, op, ins):
    pred, label = ins["Predict"][0], ins["Label"][0]
    stat_pos, stat_neg = ins["StatPos"][0], ins["StatNeg"][0]
    num_thresholds = op.attr("num_thresholds", 4095)
    if label.ndim == 2:
        label = label[:, 0]
    pos_prob = pred[:, -1] if pred.ndim == 2 else pred
    bucket = jnp.clip(
        (pos_prob * num_thresholds).astype(np.int64), 0, num_thresholds
    )
    is_pos = (label > 0).astype(stat_pos.dtype)
    pos_out = stat_pos.at[bucket].add(is_pos)
    neg_out = stat_neg.at[bucket].add(1 - is_pos)
    # trapezoid rule over the ROC curve built from bucket counts
    tp = jnp.cumsum(pos_out[::-1])[::-1]
    fp = jnp.cumsum(neg_out[::-1])[::-1]
    tot_pos, tot_neg = tp[0], fp[0]
    tp_next = jnp.concatenate([tp[1:], jnp.zeros([1], tp.dtype)])
    fp_next = jnp.concatenate([fp[1:], jnp.zeros([1], fp.dtype)])
    area = jnp.sum((fp - fp_next) * (tp + tp_next) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg), 0.0)
    return {
        "AUC": [auc.reshape([1]).astype(np.float64)],
        "StatPosOut": [pos_out],
        "StatNegOut": [neg_out],
    }
