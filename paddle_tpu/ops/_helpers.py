"""Shared emitter helpers (broadcasting rules, registration sugar).

Replaces the reference's operators/math library role for elementwise ops
(operators/elementwise/ broadcast rules): fluid's `axis` broadcast semantics
are implemented once here and shared by all elementwise emitters.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.registry import register_op


def fluid_broadcast(x, y, axis):
    """fluid elementwise broadcasting: y's shape aligns to x starting at `axis`
    (elementwise_op_function.h in the reference). axis=-1 means numpy rules /
    trailing alignment."""
    if x.ndim == y.ndim or y.ndim == 0:
        return x, y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    # squeeze trailing size-1 dims fluid allows on y (e.g. bias [C] vs [C,1,1])
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return x, y.reshape(new_shape)


def register_elementwise(op_type, fn):
    @register_op(op_type, inputs=["X", "Y"], outputs=["Out"])
    def emit(ctx, op, ins):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = fluid_broadcast(x, y, op.attr("axis", -1))
        return {"Out": [fn(x, y)]}

    return emit


def register_unary(op_type, fn, differentiable=True):
    @register_op(
        op_type, inputs=["X"], outputs=["Out"], differentiable=differentiable
    )
    def emit(ctx, op, ins):
        return {"Out": [fn(ins["X"][0], op.attrs)]}

    return emit


def reduce_axes(attrs, ndim):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if dim is None:
        return None
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % ndim if ndim else 0 for d in dim)


def register_reduce(op_type, fn):
    @register_op(op_type, inputs=["X"], outputs=["Out"])
    def emit(ctx, op, ins):
        x = ins["X"][0]
        axes = reduce_axes(op.attrs, x.ndim)
        keep = op.attr("keep_dim", False)
        out = fn(x, axis=axes, keepdims=keep)
        if out.ndim == 0:
            out = out.reshape([1])
        return {"Out": [out]}

    return emit


def stable_sigmoid_ce(x, z):
    """Numerically stable sigmoid cross-entropy from logits:
    max(x,0) - x*z + log(1+exp(-|x|)) — shared by the
    sigmoid_cross_entropy_with_logits emitter and yolov3_loss."""
    import jax

    return jnp.maximum(x, 0) - x * z + jax.nn.softplus(-jnp.abs(x))


def op_key(ctx, op):
    """Per-op RNG key: explicit seed attr wins (reference per-op seed
    semantics), else the counter-based ctx stream. Single definition —
    random.py / loss_ext.py / ctr_ops.py all share it."""
    import jax

    seed = op.attr("seed", 0)
    if seed:
        return jax.random.key(seed + op.uid)
    return ctx.key_for(op.uid, op.type)


def hash_mix(key_u32, num_hash):
    """Deterministic multiply-xorshift integer mix: num_hash parallel
    hashes of a uint32 key tensor -> [..., num_hash] uint32. Shared by the
    hash and pyramid_hash emitters (the reference links xxhash; this mix
    has the same contract — fixed, well-distributed, vectorizable)."""
    import numpy as np

    consts = jnp.asarray(
        np.array([0x9E3779B1 + 2 * k + 1 for k in range(num_hash)],
                 dtype=np.uint32)
    )
    h = key_u32[..., None] * consts
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x85EBCA77)
    h = h ^ (h >> 13)
    return h
