"""Runtime detection-padding observability.

The batched detection ops keep shapes static with fixed per-image RoI
caps + validity masks (ops/detection.py, ops/detection_ext.py), so the
interesting runtime quantity is how much of the cap is DEAD padding —
compute spent on masked-out slots. Live counts (``RoisNum`` outputs)
only exist on the device inside the jit trace, so recording happens
host-side on fetched values: bench legs and tests fetch the counts and
call :func:`record_roi_stats`.

Exports through the existing observability registry (visible in
``tools/stats_report.py``):

* ``detection.rois_per_image`` histogram — live rois per image,
  count-valued buckets;
* ``detection.padding_waste`` gauge — fraction of RoI-cap slots that
  were masked out in the latest recorded batch (0 = cap fully used);
* ``detection.roi_batches_recorded`` counter.

Trace-time op counters (``detection.<op>.instantiations`` /
``.batched_instantiations``) live in ops/detection.py `_tally`.
"""

from __future__ import annotations

import numpy as np

from ..observability import metrics

# count-valued bucket edges (rois per image), not latencies
ROI_COUNT_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def record_roi_stats(rois_num, cap):
    """Record live-roi counts for one batch: ``rois_num`` is the fetched
    per-image live count vector (any array-like, e.g. the ``RoisNum``
    output of batched generate_proposal_labels), ``cap`` the static
    per-image RoI cap those counts are padded to. Returns the
    padding-waste fraction recorded to the gauge."""
    arr = np.asarray(rois_num).reshape(-1).astype(np.int64)
    for n in arr:
        metrics.observe(
            "detection.rois_per_image", float(n), buckets=ROI_COUNT_BUCKETS
        )
    total = int(cap) * max(len(arr), 1)
    waste = 1.0 - float(arr.sum()) / total if total else 0.0
    metrics.set_gauge("detection.padding_waste", waste)
    metrics.add("detection.roi_batches_recorded")
    return waste
