"""Tensor manipulation ops: reshape/transpose/concat/split/gather/..., creation
ops (fill_constant), cast, search ops (argmax/top_k), index ops.

Reference parity: operators/reshape_op.cc, transpose_op.cc, concat_op.cc,
split_op.cc, gather_op.cc, scatter_op.cc, cast_op.cc, fill_constant_op.cc,
arg_max_op, top_k_op, expand_op, slice_op, stack_op, one_hot_op, cumsum_op,
where/masked select family — all static-shape XLA emitters.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtypes import to_numpy_dtype
from ..framework.registry import register_op, BATCH_SENTINEL


def _resolve_shape(shape, x_shape):
    """fluid reshape semantics: 0 copies the input dim; a single -1 infers."""
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(x_shape[i])
        else:
            out.append(int(s))
    return out


@register_op("reshape2", inputs=["X"], outputs=["Out", "XShape"])
def _reshape2(ctx, op, ins):
    x = ins["X"][0]
    shape = list(_resolve_shape(op.attr("shape"), x.shape))
    M = getattr(ctx, "batch_divisor", 1)
    if (
        M > 1
        and -1 not in shape
        and shape
        and shape[0] % M == 0
        and math.prod(shape) == math.prod(x.shape) * M
    ):
        # inside a pipeline stage: graph-build shapes are full-batch but the
        # runtime tensor is a microbatch (1/M); shrink the leading dim.
        # Everywhere else a size mismatch still fails inside jnp.reshape.
        shape[0] //= M
    return {"Out": [jnp.reshape(x, shape)], "XShape": []}


@register_op("flatten2", inputs=["X"], outputs=["Out", "XShape"])
def _flatten2(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", 1)
    lead = math.prod(x.shape[:axis]) if axis > 0 else 1
    return {"Out": [jnp.reshape(x, (lead, -1))], "XShape": []}


@register_op("transpose2", inputs=["X"], outputs=["Out", "XShape"])
def _transpose2(ctx, op, ins):
    x = ins["X"][0]
    return {"Out": [jnp.transpose(x, op.attr("axis"))], "XShape": []}


@register_op("concat", inputs=["X"], outputs=["Out"])
def _concat(ctx, op, ins):
    xs = [x for x in ins["X"] if x is not None]
    return {"Out": [jnp.concatenate(xs, axis=op.attr("axis", 0))]}


@register_op("split", inputs=["X"], outputs=["Out"])
def _split(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", 0)
    num = op.attr("num", 0)
    sections = op.attr("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num, axis=axis)
    return {"Out": parts}


@register_op("stack", inputs=["X"], outputs=["Y"])
def _stack(ctx, op, ins):
    xs = [x for x in ins["X"] if x is not None]
    return {"Y": [jnp.stack(xs, axis=op.attr("axis", 0))]}


@register_op("unstack", inputs=["X"], outputs=["Y"])
def _unstack(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(p, axis=axis) for p in jnp.split(x, n, axis=axis)]}


@register_op("squeeze2", inputs=["X"], outputs=["Out", "XShape"])
def _squeeze2(ctx, op, ins):
    x = ins["X"][0]
    axes = op.attr("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    return {"Out": [out], "XShape": []}


@register_op("unsqueeze2", inputs=["X"], outputs=["Out", "XShape"])
def _unsqueeze2(ctx, op, ins):
    x = ins["X"][0]
    out = x
    for a in sorted(op.attr("axes")):
        out = jnp.expand_dims(out, axis=a)
    return {"Out": [out], "XShape": []}


@register_op("slice", inputs=["Input"], outputs=["Out"])
def _slice(ctx, op, ins):
    x = ins["Input"][0]
    axes = op.attr("axes")
    starts = op.attr("starts")
    ends = op.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    for a in sorted(op.attr("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, axis=a)
    return {"Out": [out]}


@register_op("strided_slice", inputs=["Input"], outputs=["Out"])
def _strided_slice(ctx, op, ins):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(
        op.attr("axes"), op.attr("starts"), op.attr("ends"), op.attr("strides")
    ):
        idx[a] = slice(s, e, st)
    return {"Out": [x[tuple(idx)]]}


@register_op("expand", inputs=["X"], outputs=["Out"])
def _expand(ctx, op, ins):
    x = ins["X"][0]
    times = op.attr("expand_times")
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_as", inputs=["X", "target_tensor"], outputs=["Out"])
def _expand_as(ctx, op, ins):
    x, t = ins["X"][0], ins["target_tensor"][0]
    reps = [ts // xs for ts, xs in zip(t.shape, x.shape)]
    return {"Out": [jnp.tile(x, reps)]}


@register_op("tile", inputs=["X"], outputs=["Out"])
def _tile(ctx, op, ins):
    return {"Out": [jnp.tile(ins["X"][0], op.attr("repeat_times"))]}


@register_op("gather", inputs=["X", "Index"], outputs=["Out"])
def _gather(ctx, op, ins):
    x, idx = ins["X"][0], ins["Index"][0]
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]
    return {"Out": [jnp.take(x, idx, axis=op.attr("axis", 0))]}


@register_op("gather_nd", inputs=["X", "Index"], outputs=["Out"])
def _gather_nd(ctx, op, ins):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


@register_op("scatter", inputs=["X", "Ids", "Updates"], outputs=["Out"])
def _scatter(ctx, op, ins):
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    if ids.ndim == 2 and ids.shape[1] == 1:
        ids = ids[:, 0]
    if op.attr("overwrite", True):
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].add(upd)
    return {"Out": [out]}


@register_op("cast", inputs=["X"], outputs=["Out"])
def _cast(ctx, op, ins):
    return {"Out": [ins["X"][0].astype(to_numpy_dtype(op.attr("out_dtype")))]}


@register_op("fill_constant", inputs=[], outputs=["Out"])
def _fill_constant(ctx, op, ins):
    shape = list(op.attr("shape"))
    if any(s == -1 for s in shape):
        if ctx.abstract:  # shape-inference pass only
            shape = [BATCH_SENTINEL if s == -1 else s for s in shape]
        else:
            raise ValueError(
                "fill_constant with -1 (batch) dims cannot execute; use "
                "fill_any_like / fill_constant_batch_size_like instead"
            )
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    return {"Out": [jnp.full(shape, op.attr("value", 0.0), dtype=dtype)]}


@register_op("fill_any_like", inputs=["X"], outputs=["Out"], differentiable=False)
def _fill_any_like(ctx, op, ins):
    x = ins["X"][0]
    dtype = op.attr("dtype", None)
    dtype = x.dtype if dtype is None else to_numpy_dtype(dtype)
    return {"Out": [jnp.full(x.shape, op.attr("value", 0.0), dtype=dtype)]}


@register_op("fill_constant_batch_size_like", inputs=["Input"], outputs=["Out"])
def _fill_constant_bsl(ctx, op, ins):
    x = ins["Input"][0]
    shape = list(op.attr("shape"))
    in_idx = op.attr("input_dim_idx", 0)
    out_idx = op.attr("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    return {"Out": [jnp.full(shape, op.attr("value", 0.0), dtype=dtype)]}


@register_op("assign", inputs=["X"], outputs=["Out"])
def _assign(ctx, op, ins):
    return {"Out": [ins["X"][0]]}


@register_op("assign_value", inputs=[], outputs=["Out"])
def _assign_value(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    vals = np.array(op.attr("values"), dtype=dtype).reshape(op.attr("shape"))
    return {"Out": [jnp.asarray(vals)]}


@register_op("shape", inputs=["Input"], outputs=["Out"], differentiable=False)
def _shape(ctx, op, ins):
    return {"Out": [jnp.array(ins["Input"][0].shape, dtype=np.int32)]}


@register_op("arg_max", inputs=["X"], outputs=["Out"], differentiable=False)
def _arg_max(ctx, op, ins):
    x = ins["X"][0]
    return {"Out": [jnp.argmax(x, axis=op.attr("axis", -1)).astype(np.int64)]}


@register_op("arg_min", inputs=["X"], outputs=["Out"], differentiable=False)
def _arg_min(ctx, op, ins):
    x = ins["X"][0]
    return {"Out": [jnp.argmin(x, axis=op.attr("axis", -1)).astype(np.int64)]}


@register_op("argsort", inputs=["X"], outputs=["Out", "Indices"], differentiable=False)
def _argsort(ctx, op, ins):
    x = ins["X"][0]
    axis = op.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    if op.attr("descending", False):
        idx = jnp.flip(idx, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(np.int64)]}


@register_op("top_k", inputs=["X"], outputs=["Out", "Indices"], differentiable=False)
def _top_k(ctx, op, ins):
    x = ins["X"][0]
    vals, idx = jax.lax.top_k(x, op.attr("k", 1))
    return {"Out": [vals], "Indices": [idx.astype(np.int64)]}


@register_op("one_hot_v2", inputs=["X"], outputs=["Out"], differentiable=False)
def _one_hot(ctx, op, ins):
    x = ins["X"][0]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return {"Out": [jax.nn.one_hot(x, op.attr("depth"), dtype=np.float32)]}


@register_op("cumsum", inputs=["X"], outputs=["Out"])
def _cumsum(ctx, op, ins):
    x = ins["X"][0]
    if op.attr("flatten", False):
        x = x.reshape(-1)
    axis = op.attr("axis", -1)
    if op.attr("reverse", False):
        x = jnp.flip(x, axis=axis)
    if op.attr("exclusive", False):
        out = jnp.cumsum(x, axis=axis) - x
    else:
        out = jnp.cumsum(x, axis=axis)
    if op.attr("reverse", False):
        out = jnp.flip(out, axis=axis)
    return {"Out": [out]}


@register_op("where_index", inputs=["Condition"], outputs=["Out"], differentiable=False)
def _where_index(ctx, op, ins):
    # dynamic-size output: not representable in XLA static shapes; tests only
    cond = np.asarray(ins["Condition"][0])
    return {"Out": [jnp.asarray(np.argwhere(cond).astype(np.int64))]}


@register_op("where", inputs=["Condition", "X", "Y"], outputs=["Out"])
def _where(ctx, op, ins):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register_op("range", inputs=[], outputs=["Out"], differentiable=False)
def _range(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "int64"))
    return {
        "Out": [
            jnp.arange(
                op.attr("start", 0), op.attr("end"), op.attr("step", 1), dtype=dtype
            )
        ]
    }


@register_op("linspace", inputs=[], outputs=["Out"])
def _linspace(ctx, op, ins):
    dtype = to_numpy_dtype(op.attr("dtype", "float32"))
    return {
        "Out": [
            jnp.linspace(
                op.attr("start"), op.attr("stop"), op.attr("num"), dtype=dtype
            )
        ]
    }


@register_op("flip", inputs=["X"], outputs=["Out"])
def _flip(ctx, op, ins):
    return {"Out": [jnp.flip(ins["X"][0], axis=op.attr("axis"))]}


@register_op("roll", inputs=["X"], outputs=["Out"])
def _roll(ctx, op, ins):
    return {
        "Out": [jnp.roll(ins["X"][0], op.attr("shifts"), axis=op.attr("axis", None))]
    }


@register_op("pad", inputs=["X"], outputs=["Out"])
def _pad(ctx, op, ins):
    x = ins["X"][0]
    p = op.attr("paddings")  # flat [before0, after0, before1, after1, ...]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {
        "Out": [jnp.pad(x, pairs, constant_values=op.attr("pad_value", 0.0))]
    }


@register_op("take_along_axis", inputs=["Input", "Index"], outputs=["Result"])
def _take_along_axis(ctx, op, ins):
    return {
        "Result": [
            jnp.take_along_axis(
                ins["Input"][0], ins["Index"][0], axis=op.attr("Axis", 0)
            )
        ]
    }


@register_op("increment", inputs=["X"], outputs=["Out"], differentiable=False)
def _increment(ctx, op, ins):
    """In-place counter bump (reference operators/increment_op.cc). Emitting
    Out under the same variable name as X makes the executor's persistable
    write-back + donation update the counter buffer in place."""
    x = ins["X"][0]
    return {"Out": [x + jnp.asarray(op.attr("step", 1.0), dtype=x.dtype)]}


@register_op(
    "fake_quantize_dequantize_abs_max",
    inputs=["X"],
    outputs=["Out", "OutScale"],
)
def _fake_quant_dequant_abs_max(ctx, op, ins):
    """QAT fake quantization (reference operators/fake_quantize_op.cc):
    quantize to `bit_length` levels by abs-max scale, dequantize back —
    forward sees quantization error, backward is straight-through (the
    generic __vjp__ differentiates round() as 0 but the scale path keeps
    x's grad: use x + stop_gradient(q - x))."""
    x = ins["X"][0]
    bits = op.attr("bit_length", 8)
    bound = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(x)) + 1e-9
    q = jnp.round(x / scale * bound) * scale / bound
    # straight-through estimator: identity gradient
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [scale.reshape([1])]}


@register_op(
    "fake_channel_wise_quantize_dequantize_abs_max",
    inputs=["X"],
    outputs=["Out", "OutScale"],
)
def _fake_quant_channelwise(ctx, op, ins):
    x = ins["X"][0]
    bits = op.attr("bit_length", 8)
    axis = op.attr("quant_axis", 0)
    bound = float(2 ** (bits - 1) - 1)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True) + 1e-9
    q = jnp.round(x / scale * bound) * scale / bound
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [scale.reshape(-1)]}


@register_op("tril_triu", inputs=["X"], outputs=["Out"])
def _tril_triu(ctx, op, ins):
    """Lower/upper triangle (reference operators/tril_triu_op.cc)."""
    x = ins["X"][0]
    diagonal = int(op.attr("diagonal", 0))
    lower = bool(op.attr("lower", True))
    fn = jnp.tril if lower else jnp.triu
    return {"Out": [fn(x, k=diagonal)]}
