"""Detection ops (reference operators/detection/, ~16.7k LoC:
iou_similarity_op, box_coder_op, prior_box_op, yolo_box_op,
multiclass_nms_op, roi_align_op ...).

TPU-native re-design: boxes are dense [_, 4] tensors; NMS — whose reference
kernel emits a VARIABLE number of boxes via LoD — returns a FIXED keep_top_k
set padded with -1 labels plus a validity count, so the whole detection
head stays inside one static-shape XLA computation (the standard TPU
object-detection formulation). Suppression uses the O(k^2) masked matrix
form on the VPU instead of the reference's sequential CPU loop.

Cross-image batching (r6): the roi family accepts a leading batch dim —
ROIs [B, R, 4] against X [B, C, H, W] runs the single-image kernel
vmapped over images (fixed per-image RoI cap R, padded rows are
degenerate boxes the consumers mask) — so a B-image detection step is ONE
wide program instead of B unrolled one-image graphs (the BASELINE.md r5
Mask R-CNN limiter: ~50-58 ms/image of small-op bookkeeping that one
wide op family amortizes; same conclusion as the XLA fusion analysis,
arXiv:2301.13062, that many small fusions lose to one wide one).
`generate_proposals` and `multiclass_nms` were already rank-lifted over
[N, ...]; ops/detection_ext.py lifts the assignment/label family the
same way.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from ..observability import metrics as _metrics


def _tally(ctx, name, batched):
    """Trace-time detection.* op counters: one bump per emitter
    instantiation (a program trace, including __vjp__ replays), NOT per
    step — runtime values never leave the device inside a jit trace.
    Skipped during abstract shape replay so the PR-5 verifier doesn't
    inflate the counts."""
    if getattr(ctx, "abstract", False):
        return
    _metrics.add(f"detection.{name}.instantiations")
    if batched:
        _metrics.add(f"detection.{name}.batched_instantiations")


def _iou_matrix(a, b):
    """[N,4] x [M,4] -> [N,M] IoU (xmin,ymin,xmax,ymax)."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0
    )
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0
    )
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("iou_similarity", inputs=["X", "Y"], outputs=["Out"])
def _iou_similarity(ctx, op, ins):
    return {"Out": [_iou_matrix(ins["X"][0], ins["Y"][0])]}


@register_op("box_coder", inputs=["PriorBox", "PriorBoxVar", "TargetBox"],
             outputs=["OutputBox"])
def _box_coder(ctx, op, ins):
    """encode_center_size / decode_center_size (box_coder_op.cc)."""
    prior = ins["PriorBox"][0]  # [M, 4]
    pvar = (
        ins["PriorBoxVar"][0]
        if ins.get("PriorBoxVar") and ins["PriorBoxVar"][0] is not None
        else None
    )
    target = ins["TargetBox"][0]
    code_type = op.attr("code_type", "encode_center_size")
    norm = op.attr("box_normalized", True)
    off = 0.0 if norm else 1.0

    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph

    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        out = jnp.stack(
            [
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :]),
            ],
            axis=-1,
        )  # [N, M, 4]
        if pvar is not None:
            out = out / pvar[None, :, :]
        return {"OutputBox": [out]}

    # decode: target [N, M, 4] deltas (or [N,4] broadcast against priors)
    t = target if target.ndim == 3 else target[:, None, :]
    if pvar is not None:
        t = t * pvar[None, :, :]
    dcx = t[..., 0] * pw[None, :] + pcx[None, :]
    dcy = t[..., 1] * ph[None, :] + pcy[None, :]
    dw = jnp.exp(t[..., 2]) * pw[None, :]
    dh = jnp.exp(t[..., 3]) * ph[None, :]
    out = jnp.stack(
        [
            dcx - 0.5 * dw,
            dcy - 0.5 * dh,
            dcx + 0.5 * dw - off,
            dcy + 0.5 * dh - off,
        ],
        axis=-1,
    )
    return {"OutputBox": [out]}


@register_op("prior_box", inputs=["Input", "Image"],
             outputs=["Boxes", "Variances"], differentiable=False)
def _prior_box(ctx, op, ins):
    """SSD prior boxes per feature-map cell (prior_box_op.cc)."""
    feat = ins["Input"][0]  # [B, C, H, W]
    img = ins["Image"][0]  # [B, C, IH, IW]
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in op.attr("min_sizes")]
    max_sizes = [float(s) for s in op.attr("max_sizes", [])]
    ars = [1.0]
    for a in op.attr("aspect_ratios", [1.0]):
        a = float(a)
        if not any(abs(a - e) < 1e-6 for e in ars):
            ars.append(a)
            if op.attr("flip", True):
                ars.append(1.0 / a)
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = op.attr("clip", True)
    step_w = op.attr("step_w", 0.0) or IW / W
    step_h = op.attr("step_h", 0.0) or IH / H
    offset = op.attr("offset", 0.5)

    whs = []
    for ms in min_sizes:
        for a in ars:
            whs.append((ms * (a ** 0.5), ms / (a ** 0.5)))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
    whs = jnp.asarray(whs, jnp.float32)  # [P, 2]

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    centers = jnp.stack([cxg, cyg], -1)[..., None, :]  # [H, W, 1, 2]
    half = whs[None, None, :, :] / 2.0
    mins = (centers - half) / jnp.asarray([IW, IH], jnp.float32)
    maxs = (centers + half) / jnp.asarray([IW, IH], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], -1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), boxes.shape
    )
    return {"Boxes": [boxes], "Variances": [var]}


@register_op("yolo_box", inputs=["X", "ImgSize"], outputs=["Boxes", "Scores"],
             differentiable=False)
def _yolo_box(ctx, op, ins):
    """Decode YOLOv3 head output (yolo_box_op.cc): X [B, A*(5+C), H, W] ->
    boxes [B, A*H*W, 4] + scores [B, A*H*W, C]."""
    x = ins["X"][0]
    img_size = ins["ImgSize"][0]  # [B, 2] (h, w)
    anchors = [int(a) for a in op.attr("anchors")]
    class_num = op.attr("class_num")
    conf_thresh = op.attr("conf_thresh", 0.01)
    downsample = op.attr("downsample_ratio", 32)
    B, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)

    x = x.reshape(B, A, 5 + class_num, H, W)
    tx, ty = jax.nn.sigmoid(x[:, :, 0]), jax.nn.sigmoid(x[:, :, 1])
    tw, th = x[:, :, 2], x[:, :, 3]
    conf = jax.nn.sigmoid(x[:, :, 4])  # [B, A, H, W]
    cls = jax.nn.sigmoid(x[:, :, 5:])  # [B, A, C, H, W]

    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    cx = (tx + gx) / W
    cy = (ty + gy) / H
    input_size = jnp.asarray([downsample * H, downsample * W], jnp.float32)
    bw = jnp.exp(tw) * an[None, :, 0, None, None] / input_size[1]
    bh = jnp.exp(th) * an[None, :, 1, None, None] / input_size[0]

    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    x0 = (cx - bw / 2) * imw
    y0 = (cy - bh / 2) * imh
    x1 = (cx + bw / 2) * imw
    y1 = (cy + bh / 2) * imh
    boxes = jnp.stack([x0, y0, x1, y1], -1).reshape(B, A * H * W, 4)
    score = conf[:, :, None] * cls  # [B, A, C, H, W]
    keep = (conf > conf_thresh).astype(score.dtype)[:, :, None]
    score = (score * keep).transpose(0, 1, 3, 4, 2).reshape(
        B, A * H * W, class_num
    )
    return {"Boxes": [boxes], "Scores": [score]}


def _greedy_nms(boxes_k, keep_pred, nms_thresh, block=64):
    """alive mask over rank-ordered boxes [k, 4]: box i survives iff
    keep_pred[i] and it overlaps no surviving higher-ranked box — the
    sequential suppression loop of multiclass_nms_op.cc.

    Blocked for TPU (r5): a per-box lax.scan costs k sequential device
    iterations of tiny work (k=512 in the RPN — measured ~9 ms/step of
    while-loop time at b=1). Instead, scan over k/block blocks: earlier
    blocks' final alive states suppress the whole block at once
    (vectorized), and the intra-block recurrence unrolls into `block`
    STATIC vector steps (no dynamic slicing, fully pipelined). Semantics
    are identical; the dynamic iteration count drops k/block-fold."""
    k = boxes_k.shape[0]
    iou = _iou_matrix(boxes_k, boxes_k)
    sup_mat = iou > nms_thresh  # [k, k]
    if k <= block:
        # single block: the whole recurrence is static
        alive = jnp.zeros(k, bool)
        for i in range(k):
            sup = jnp.any(alive & sup_mat[i])
            alive = alive.at[i].set(~sup & keep_pred[i])
        return alive

    nb = -(-k // block)
    pad = nb * block - k
    if pad:
        sup_mat = jnp.pad(sup_mat, ((0, pad), (0, pad)))
        keep_pred = jnp.pad(keep_pred, (0, pad))  # padded rows: keep=False
    kp = nb * block

    def block_step(alive, bi):
        base = bi * block
        rows = lax.dynamic_slice(sup_mat, (base, 0), (block, kp))
        keep_blk = lax.dynamic_slice(keep_pred, (base,), (block,))
        # suppression by already-resolved earlier boxes: alive is True
        # only on the processed prefix, so no j<i mask is needed
        sup_prefix = jnp.any(rows & alive[None, :], axis=-1)  # [block]
        intra = lax.dynamic_slice(rows, (0, base), (block, block))
        blk_alive = jnp.zeros(block, bool)
        for i in range(block):  # static unroll: no device round-trips
            sup_i = sup_prefix[i] | jnp.any(blk_alive & intra[i])
            blk_alive = blk_alive.at[i].set(~sup_i & keep_blk[i])
        return lax.dynamic_update_slice(alive, blk_alive, (base,)), None

    alive0 = jnp.zeros(kp, bool)
    alive, _ = lax.scan(block_step, alive0, jnp.arange(nb))
    return alive[:k]


def multiclass_nms_core(boxes, scores, attrs):
    """Shared NMS core for multiclass_nms / multiclass_nms2: per class,
    greedy-suppress by IoU, then global keep_top_k by score. Returns
    (out [B, kk, 6], num [B], in_idx [B, kk]) where in_idx is the kept
    row's index into the INPUT box set (-1 for padded rows)."""
    score_thresh = attrs.get("score_threshold", 0.0)
    nms_thresh = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 64)
    keep_top_k = attrs.get("keep_top_k", 16)
    background = attrs.get("background_label", 0)
    B, C, N = scores.shape
    k = min(nms_top_k, N)
    if background is not None and 0 <= background < C:
        # reference multiclass_nms_op.cc skips c == background_label
        scores = scores.at[:, background, :].set(-jnp.inf)

    def one_class(b_boxes, c_scores):
        sc, idx = lax.top_k(c_scores, k)
        bx = b_boxes[idx]
        alive = _greedy_nms(bx, sc > score_thresh, nms_thresh)
        return sc * alive, idx

    def one_image(b_boxes, b_scores):
        cls_scores, cls_idx = jax.vmap(
            lambda cs: one_class(b_boxes, cs)
        )(b_scores)  # [C, k], [C, k]
        flat_scores = cls_scores.reshape(-1)
        flat_idx = cls_idx.reshape(-1)
        labels = jnp.repeat(jnp.arange(C), k)
        kk = min(keep_top_k, flat_scores.shape[0])
        top_sc, top_i = lax.top_k(flat_scores, kk)
        valid = top_sc > jnp.maximum(score_thresh, 0.0)
        lab = jnp.where(valid, labels[top_i], -1).astype(jnp.float32)
        src = flat_idx[top_i]
        bx = b_boxes[src]
        out = jnp.concatenate(
            [lab[:, None], top_sc[:, None], bx], axis=-1
        )
        in_idx = jnp.where(valid, src, -1).astype(jnp.int32)
        return out, valid.sum().astype(jnp.int32), in_idx

    return jax.vmap(one_image)(boxes, scores)


@register_op(
    "multiclass_nms", inputs=["BBoxes", "Scores"],
    outputs=["Out", "NmsRoisNum"], differentiable=False,
)
def _multiclass_nms(ctx, op, ins):
    """Fixed-size NMS (multiclass_nms_op.cc re-designed for static shapes):
    Out [B, keep_top_k, 6] rows [label, score, x0, y0, x1, y1], invalid
    rows label=-1; NmsRoisNum [B]."""
    _tally(ctx, "multiclass_nms", batched=ins["BBoxes"][0].shape[0] > 1)
    out, num, _ = multiclass_nms_core(
        ins["BBoxes"][0], ins["Scores"][0], op.attrs
    )
    return {"Out": [out], "NmsRoisNum": [num]}


from ._helpers import stable_sigmoid_ce as _sce  # yolov3_loss_op.h:35


@register_op(
    "yolov3_loss",
    inputs=["X", "GTBox", "GTLabel", "GTScore"],
    outputs=["Loss", "ObjectnessMask", "GTMatchMask"],
)
def _yolov3_loss(ctx, op, ins):
    """YOLOv3 training loss (behavior of detection/yolov3_loss_op.h, fully
    vectorized for XLA: the reference's per-cell/per-gt loops become
    broadcast IoU tables, gathers for the positive-sample losses, and one
    scatter for the objectness mask).

    X [N, M*(5+C), H, W] raw head output; GTBox [N, B, 4] normalized
    (cx, cy, w, h); GTLabel [N, B] int; optional GTScore [N, B] (mixup).
    Per-cell predicted boxes with IoU > ignore_thresh against any gt are
    excluded from the negative objectness loss (mask -1); each valid gt is
    assigned its best shape-IoU anchor, and when that anchor belongs to this
    head's anchor_mask the cell owes location (sigmoid-CE for tx/ty, L1 for
    tw/th, scaled by (2 - w*h) * score), class (per-class sigmoid-CE, label
    smoothing optional) and positive objectness losses. Like the reference,
    the grid is assumed square (grid_size = H everywhere). Two gts landing
    on the same cell: one objectness write wins (scatter; the reference's
    sequential loop keeps the last), while location/class losses accrue for
    both. Differentiable via the generic vjp; index/assignment computations
    are integer-valued and carry no gradient, matching the reference's
    hand-written grad kernel.
    """
    x4 = ins["X"][0]
    gt_box = ins["GTBox"][0].astype(jnp.float32)
    gt_label = ins["GTLabel"][0].astype(jnp.int32)
    gt_score = (
        ins["GTScore"][0].astype(jnp.float32)
        if ins.get("GTScore") and ins["GTScore"][0] is not None
        else jnp.ones(gt_label.shape, jnp.float32)
    )
    anchors = [int(a) for a in op.attr("anchors")]
    anchor_mask = [int(a) for a in op.attr("anchor_mask")]
    class_num = int(op.attr("class_num"))
    ignore_thresh = float(op.attr("ignore_thresh", 0.7))
    downsample = int(op.attr("downsample_ratio", 32))
    use_label_smooth = bool(op.attr("use_label_smooth", True))
    scale_xy = float(op.attr("scale_x_y", 1.0))
    bias_xy = -0.5 * (scale_xy - 1.0)

    N, _, H, W = x4.shape
    A = len(anchors) // 2
    M = len(anchor_mask)
    B = gt_box.shape[1]
    input_size = downsample * H
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    am = an[jnp.asarray(anchor_mask)]  # [M, 2]

    x = x4.astype(jnp.float32).reshape(N, M, 5 + class_num, H, W)
    gx, gy, gw, gh = (gt_box[..., i] for i in range(4))  # [N, B]
    valid = (gw > 1e-6) & (gh > 1e-6)

    if use_label_smooth:
        smooth = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - smooth, smooth
    else:
        label_pos, label_neg = 1.0, 0.0

    # ---- per-cell predicted boxes and ignore mask ----
    col = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    row = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    px = (col + jax.nn.sigmoid(x[:, :, 0]) * scale_xy + bias_xy) / H
    py = (row + jax.nn.sigmoid(x[:, :, 1]) * scale_xy + bias_xy) / H
    pw = jnp.exp(x[:, :, 2]) * am[None, :, 0, None, None] / input_size
    ph = jnp.exp(x[:, :, 3]) * am[None, :, 1, None, None] / input_size

    def overlap(c1, w1, c2, w2):
        return jnp.minimum(c1 + w1 / 2, c2 + w2 / 2) - jnp.maximum(
            c1 - w1 / 2, c2 - w2 / 2
        )

    # [N, M, H, W, B] IoU of every predicted box against every gt
    ow = overlap(px[..., None], pw[..., None],
                 gx[:, None, None, None, :], gw[:, None, None, None, :])
    oh = overlap(py[..., None], ph[..., None],
                 gy[:, None, None, None, :], gh[:, None, None, None, :])
    inter = jnp.where((ow < 0) | (oh < 0), 0.0, ow * oh)
    union = (pw * ph)[..., None] + (gw * gh)[:, None, None, None, :] - inter
    iou = jnp.where(valid[:, None, None, None, :], inter / union, 0.0)
    best_iou = jnp.max(iou, axis=-1)  # [N, M, H, W]
    obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)

    # ---- per-gt best anchor (shape-only IoU over ALL anchors) ----
    aw = an[:, 0] / input_size  # [A]
    ah = an[:, 1] / input_size
    inter_a = jnp.minimum(gw[..., None], aw) * jnp.minimum(gh[..., None], ah)
    union_a = (gw * gh)[..., None] + aw * ah - inter_a
    best_n = jnp.argmax(inter_a / union_a, axis=-1)  # [N, B]
    mask_table = -np.ones(A, np.int32)
    for i, a in enumerate(anchor_mask):
        mask_table[a] = i
    gt_match = jnp.where(valid, jnp.asarray(mask_table)[best_n], -1)

    pos = valid & (gt_match >= 0)  # [N, B]
    midx = jnp.maximum(gt_match, 0)
    gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)

    # per-gt flat cell index into [M*H*W]; one-hot row is all-zero for
    # negatives, so gathered logits are 0 there (masked out below anyway)
    K = M * H * W
    cell_idx = (midx * H + gj) * W + gi  # [N, B]
    cell_onehot = (
        jax.nn.one_hot(cell_idx, K, dtype=jnp.float32)
        * pos[..., None].astype(jnp.float32)
    )  # [N, B, K]
    x_flat = x.transpose(0, 1, 3, 4, 2).reshape(N, K, 5 + class_num)

    # ---- location loss ----
    # one-hot MATMUL gather, not advanced indexing: a data-dependent
    # gather's vjp is a scatter-add, which XLA lowers on TPU as a
    # sequential while-loop of dynamic slices — ~N*B scalar-core stalls
    # per head per step (measured: 480 slice pairs, ~50ms/step idle at
    # b=16; the MXU einsum is microseconds)
    tx = gx * H - gi.astype(jnp.float32)
    ty = gy * H - gj.astype(jnp.float32)
    best_aw = an[best_n, 0]
    best_ah = an[best_n, 1]
    tw = jnp.log(jnp.where(pos, gw * input_size / best_aw, 1.0))
    th = jnp.log(jnp.where(pos, gh * input_size / best_ah, 1.0))
    loc_scale = (2.0 - gw * gh) * gt_score
    cell = jnp.einsum("nbk,nkc->nbc", cell_onehot, x_flat)  # [N, B, 5+C]
    loc = (
        _sce(cell[..., 0], tx) + _sce(cell[..., 1], ty)
        + jnp.abs(cell[..., 2] - tw) + jnp.abs(cell[..., 3] - th)
    ) * loc_scale
    loc_loss = jnp.sum(jnp.where(pos, loc, 0.0), axis=1)

    # ---- class loss ----
    cls_logits = cell[..., 5:]  # [N, B, C]
    onehot = jax.nn.one_hot(gt_label, class_num, dtype=jnp.float32)
    target = onehot * label_pos + (1.0 - onehot) * label_neg
    cls = jnp.sum(_sce(cls_logits, target), axis=-1) * gt_score
    cls_loss = jnp.sum(jnp.where(pos, cls, 0.0), axis=1)

    # ---- objectness mask write + loss ----
    # last-write-wins over gts without a scatter (same TPU reason as the
    # gather above): B is small and static, so B masked selects vectorized
    # over all K cells replace the reference's sequential per-gt loop with
    # identical collision semantics (later gt overwrites)
    flat = obj_mask.reshape(N, K)
    for bb in range(B):
        hit = cell_onehot[:, bb, :] > 0.5  # [N, K]; all-false when not pos
        flat = jnp.where(hit, gt_score[:, bb, None], flat)
    obj_mask = flat.reshape(N, M, H, W)
    conf = x[:, :, 4]
    obj_l = jnp.where(
        obj_mask > 1e-5,
        _sce(conf, 1.0) * obj_mask,
        jnp.where(obj_mask > -0.5, _sce(conf, 0.0), 0.0),
    )
    obj_loss = jnp.sum(obj_l, axis=(1, 2, 3))

    loss = loc_loss + cls_loss + obj_loss
    return {
        "Loss": [loss],
        "ObjectnessMask": [obj_mask],
        "GTMatchMask": [gt_match.astype(jnp.int32)],
    }


def _roi_batch_idx(rois_num, R, N, abstract=False):
    """per-roi image index from RoisNum [N] (the LoD-free replacement for
    the reference's ROIs LoD): roi r belongs to image sum(r >= cumsum)."""
    if rois_num is None:
        if N != 1 and not abstract:
            # assigning every ROI to image 0 would be silently wrong — the
            # reference derives the mapping from the ROIs' LoD, so a
            # multi-image batch without RoisNum is ambiguous here. Shape
            # inference (abstract=True) sees the -1 batch sentinel and must
            # not reject a program whose runtime batch is 1.
            raise ValueError(
                "roi op over a multi-image batch needs RoisNum "
                "(per-image roi counts)"
            )
        return jnp.zeros((R,), jnp.int32)
    bounds = jnp.cumsum(rois_num.astype(jnp.int32))  # [N]
    r = jnp.arange(R, dtype=jnp.int32)
    return jnp.sum(r[:, None] >= bounds[None, :], axis=1).astype(jnp.int32)


def _roi_align_compute(x, rois, bidx, ph, pw, scale, s):
    """Single-batch RoIAlign body: x [N, C, H, W], rois [R, 4], bidx [R]
    image index per roi -> [R, C, ph, pw] (float32)."""
    N, C, H, W = x.shape
    xmin = rois[:, 0] * scale
    ymin = rois[:, 1] * scale
    xmax = rois[:, 2] * scale
    ymax = rois[:, 3] * scale
    roi_w = jnp.maximum(xmax - xmin, 1.0)
    roi_h = jnp.maximum(ymax - ymin, 1.0)
    bw = roi_w / pw
    bh = roi_h / ph

    iy = jnp.arange(s, dtype=jnp.float32) + 0.5
    py = jnp.arange(ph, dtype=jnp.float32)
    px = jnp.arange(pw, dtype=jnp.float32)
    # sample coords [R, ph(pw), s]
    ys = (
        ymin[:, None, None]
        + py[None, :, None] * bh[:, None, None]
        + iy[None, None, :] * bh[:, None, None] / s
    )
    xs = (
        xmin[:, None, None]
        + px[None, :, None] * bw[:, None, None]
        + iy[None, None, :] * bw[:, None, None] / s
    )

    def axis_weights(coord, size):
        """(low idx, high idx, weight_low, weight_high, in-bounds)."""
        inb = (coord >= -1.0) & (coord <= size)
        c = jnp.maximum(coord, 0.0)
        low = jnp.minimum(c.astype(jnp.int32), size - 1)
        at_edge = low >= size - 1
        high = jnp.minimum(low + 1, size - 1)
        frac = jnp.where(at_edge, 0.0, c - low)
        return low, high, 1.0 - frac, frac, inb

    yl, yh, wyl, wyh, yin = axis_weights(ys, H)
    xl, xh, wxl, wxh, xin = axis_weights(xs, W)

    # gather the 4 corners for every (roi, bin_y, bin_x, sy, sx)
    b = bidx[:, None, None, None, None]
    YL = yl[:, :, None, :, None]
    YH = yh[:, :, None, :, None]
    XL = xl[:, None, :, None, :]
    XH = xh[:, None, :, None, :]

    def g(yi, xi):
        return x[b, :, yi, xi]  # [R, ph, pw, s, s, C]

    WY_L = wyl[:, :, None, :, None]
    WY_H = wyh[:, :, None, :, None]
    WX_L = wxl[:, None, :, None, :]
    WX_H = wxh[:, None, :, None, :]
    val = (
        g(YL, XL) * (WY_L * WX_L)[..., None]
        + g(YL, XH) * (WY_L * WX_H)[..., None]
        + g(YH, XL) * (WY_H * WX_L)[..., None]
        + g(YH, XH) * (WY_H * WX_H)[..., None]
    )
    inb = (yin[:, :, None, :, None] & xin[:, None, :, None, :])[..., None]
    val = jnp.where(inb, val, 0.0)
    out = jnp.mean(val, axis=(3, 4))  # average the s*s samples
    return jnp.transpose(out, (0, 3, 1, 2))


@register_op(
    "roi_align", inputs=["X", "ROIs", "RoisNum"], outputs=["Out"]
)
def _roi_align(ctx, op, ins):
    """RoIAlign (roi_align_op.h, Mask R-CNN head input): average of
    bilinear samples per output bin. The reference's adaptive sampling
    count ceil(bin_size) is data-dependent — static-shape re-design uses a
    fixed grid (sampling_ratio attr; <=0 falls back to 2, the standard
    detectron setting) so the whole op is gathers + one mean on the MXU
    host. Differentiable via the generic vjp (gather grad = scatter-add,
    exactly the reference's hand-written bilinear backward).

    Batched contract (r6): ROIs [B, R, 4] with X [B, C, H, W] — image b's
    rois sample ONLY feature map b (no RoisNum needed; the per-image RoI
    cap R is static) -> Out [B, R, C, ph, pw]."""
    x = ins["X"][0]
    rois = ins["ROIs"][0].astype(jnp.float32)
    rois_num = (
        ins["RoisNum"][0]
        if ins.get("RoisNum") and ins["RoisNum"][0] is not None
        else None
    )
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    sr = int(op.attr("sampling_ratio", -1))
    s = sr if sr > 0 else 2
    if rois.ndim == 3:  # [B, R, 4] cross-image batched form
        _tally(ctx, "roi_align", batched=True)
        zeros = jnp.zeros((rois.shape[1],), jnp.int32)
        out = jax.vmap(
            lambda xb, rb: _roi_align_compute(
                xb[None], rb, zeros, ph, pw, scale, s
            )
        )(x, rois)  # [B, R, C, ph, pw]
        return {"Out": [out.astype(x.dtype)]}
    _tally(ctx, "roi_align", batched=False)
    N = x.shape[0]
    R = rois.shape[0]
    bidx = _roi_batch_idx(rois_num, R, N, ctx.abstract)
    out = _roi_align_compute(x, rois, bidx, ph, pw, scale, s)
    return {"Out": [out.astype(x.dtype)]}


def _roi_pool_compute(x, rois, bidx, ph, pw, scale):
    """Single-batch RoIPool body: x [N, C, H, W], rois [R, 4], bidx [R]
    -> (out [R, C, ph, pw], argmax [R, C, ph, pw])."""
    N, C, H, W = x.shape
    R = rois.shape[0]

    def cround(v):
        # std::round = half away from zero (coords are >= 0 here); jnp.round
        # is half-to-even and would shift bins at exact .5 boundaries
        return jnp.floor(v + 0.5).astype(jnp.int32)

    xmin = cround(rois[:, 0] * scale)
    ymin = cround(rois[:, 1] * scale)
    xmax = cround(rois[:, 2] * scale)
    ymax = cround(rois[:, 3] * scale)
    roi_h = jnp.maximum(ymax - ymin + 1, 1)
    roi_w = jnp.maximum(xmax - xmin + 1, 1)

    py = jnp.arange(ph, dtype=jnp.int32)
    px = jnp.arange(pw, dtype=jnp.int32)
    # bin edges, clipped to the map (roi_pool_op.cc bin arithmetic)
    hstart = jnp.clip(
        ymin[:, None] + (py[None, :] * roi_h[:, None]) // ph, 0, H
    )
    hend = jnp.clip(
        ymin[:, None] + ((py[None, :] + 1) * roi_h[:, None] + ph - 1) // ph,
        0, H,
    )
    wstart = jnp.clip(
        xmin[:, None] + (px[None, :] * roi_w[:, None]) // pw, 0, W
    )
    wend = jnp.clip(
        xmin[:, None] + ((px[None, :] + 1) * roi_w[:, None] + pw - 1) // pw,
        0, W,
    )
    hh = jnp.arange(H, dtype=jnp.int32)
    ww = jnp.arange(W, dtype=jnp.int32)
    # [R, ph, H] / [R, pw, W] membership
    in_h = (hh[None, None, :] >= hstart[..., None]) & (
        hh[None, None, :] < hend[..., None]
    )
    in_w = (ww[None, None, :] >= wstart[..., None]) & (
        ww[None, None, :] < wend[..., None]
    )
    mask = in_h[:, :, None, :, None] & in_w[:, None, :, None, :]
    feats = x[bidx]  # [R, C, H, W]
    masked = jnp.where(
        mask[:, None], feats[:, :, None, None], -jnp.inf
    )  # [R, C, ph, pw, H, W]
    flat = masked.reshape(R, C, ph, pw, H * W)
    arg = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    out = jnp.max(flat, axis=-1)
    empty = ~jnp.any(mask, axis=(3, 4))  # [R, ph, pw]
    out = jnp.where(empty[:, None], 0.0, out)
    arg = jnp.where(empty[:, None], -1, arg)
    return out, arg


@register_op(
    "roi_pool", inputs=["X", "ROIs", "RoisNum"], outputs=["Out", "Argmax"]
)
def _roi_pool(ctx, op, ins):
    """RoIPool (roi_pool_op.cc): max over integer-quantized bins. Static
    re-design: every bin maxes a masked view of the full feature map
    (O(H*W) per bin — fine for head-sized maps; roi_align is the
    recommended TPU path). Batched contract as roi_align: ROIs [B, R, 4]
    with X [B, C, H, W] -> Out/Argmax [B, R, C, ph, pw]."""
    x = ins["X"][0]
    rois = ins["ROIs"][0].astype(jnp.float32)
    rois_num = (
        ins["RoisNum"][0]
        if ins.get("RoisNum") and ins["RoisNum"][0] is not None
        else None
    )
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    if rois.ndim == 3:  # [B, R, 4] cross-image batched form
        _tally(ctx, "roi_pool", batched=True)
        zeros = jnp.zeros((rois.shape[1],), jnp.int32)
        out, arg = jax.vmap(
            lambda xb, rb: _roi_pool_compute(xb[None], rb, zeros, ph, pw,
                                             scale)
        )(x, rois)
        return {"Out": [out.astype(x.dtype)], "Argmax": [arg]}
    _tally(ctx, "roi_pool", batched=False)
    N = x.shape[0]
    R = rois.shape[0]
    bidx = _roi_batch_idx(rois_num, R, N, ctx.abstract)
    out, arg = _roi_pool_compute(x, rois, bidx, ph, pw, scale)
    return {"Out": [out.astype(x.dtype)], "Argmax": [arg]}


@register_op(
    "anchor_generator", inputs=["Input"], outputs=["Anchors", "Variances"],
    differentiable=False,
)
def _anchor_generator(ctx, op, ins):
    """RPN anchors per feature-map cell (anchor_generator_op.h:38-85):
    anchors [H, W, A, 4] with A = len(aspect_ratios)*len(anchor_sizes)."""
    feat = ins["Input"][0]
    H, W = feat.shape[2], feat.shape[3]
    sizes = [float(v) for v in op.attr("anchor_sizes")]
    ars = [float(v) for v in op.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    stride = [float(v) for v in op.attr("stride")]
    offset = float(op.attr("offset", 0.5))
    sw, sh = stride[0], stride[1]

    whs = []
    for ar in ars:
        base_w = np.round(np.sqrt(sw * sh / ar))
        base_h = np.round(base_w * ar)
        for size in sizes:
            whs.append((size / sw * base_w, size / sh * base_h))
    whs = jnp.asarray(whs, jnp.float32)  # [A, 2]

    xc = jnp.arange(W, dtype=jnp.float32) * sw + offset * (sw - 1)
    yc = jnp.arange(H, dtype=jnp.float32) * sh + offset * (sh - 1)
    xg, yg = jnp.meshgrid(xc, yc)  # [H, W]
    ctr = jnp.stack([xg, yg], -1)[:, :, None, :]  # [H, W, 1, 2]
    half = (whs[None, None] - 1.0) / 2.0
    mins = ctr - half
    maxs = ctr + half
    anchors = jnp.concatenate([mins, maxs], -1)  # [H, W, A, 4]
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), anchors.shape
    )
    return {"Anchors": [anchors], "Variances": [var]}


@register_op("box_clip", inputs=["Input", "ImInfo"], outputs=["Output"])
def _box_clip(ctx, op, ins):
    """Clip boxes to image bounds (box_clip_op.cc): ImInfo [N, 3] =
    (h, w, im_scale); boxes [N, M, 4] clipped to [0, dim/scale - 1]."""
    boxes = ins["Input"][0]
    im_info = ins["ImInfo"][0].astype(jnp.float32)
    # reference rounds the descaled image dims before the -1 (box_clip_op.h)
    hmax = jnp.round(im_info[:, 0] / im_info[:, 2]) - 1.0  # [N]
    wmax = jnp.round(im_info[:, 1] / im_info[:, 2]) - 1.0
    zero = jnp.zeros_like(wmax)
    lo = jnp.stack([zero, zero, zero, zero], -1)[:, None, :]
    hi = jnp.stack([wmax, hmax, wmax, hmax], -1)[:, None, :]
    return {"Output": [jnp.clip(boxes, lo, hi)]}


@register_op("sigmoid_focal_loss", inputs=["X", "Label", "FgNum"],
             outputs=["Out"])
def _sigmoid_focal_loss(ctx, op, ins):
    """RetinaNet focal loss (detection/sigmoid_focal_loss_op.cu): X [N, C]
    logits, Label [N, 1] in {0..C} (0 = background), FgNum [1] foreground
    count; per-class sigmoid CE weighted by alpha/(1-alpha) and
    (1-p_t)^gamma, normalized by fg_num."""
    x = ins["X"][0].astype(jnp.float32)
    label = ins["Label"][0].astype(jnp.int32).reshape(-1)
    fg = jnp.maximum(ins["FgNum"][0].astype(jnp.float32).reshape(()), 1.0)
    gamma = float(op.attr("gamma", 2.0))
    alpha = float(op.attr("alpha", 0.25))
    N, C = x.shape
    # target[n, c] = 1 iff label[n] == c+1 (class ids are 1-based; 0 = bg)
    t = (label[:, None] == (jnp.arange(C)[None, :] + 1)).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * t + (1 - p) * (1 - t)
    a_t = alpha * t + (1 - alpha) * (1 - t)
    return {"Out": [a_t * ((1 - p_t) ** gamma) * ce / fg]}


@register_op("density_prior_box", inputs=["Input", "Image"],
             outputs=["Boxes", "Variances"], differentiable=False)
def _density_prior_box(ctx, op, ins):
    """Densified SSD priors (detection/density_prior_box_op.h): per cell,
    for each (fixed_size, density) pair lay a density x density grid of
    shifted boxes scaled by fixed_ratios."""
    feat, img = ins["Input"][0], ins["Image"][0]
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    fixed_sizes = [float(v) for v in op.attr("fixed_sizes")]
    fixed_ratios = [float(v) for v in op.attr("fixed_ratios", [1.0])]
    densities = [int(v) for v in op.attr("densities")]
    variances = [float(v) for v in op.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    clip = bool(op.attr("clip", False))
    step_w = op.attr("step_w", 0.0) or IW / W
    step_h = op.attr("step_h", 0.0) or IH / H
    offset = float(op.attr("offset", 0.5))

    # the density grid spans one STEP cell, not the box size
    # (density_prior_box_op.h:69-101: shift = step_average / density,
    # centers at -step_average/2 + shift/2 + j*shift from the cell center)
    step_average = int(0.5 * (step_w + step_h))
    whs, shifts = [], []
    for size, density in zip(fixed_sizes, densities):
        for ar in fixed_ratios:
            bw = size * np.sqrt(ar)
            bh = size / np.sqrt(ar)
            shift = step_average / density
            for di in range(density):
                for dj in range(density):
                    whs.append((bw, bh))
                    shifts.append((
                        -step_average / 2.0 + shift / 2.0 + dj * shift,
                        -step_average / 2.0 + shift / 2.0 + di * shift,
                    ))
    whs = jnp.asarray(whs, jnp.float32)      # [P, 2]
    shifts = jnp.asarray(shifts, jnp.float32)

    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    centers = jnp.stack([cxg, cyg], -1)[:, :, None, :] + shifts[None, None]
    half = whs[None, None] / 2.0
    mins = (centers - half) / jnp.asarray([IW, IH], jnp.float32)
    maxs = (centers + half) / jnp.asarray([IW, IH], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], -1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


@register_op(
    "generate_proposals",
    inputs=["Scores", "BboxDeltas", "ImInfo", "Anchors", "Variances"],
    outputs=["RpnRois", "RpnRoiProbs", "RpnRoisNum"],
    differentiable=False,
)
def _generate_proposals(ctx, op, ins):
    """RPN proposal generation (detection/generate_proposals_op.cc),
    static-shape re-design: decode all anchors, clip to the image, mask
    degenerate boxes, take pre_nms_topN by score, greedy-NMS on the fixed
    set, emit exactly post_nms_topN rois per image (padded; RpnRoisNum
    counts the valid ones) — the reference emits a variable count via LoD.
    Natively rank-lifted over the image batch N (the one detection op the
    seed already batched); N>1 is the cross-image path.
    """
    _tally(ctx, "generate_proposals", batched=ins["Scores"][0].shape[0] > 1)
    scores = ins["Scores"][0]          # [N, A, H, W]
    deltas = ins["BboxDeltas"][0]      # [N, A*4, H, W]
    im_info = ins["ImInfo"][0].astype(jnp.float32)  # [N, 3]
    anchors = ins["Anchors"][0].reshape(-1, 4).astype(jnp.float32)
    variances = ins["Variances"][0].reshape(-1, 4).astype(jnp.float32)
    pre_n = int(op.attr("pre_nms_topN", 6000))
    post_n = int(op.attr("post_nms_topN", 1000))
    nms_thresh = float(op.attr("nms_thresh", 0.7))
    min_size = float(op.attr("min_size", 0.1))

    N, A, H, W = scores.shape
    M = A * H * W
    sc = scores.transpose(0, 2, 3, 1).reshape(N, M)
    dl = (
        deltas.reshape(N, A, 4, H, W).transpose(0, 3, 4, 1, 2).reshape(N, M, 4)
    )

    # decode (anchor + variance-scaled deltas; generate_proposals box coder)
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw * 0.5
    acy = anchors[:, 1] + ah * 0.5
    cx = variances[:, 0] * dl[..., 0] * aw + acx
    cy = variances[:, 1] * dl[..., 1] * ah + acy
    w = jnp.exp(jnp.minimum(variances[:, 2] * dl[..., 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(variances[:, 3] * dl[..., 3], 10.0)) * ah
    x0 = cx - 0.5 * w
    y0 = cy - 0.5 * h
    x1 = cx + 0.5 * w - 1.0
    y1 = cy + 0.5 * h - 1.0

    imh = im_info[:, 0:1]
    imw = im_info[:, 1:2]
    x0 = jnp.clip(x0, 0.0, imw - 1.0)
    x1 = jnp.clip(x1, 0.0, imw - 1.0)
    y0 = jnp.clip(y0, 0.0, imh - 1.0)
    y1 = jnp.clip(y1, 0.0, imh - 1.0)
    boxes = jnp.stack([x0, y0, x1, y1], -1)  # [N, M, 4]

    # FilterBoxes (generate_proposals_op.cc:168): keep iff the box size in
    # ORIGINAL image units (w/im_scale + 1) reaches max(min_size, 1)
    ms = max(min_size, 1.0)
    im_scale = im_info[:, 2:3]
    keep = ((x1 - x0) / im_scale + 1.0 >= ms) & (
        (y1 - y0) / im_scale + 1.0 >= ms
    )
    sc = jnp.where(keep, sc, -jnp.inf)

    k = min(pre_n, M)
    top_sc, top_i = lax.top_k(sc, k)  # [N, k]
    top_boxes = jnp.take_along_axis(boxes, top_i[..., None], axis=1)

    alive = jax.vmap(
        lambda bx, s: _greedy_nms(bx, jnp.isfinite(s), nms_thresh)
    )(top_boxes, top_sc)  # [N, k]
    # stable-order select the first post_n survivors (already score-sorted)
    rank = jnp.cumsum(alive.astype(jnp.int32), axis=1) - 1
    out_boxes = jnp.zeros((N, post_n, 4), boxes.dtype)
    out_probs = jnp.zeros((N, post_n), sc.dtype)
    n_idx = jnp.arange(N)[:, None].repeat(k, 1)
    # suppressed boxes and rank overflow both land on the dump row post_n
    sel_cl = jnp.where(alive & (rank < post_n), rank, post_n)
    out_boxes = jnp.concatenate(
        [out_boxes, jnp.zeros((N, 1, 4), boxes.dtype)], axis=1
    ).at[n_idx, sel_cl].set(top_boxes, mode="drop")[:, :post_n]
    out_probs = jnp.concatenate(
        [out_probs, jnp.zeros((N, 1), sc.dtype)], axis=1
    ).at[n_idx, sel_cl].set(top_sc, mode="drop")[:, :post_n]
    num = jnp.minimum(jnp.sum(alive, axis=1), post_n).astype(jnp.int32)
    return {
        "RpnRois": [out_boxes],
        "RpnRoiProbs": [out_probs[..., None]],
        "RpnRoisNum": [num],
    }
