"""save_dygraph / load_dygraph (reference dygraph/checkpoint.py:33,98)."""

from __future__ import annotations

import os
import pickle

import numpy as np


def save_dygraph(state_dict, model_path):
    """state_dict: Layer.state_dict() or optimizer state. Writes one file."""
    payload = {
        k: np.asarray(v) if hasattr(v, "shape") else v
        for k, v in state_dict.items()
    }
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(payload, f, protocol=4)


def load_dygraph(model_path):
    with open(model_path + ".pdparams", "rb") as f:
        params = pickle.load(f)
    opt_path = model_path + ".pdopt"
    opt = None
    if os.path.exists(opt_path):
        with open(opt_path, "rb") as f:
            opt = pickle.load(f)
    return params, opt
