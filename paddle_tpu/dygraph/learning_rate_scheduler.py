"""Eager-mode LR schedules (reference python/paddle/fluid/dygraph/
learning_rate_scheduler.py: NoamDecay, PiecewiseDecay, NaturalExpDecay,
ExponentialDecay, InverseTimeDecay, PolynomialDecay, CosineDecay,
LinearLrWarmup, ReduceLROnPlateau).

Each instance is a callable the eager optimizers accept as learning_rate
(Optimizer._eager_lr calls it once per minimize); __call__ returns the
current LR and advances the internal step, mirroring the reference's
LearningRateDecay.__call__ semantics. Pure host-side scalar math — the value
feeds the jitted update as a traced scalar, so no recompile per step.
"""

from __future__ import annotations

import math


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = int(begin)
        self.step_size = int(step)

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return float(lr)

    def step(self):
        raise NotImplementedError


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1, learning_rate=1.0):
        super().__init__(begin, step)
        self.d_model = float(d_model)
        self.warmup_steps = float(warmup_steps)
        self.learning_rate = float(learning_rate)

    def step(self):
        s = max(self.step_num, 1)
        a = s ** -0.5
        b = s * self.warmup_steps ** -1.5
        return self.learning_rate * self.d_model ** -0.5 * min(a, b)


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for b, v in zip(self.boundaries, self.values[:-1]):
            if self.step_num < b:
                return v
        return self.values[-1]


class ExponentialDecay(LearningRateDecay):
    def __init__(
        self, learning_rate, decay_steps, decay_rate, staircase=False,
        begin=0, step=1,
    ):
        super().__init__(begin, step)
        self.learning_rate = float(learning_rate)
        self.decay_steps = float(decay_steps)
        self.decay_rate = float(decay_rate)
        self.staircase = staircase

    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * self.decay_rate ** div


class NaturalExpDecay(ExponentialDecay):
    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate * math.exp(-self.decay_rate * div)


class InverseTimeDecay(ExponentialDecay):
    def step(self):
        div = self.step_num / self.decay_steps
        if self.staircase:
            div = math.floor(div)
        return self.learning_rate / (1.0 + self.decay_rate * div)


class PolynomialDecay(LearningRateDecay):
    def __init__(
        self, learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0,
        cycle=False, begin=0, step=1,
    ):
        super().__init__(begin, step)
        self.learning_rate = float(learning_rate)
        self.decay_steps = float(decay_steps)
        self.end_learning_rate = float(end_learning_rate)
        self.power = float(power)
        self.cycle = cycle

    def step(self):
        s = float(self.step_num)
        if self.cycle:
            ratio = math.ceil(s / self.decay_steps)
            if s == 0:
                ratio = 1.0
            steps = self.decay_steps * ratio
        else:
            steps = self.decay_steps
            s = min(s, steps)
        frac = (1.0 - s / steps) ** self.power
        return (self.learning_rate - self.end_learning_rate) * frac + (
            self.end_learning_rate
        )


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0, step=1):
        super().__init__(begin, step)
        self.learning_rate = float(learning_rate)
        self.step_each_epoch = float(step_each_epoch)
        self.epochs = float(epochs)

    def step(self):
        epoch = math.floor(self.step_num / self.step_each_epoch)
        return (
            0.5
            * self.learning_rate
            * (math.cos(epoch * math.pi / self.epochs) + 1.0)
        )


class LinearLrWarmup(LearningRateDecay):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, begin=0, step=1):
        super().__init__(begin, step)
        self.learning_rate = learning_rate  # float or LearningRateDecay
        self.warmup_steps = float(warmup_steps)
        self.start_lr = float(start_lr)
        self.end_lr = float(end_lr)

    def step(self):
        if self.step_num < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * (
                self.step_num / self.warmup_steps
            )
        base = self.learning_rate
        if isinstance(base, LearningRateDecay):
            base.step_num = self.step_num
            return base.step()
        return float(base)


class ReduceLROnPlateau:
    """Metric-driven decay (reference :?): call .step(metric) per eval."""

    def __init__(
        self, learning_rate, mode="min", decay_rate=0.1, patience=10,
        threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0.0,
    ):
        self.lr = float(learning_rate)
        self.mode = mode
        self.decay_rate = float(decay_rate)
        self.patience = int(patience)
        self.threshold = float(threshold)
        self.threshold_mode = threshold_mode  # "rel" (reference default) | "abs"
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0

    def __call__(self):
        return self.lr

    def _is_better(self, cur):
        if self.best is None:
            return True
        if self.threshold_mode == "rel":
            if self.mode == "min":
                return cur < self.best * (1.0 - self.threshold)
            return cur > self.best * (1.0 + self.threshold)
        if self.mode == "min":
            return cur < self.best - self.threshold
        return cur > self.best + self.threshold

    def step(self, metric):
        m = float(metric)
        if self._is_better(m):
            self.best = m
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        elif self.num_bad > self.patience:
            self.lr = max(self.lr * self.decay_rate, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        return self.lr
