"""TracedLayer — dygraph→compiled capture (reference dygraph/jit.py:204).

TPU-native: instead of replaying a ProgramDesc trace (the reference's
program_desc_tracer), the layer's forward is traced ONCE by jax.jit over its
parameters + inputs. The captured computation is exactly what eager mode
runs (same emitters), now fused and cached — the analog of @declarative /
dygraph_to_static, without AST rewriting for the functional subset.
"""

from __future__ import annotations

import jax

from .base import guard, no_grad_ctx
from .varbase import VarBase


class TracedLayer:
    def __init__(self, layer, fn):
        self._layer = layer
        self._fn = fn

    @staticmethod
    def trace(layer, inputs):
        """Returns (outputs, TracedLayer). inputs: list[VarBase]."""
        params = {name: p for name, p in layer.named_parameters()}

        def pure(param_vals, in_vals):
            originals = {n: p._value for n, p in params.items()}
            try:
                for name, p in params.items():
                    p._value = param_vals[name]
                with no_grad_ctx():
                    out = layer(*[VarBase(v) for v in in_vals])
                outs = out if isinstance(out, (list, tuple)) else [out]
                return [o.value for o in outs]
            finally:
                # params must not hold tracers once the trace finishes
                for n, p in params.items():
                    p._value = originals[n]

        jitted = jax.jit(pure)
        traced = TracedLayer(layer, jitted)
        out_vals = jitted(
            {n: p.value for n, p in params.items()},
            [v.value for v in inputs],
        )
        outs = [VarBase(v) for v in out_vals]
        traced._params = params
        return outs, traced

    def __call__(self, inputs):
        out_vals = self._fn(
            {n: p.value for n, p in self._params.items()},
            [v.value for v in inputs],
        )
        return [VarBase(v) for v in out_vals]
