"""dygraph.Layer: the eager module base class (reference dygraph/layers.py:
Layer.__call__ :449 with pre/post hooks, sublayers, state_dict)."""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax.numpy as jnp

from ..framework import unique_name
from .varbase import ParamBase, VarBase


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = unique_name.generate(
            name_scope or type(self).__name__.lower()
        )
        self._dtype = dtype
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self.training = True

    def full_name(self):
        return self._full_name

    # -- parameter management ----------------------------------------------
    def create_parameter(self, shape, dtype=None, initializer=None,
                         attr=None, is_bias=False, name=None):
        from ..initializer import Constant, Xavier

        dtype = dtype or self._dtype
        init = initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = Constant(0.0) if is_bias else Xavier()
        pname = name or (getattr(attr, "name", None) if attr is not None else None)
        pname = pname or unique_name.generate(self._full_name + ".w")
        value = init.numpy_init(shape, dtype)
        return ParamBase(jnp.asarray(value), name=pname)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, value, persistable=True):
        vb = value if isinstance(value, VarBase) else VarBase(value)
        vb.persistable = persistable
        vb.stop_gradient = True
        self._buffers[name] = vb
        return vb

    def parameters(self, include_sublayers=True):
        out = list(self._parameters.values())
        if include_sublayers:
            for sl in self._sub_layers.values():
                out.extend(sl.parameters())
        return out

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for sl in self._sub_layers.values():
            out.extend(sl.sublayers(include_self=True))
        return out

    def named_parameters(self, prefix=""):
        for name, p in self._parameters.items():
            yield (f"{prefix}{name}", p)
        for lname, sl in self._sub_layers.items():
            yield from sl.named_parameters(prefix=f"{prefix}{lname}.")

    # -- train/eval ---------------------------------------------------------
    def train(self):
        self.training = True
        for sl in self._sub_layers.values():
            sl.train()
        return self

    def eval(self):
        self.training = False
        for sl in self._sub_layers.values():
            sl.eval()
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, prefix=""):
        out = OrderedDict()
        for name, p in self._parameters.items():
            out[f"{prefix}{name}"] = p.numpy()
        for name, b in self._buffers.items():
            out[f"{prefix}{name}"] = b.numpy()
        for lname, sl in self._sub_layers.items():
            out.update(sl.state_dict(prefix=f"{prefix}{lname}."))
        return out

    def set_dict(self, state, use_structured_name=True):
        for name, p in self._parameters.items():
            if name in state:
                p.set_value(jnp.asarray(state[name]))
        for name, b in self._buffers.items():
            if name in state:
                b.set_value(jnp.asarray(state[name]))
        for lname, sl in self._sub_layers.items():
            sub = {
                k[len(lname) + 1:]: v
                for k, v in state.items()
                if k.startswith(lname + ".")
            }
            sl.set_dict(sub)

    load_dict = set_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- hooks + call --------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return key

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return key

    def __call__(self, *args, **kwargs):
        from .tracer import _current

        tr = _current()
        if tr is not None:
            tr.train_mode = self.training
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    # -- attribute sugar: assignment auto-registers --------------------------
    def __setattr__(self, name, value):
        if isinstance(value, ParamBase):
            self.__dict__.setdefault("_parameters", OrderedDict())[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", OrderedDict())[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and (
            not isinstance(layers[0][0], Layer)
        ):
            layers = layers[0]
        for i, item in enumerate(layers):
            if isinstance(item, (list, tuple)):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def forward(self, x):
        for sl in self._sub_layers.values():
            x = sl(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, sl in enumerate(sublayers or []):
            self.add_sublayer(str(i), sl)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __iter__(self):
        return iter(self._parameters.values())

    def __len__(self):
        return len(self._parameters)
