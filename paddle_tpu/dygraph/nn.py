"""Eager module zoo (reference dygraph/nn.py: Conv2D :42, Linear :888,
BatchNorm :1125, Embedding :1472, LayerNorm :1632, Pool2D, Dropout, GRUUnit
:1806). Each module owns ParamBase weights and calls the shared op emitters
through the tracer — one op set for both execution modes."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..initializer import Constant, Normal, Xavier
from .layers import Layer
from .tracer import trace_op, trace_op_multi
from .varbase import VarBase


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [input_dim, output_dim], dtype, attr=param_attr, initializer=Xavier()
        )
        self.bias = (
            self.create_parameter([output_dim], dtype, attr=bias_attr,
                                  is_bias=True)
            if bias_attr is not False
            else None
        )
        self._act = act

    def forward(self, x):
        out = trace_op(
            "mul", {"X": [x], "Y": [self.weight]},
            {"x_num_col_dims": len(x.shape) - 1, "y_num_col_dims": 1},
        )
        if self.bias is not None:
            out = trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]},
                {"axis": len(x.shape) - 1},
            )
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})
        return out


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        k = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
        groups = groups or 1
        std = (2.0 / (k[0] * k[1] * num_channels)) ** 0.5
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, k[0], k[1]], dtype,
            attr=param_attr, initializer=Normal(0.0, std),
        )
        self.bias = (
            self.create_parameter([num_filters], dtype, attr=bias_attr,
                                  is_bias=True)
            if bias_attr is not False
            else None
        )
        self._attrs = {
            "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
            "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
            "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 2,
            "groups": groups,
            "padding_algorithm": "EXPLICIT",
        }
        self._act = act

    def forward(self, x):
        out = trace_op_multi(
            "conv2d", {"Input": [x], "Filter": [self.weight]}, self._attrs
        )["Output"][0]
        if self.bias is not None:
            out = trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}
            )
        if self._act:
            out = trace_op(self._act, {"X": [out]}, {})
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, exclusive=True):
        super().__init__()
        self._attrs = {
            "ksize": list(pool_size) if isinstance(pool_size, (list, tuple)) else [pool_size] * 2,
            "pooling_type": pool_type,
            "strides": list(pool_stride) if isinstance(pool_stride, (list, tuple)) else [pool_stride] * 2,
            "paddings": list(pool_padding) if isinstance(pool_padding, (list, tuple)) else [pool_padding] * 2,
            "global_pooling": global_pooling,
            "exclusive": exclusive,
        }

    def forward(self, x):
        return trace_op("pool2d", {"X": [x]}, self._attrs)


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", use_global_stats=False):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], dtype, attr=param_attr, initializer=Constant(1.0)
        )
        self.bias = self.create_parameter(
            [num_channels], dtype, attr=bias_attr, is_bias=True
        )
        self._mean = self.register_buffer(
            "_mean_buf", jnp.zeros([num_channels], dtype)
        )
        self._variance = self.register_buffer(
            "_var_buf", jnp.ones([num_channels], dtype)
        )
        self._attrs = {
            "momentum": momentum,
            "epsilon": epsilon,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        }
        self._act = act

    def forward(self, x):
        attrs = dict(self._attrs)
        attrs["is_test"] = not self.training
        outs = trace_op_multi(
            "batch_norm",
            {
                "X": [x],
                "Scale": [self.weight],
                "Bias": [self.bias],
                "Mean": [self._mean],
                "Variance": [self._variance],
            },
            attrs,
        )
        y = outs["Y"][0]
        if self.training:
            # running-stat update: functional outputs written back to buffers
            self._mean.set_value(outs["MeanOut"][0].value)
            self._variance.set_value(outs["VarianceOut"][0].value)
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {})
        return y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None,
                 param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            list(size), dtype, attr=param_attr, initializer=Xavier()
        )
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        return trace_op(
            "lookup_table_v2",
            {"W": [self.weight], "Ids": [ids]},
            {"padding_idx": self._padding_idx},
        )


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self._norm_rank = len(normalized_shape)
        self.weight = (
            self.create_parameter([n], dtype, attr=param_attr,
                                  initializer=Constant(1.0))
            if scale
            else None
        )
        self.bias = (
            self.create_parameter([n], dtype, attr=bias_attr, is_bias=True)
            if shift
            else None
        )
        self._epsilon = epsilon
        self._act = act

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        y = trace_op_multi(
            "layer_norm",
            ins,
            {
                "begin_norm_axis": len(x.shape) - self._norm_rank,
                "epsilon": self._epsilon,
            },
        )["Y"][0]
        if self._act:
            y = trace_op(self._act, {"X": [y]}, {})
        return y


class Dropout(Layer):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        return trace_op(
            "dropout", {"X": [x]},
            {"dropout_prob": self._p, "is_test": not self.training,
             "dropout_implementation": "upscale_in_train"},
        )


class GRUUnit(Layer):
    """Single GRU step (reference dygraph/nn.py:1806): gate/candidate weights
    packed fluid-style: weight [D, 3D] (update|reset|cand)."""

    def __init__(self, size, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        d = size // 3
        self._d = d
        self.weight = self.create_parameter([d, d * 3], dtype, attr=param_attr)
        self.bias = (
            self.create_parameter([1, d * 3], dtype, attr=bias_attr, is_bias=True)
            if bias_attr is not False
            else None
        )

    def forward(self, inputs, hidden):
        # inputs [B, 3D] (x projections), hidden [B, D]
        d = self._d
        gates_x = inputs
        if self.bias is not None:
            gates_x = trace_op(
                "elementwise_add", {"X": [gates_x], "Y": [self.bias]},
                {"axis": -1},
            )
        hw = trace_op("matmul", {"X": [hidden], "Y": [self.weight]}, {})
        xu, xr, xc = (gates_x[:, :d], gates_x[:, d:2 * d], gates_x[:, 2 * d:])
        hu, hr, hc = (hw[:, :d], hw[:, d:2 * d], hw[:, 2 * d:])
        u = trace_op("sigmoid", {"X": [xu + hu]}, {})
        r = trace_op("sigmoid", {"X": [xr + hr]}, {})
        c = trace_op("tanh", {"X": [xc + r * hc]}, {})
        one = VarBase(jnp.ones_like(u.value))
        h = u * hidden + (one - u) * c
        return h, h, c
