"""Eager ("dygraph") mode — reference python/paddle/fluid/dygraph/ +
paddle/fluid/imperative/ re-designed for TPU:

* one op set: eager calls the same JAX emitters as the static Executor;
* taped autograd with jax.vjp closures (tracer.py) instead of the OpBase
  grad-node graph + BasicEngine;
* TracedLayer captures the eager net via jax.jit (jit.py) — the
  dygraph→static bridge without ProgramDesc replay.
"""

from .base import enabled, guard, no_grad, to_variable  # noqa: F401
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from .jit import TracedLayer  # noqa: F401
from .layers import Layer, LayerList, ParameterList, Sequential  # noqa: F401
from .nn import (  # noqa: F401
    BatchNorm,
    Conv2D,
    Dropout,
    Embedding,
    GRUUnit,
    LayerNorm,
    Linear,
    Pool2D,
)
from .parallel import DataParallel, ParallelEnv, prepare_context  # noqa: F401
from .tracer import Tracer  # noqa: F401
from .varbase import ParamBase, VarBase  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    CosineDecay,
    ExponentialDecay,
    InverseTimeDecay,
    LearningRateDecay,
    LinearLrWarmup,
    NaturalExpDecay,
    NoamDecay,
    PiecewiseDecay,
    PolynomialDecay,
    ReduceLROnPlateau,
)
from .dygraph_to_static import ProgramTranslator, declarative, to_static  # noqa: F401
