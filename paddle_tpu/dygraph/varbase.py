"""VarBase: the eager-mode tensor (reference: imperative/layer.h VarBase,
python varbase_patch_methods.py).

TPU-native design: a VarBase wraps a jax Array resident on device. Autograd
is a per-tracer tape of vjp closures (tracer.py) — the analog of the
reference's OpBase grad-node graph (imperative/tracer.cc:80) walked by
BasicEngine (imperative/basic_engine.cc:159); here each tape entry's vjp_fn
carries its residuals as device arrays, so backward is a reverse sweep
calling jax closures, with gradient accumulation by addition (the
reference's SortedGradientAccumulator collapses to `+=`).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dtypes import convert_dtype
from ..framework import unique_name


class VarBase:
    def __init__(self, value, name=None, persistable=False, stop_gradient=True):
        self._value = value if hasattr(value, "dtype") else jnp.asarray(value)
        self.name = name or unique_name.generate("eager_tmp")
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self._grad = None  # accumulated gradient (jax array)

    # -- value access -------------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return convert_dtype(self._value.dtype)

    def numpy(self):
        return np.asarray(self._value)

    def set_value(self, v):
        self._value = v if hasattr(v, "dtype") else jnp.asarray(v)

    def detach(self):
        return VarBase(self._value, stop_gradient=True)

    def astype(self, dtype):
        from .tracer import trace_op

        return trace_op("cast", {"X": [self]}, {"in_dtype": self.dtype,
                                                "out_dtype": dtype})

    # -- autograd -----------------------------------------------------------
    def backward(self, retain_graph=False):
        from .tracer import _require_tracer

        _require_tracer().run_backward(self, retain_graph=retain_graph)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    def clear_gradient(self):
        self._grad = None

    @property
    def grad(self):
        return self._grad

    # -- operator sugar (mirrors static Variable) ----------------------------
    def _binary(self, other, op_type, reverse=False):
        from .tracer import trace_op

        if not isinstance(other, VarBase):
            other = VarBase(jnp.asarray(other, dtype=self._value.dtype))
        x, y = (other, self) if reverse else (self, other)
        return trace_op(op_type, {"X": [x], "Y": [y]}, {"axis": -1})

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __floordiv__(self, o):
        return self._binary(o, "elementwise_floordiv")

    def __mod__(self, o):
        return self._binary(o, "elementwise_mod")

    def __neg__(self):
        from .tracer import trace_op

        return trace_op("scale", {"X": [self]}, {"scale": -1.0, "bias": 0.0})

    # comparisons build compare ops (fluid math_op_patch parity) — needed by
    # @declarative-converted `while i < n:` style tensor conditions.
    # __eq__ is elementwise like fluid's patched ==; identity hashing is
    # kept explicitly (torch.Tensor makes the same trade). Non-coercible
    # operands (None, strings, arbitrary objects) return NotImplemented so
    # python's fallback equality holds — `vb == None` is False, not a raise
    def _coercible(self, o):
        import numbers

        return isinstance(
            o, (VarBase, numbers.Number, bool, np.ndarray, jnp.ndarray)
        )

    def __eq__(self, o):
        if not self._coercible(o):
            return NotImplemented
        return self._binary(o, "equal")

    def __ne__(self, o):
        if not self._coercible(o):
            return NotImplemented
        return self._binary(o, "not_equal")

    __hash__ = object.__hash__

    def __lt__(self, o):
        return self._binary(o, "less_than")

    def __le__(self, o):
        return self._binary(o, "less_equal")

    def __gt__(self, o):
        return self._binary(o, "greater_than")

    def __ge__(self, o):
        return self._binary(o, "greater_equal")

    def __matmul__(self, o):
        from .tracer import trace_op

        return trace_op("matmul", {"X": [self], "Y": [o]}, {})

    def __getitem__(self, idx):
        out = self._value[idx]
        from .tracer import _current, _record_getitem

        tr = _current()
        res = VarBase(out, stop_gradient=self.stop_gradient)
        if tr is not None and tr.enable_grad and not self.stop_gradient:
            res.stop_gradient = False
            _record_getitem(tr, self, idx, res)
        return res

    def __len__(self):
        return int(self._value.shape[0])

    def __bool__(self):
        # eager `if tensor_cond:` must read the VALUE (reference eager
        # semantics); default object truthiness silently took the True
        # branch for any tensor. Multi-element raises like numpy.
        if int(np.prod(self._value.shape)) != 1:
            raise ValueError(
                "The truth value of a multi-element VarBase is ambiguous; "
                "use reductions (any/all) or @declarative for traced "
                "control flow"
            )
        return bool(np.asarray(self._value).reshape(()))

    def __repr__(self):
        return f"VarBase(name={self.name}, shape={self.shape}, dtype={self.dtype})\n{self._value}"


class ParamBase(VarBase):
    """Trainable eager parameter (reference framework.py:5064)."""

    def __init__(self, value, name=None, trainable=True):
        super().__init__(
            value, name=name, persistable=True, stop_gradient=not trainable
        )
        self.trainable = trainable
        self.regularizer = None
        self.need_clip = True
