"""dygraph.base: guard / to_variable / no_grad (reference dygraph/base.py)."""

from __future__ import annotations

import contextlib
import functools

import numpy as np

import jax.numpy as jnp

from .tracer import Tracer, _current, _set_tracer
from .varbase import VarBase


@contextlib.contextmanager
def guard(place=None):
    """Enter eager mode (reference dygraph/base.py guard)."""
    old = _current()
    tracer = Tracer()
    _set_tracer(tracer)
    try:
        yield
    finally:
        _set_tracer(old)


def enabled():
    return _current() is not None


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    arr = jnp.asarray(value)
    return VarBase(arr, name=name, stop_gradient=True)


@contextlib.contextmanager
def no_grad_ctx():
    tr = _current()
    if tr is None:
        yield
        return
    old = tr.enable_grad
    tr.enable_grad = False
    try:
        yield
    finally:
        tr.enable_grad = old


def no_grad(fn=None):
    """Usable as decorator or context manager (fluid parity)."""
    if fn is None:
        return no_grad_ctx()

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad_ctx():
            return fn(*a, **k)

    return wrapper
