"""AST conversion of plain-Python control flow for @declarative.

Reference: dygraph/dygraph_to_static/ — 19 transformer files
(program_translator.py:252 ProgramTranslator, ifelse_transformer.py,
loop_transformer.py, break_continue_transformer.py,
logical_transformer.py) rewrite a dygraph function's source so
`if tensor:` / `while tensor:` / `for`+`break` become cond/while ops.

TPU-native version — ONE transformer pass + runtime dispatch helpers:

  * `if`/`elif`/`else` -> branch closures + `_dy2st_if(cond, t, f)`;
  * `while` -> cond/body closures over explicit loop vars +
    `_dy2st_while`;
  * `for x in range(...)` -> the equivalent while (increment hoisted
    before the body so `continue` stays safe);
  * `break`/`continue` -> boolean flags + guarded tails (the reference's
    break_continue_transformer scheme), folded into the loop condition;
  * `and`/`or`/`not` inside converted conditions -> `_dy2st_and/or/not`
    (logical_transformer parity; evaluation is non-short-circuit on
    tensors, like the reference's logical_and lowering).

The helpers dispatch at RUNTIME on the condition's type, so one
converted body serves every mode:
  * python value     -> ordinary python control flow (closures called
    directly; semantics unchanged);
  * eager VarBase with a CONCRETE value (plain dygraph) -> python
    control flow on bool(value);
  * eager VarBase holding a TRACER (inside @declarative's jit) ->
    lax.cond / lax.while_loop;
  * static-graph Variable -> layers.cond / layers.While ops.

Known limits (documented, reference shares most): a converted `if` must
not contain `return`/`yield` (left as plain python — fine for python
conds); loop-carried variables must be bound before the loop and keep
one shape/dtype; tensor `while` under jit is forward-only
(lax.while_loop has no transpose — use layers.While(max_iters=...) /
StaticRNN for trainable loops, as the While docstring prescribes).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap

__all__ = ["convert_function", "HELPERS"]


# ---------------------------------------------------------------------------
# runtime helpers (injected into the transformed function's globals)
# ---------------------------------------------------------------------------


def _kind(x):
    from ..framework.program import Variable
    from .varbase import VarBase

    if isinstance(x, Variable):
        return "static"
    v = x.value if isinstance(x, VarBase) else x
    import jax

    if isinstance(v, jax.core.Tracer):
        return "tracer"
    if isinstance(v, jax.Array):
        return "eager"
    return "py"


def _as_scalar_bool(x):
    import jax.numpy as jnp

    return jnp.reshape(jnp.asarray(x), ()).astype(bool)


def _unwrap(v):
    from .varbase import VarBase

    return v.value if isinstance(v, VarBase) else v


def _wrap_like(val, proto):
    from .varbase import VarBase

    if isinstance(proto, VarBase):
        return VarBase(val, stop_gradient=proto.stop_gradient)
    return val


def _dy2st_if(cond, true_fn, false_fn):
    """Dispatch a converted `if`. true_fn/false_fn are closures returning
    the tuple of names assigned in either branch."""
    k = _kind(cond)
    if k == "py":
        return true_fn() if cond else false_fn()
    if k == "eager":
        return true_fn() if bool(_unwrap(cond)) else false_fn()
    if k == "static":
        from .. import layers

        outs = layers.cond(cond, lambda: list(true_fn()),
                           lambda: list(false_fn()))
        return tuple(outs) if isinstance(outs, (list, tuple)) else (outs,)

    # tracer: lax.cond with both branches traced functionally
    import jax.numpy as jnp
    from jax import lax

    from .varbase import VarBase

    def run(fn):
        def g(_):
            return tuple(jnp.asarray(_unwrap(o)) for o in fn())

        return g

    try:
        vals = lax.cond(_as_scalar_bool(_unwrap(cond)), run(true_fn),
                        run(false_fn), None)
    except TypeError as e:
        raise TypeError(
            "declarative if-conversion: the two branches must assign "
            "matching shapes/dtypes to every variable written in either "
            f"branch ({e})"
        ) from e
    return tuple(VarBase(v) for v in vals)


def _dy2st_while(cond_fn, body_fn, init):
    """Dispatch a converted `while`. cond_fn/body_fn take the loop vars as
    parameters; body_fn returns their new values."""
    probe = cond_fn(*init)
    k = _kind(probe)
    if k in ("py", "eager"):

        def as_bool(c):
            return bool(_unwrap(c))

        vals = tuple(init)
        c = probe
        while as_bool(c):
            vals = tuple(body_fn(*vals))
            c = cond_fn(*vals)
        return vals
    if k == "static":
        from .. import layers
        from ..framework.program import Variable

        def lift(v, i):
            if isinstance(v, Variable):
                return v
            if isinstance(v, bool):
                return layers.fill_constant([1], "bool", v)
            if isinstance(v, int):
                return layers.fill_constant([1], "int64", v)
            if isinstance(v, float):
                return layers.fill_constant([1], "float32", v)
            raise TypeError(
                "declarative while-conversion (static mode): loop "
                f"variable #{i} is {type(v).__name__}; initialize loop "
                "carries as tensors or python numbers"
            )

        init = tuple(lift(v, i) for i, v in enumerate(init))
        probe = cond_fn(*init)
        cond_var = layers.reshape(layers.cast(probe, "bool"), [1])
        w = layers.While(cond_var)
        with w.block():
            outs = body_fn(*init)
            for old, new in zip(init, outs):
                layers.assign(new, old)
            layers.assign(
                layers.reshape(layers.cast(cond_fn(*init), "bool"), [1]),
                cond_var,
            )
        return tuple(init)

    # tracer: lax.while_loop over unwrapped values (forward-only)
    import jax.numpy as jnp
    from jax import lax

    protos = tuple(init)
    init_vals = tuple(jnp.asarray(_unwrap(v)) for v in init)

    def cond_w(vals):
        wrapped = tuple(_wrap_like(v, p) for v, p in zip(vals, protos))
        return _as_scalar_bool(_unwrap(cond_fn(*wrapped)))

    def body_w(vals):
        wrapped = tuple(_wrap_like(v, p) for v, p in zip(vals, protos))
        outs = body_fn(*wrapped)
        return tuple(
            jnp.asarray(_unwrap(o)).astype(iv.dtype).reshape(iv.shape)
            for o, iv in zip(outs, init_vals)
        )

    vals = lax.while_loop(cond_w, body_w, init_vals)
    return tuple(_wrap_like(v, p) for v, p in zip(vals, protos))


def _logical(op, a, b=None):
    ka = _kind(a)
    kb = _kind(b) if b is not None else "py"
    if ka == "py" and kb == "py":
        if op == "and":
            return a and b
        if op == "or":
            return a or b
        return not a
    if ka == "static" or kb == "static":
        from .. import layers
        from ..framework.program import Variable

        def as_bool_var(x):
            if not isinstance(x, Variable):
                return layers.fill_constant([1], "bool", bool(x))
            return layers.cast(x, "bool")

        if op == "and":
            return layers.logical_and(as_bool_var(a), as_bool_var(b))
        if op == "or":
            return layers.logical_or(as_bool_var(a), as_bool_var(b))
        return layers.logical_not(as_bool_var(a))
    # eager / tracer VarBase (or mixed with python bools)
    import jax.numpy as jnp

    from .varbase import VarBase

    av = _as_scalar_bool(_unwrap(a))
    if op == "not":
        return VarBase(jnp.logical_not(av))
    bv = _as_scalar_bool(_unwrap(b))
    out = jnp.logical_and(av, bv) if op == "and" else jnp.logical_or(av, bv)
    return VarBase(out)


def _dy2st_and(a, b):
    return _logical("and", a, b)


def _dy2st_or(a, b):
    return _logical("or", a, b)


def _dy2st_not(a):
    return _logical("not", a)


HELPERS = {
    "_dy2st_if": _dy2st_if,
    "_dy2st_while": _dy2st_while,
    "_dy2st_and": _dy2st_and,
    "_dy2st_or": _dy2st_or,
    "_dy2st_not": _dy2st_not,
}


# ---------------------------------------------------------------------------
# AST analysis utilities
# ---------------------------------------------------------------------------


def _assigned_names(stmts):
    """Names bound by a statement list (incl. nested, excl. inner defs)."""
    names = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store,)):
                if node.id not in names:
                    names.append(node.id)

        def visit_FunctionDef(self, node):
            # a (generated or user) inner def is a local helper, not data
            # flowing through cond/while; don't descend either
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _contains(stmts, types):
    """Like ast.walk-search, but does NOT descend into nested function
    definitions/lambdas: a Return inside a generated branch closure (or a
    user inner def) must not block converting the enclosing construct."""
    _SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def walk(node):
        if isinstance(node, types):
            return True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPES):
                continue
            if walk(child):
                return True
        return False

    return any(walk(s) for s in stmts)


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _tuple_load(names):
    return ast.Tuple(elts=[_load(n) for n in names], ctx=ast.Load())


def _call(fn_name, args):
    return ast.Call(func=_load(fn_name), args=args, keywords=[])


def _make_fn(name, argnames, body, defaults=None):
    fd = ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=a) for a in argnames],
                           kwonlyargs=[], kw_defaults=[],
                           defaults=list(defaults or [])),
        body=body, decorator_list=[],
    )
    if hasattr(fd, "type_params"):  # py3.12+ field must exist for compile
        fd.type_params = []
    return fd


def _read_before_write(stmts):
    """Names read before being bound in a straight-line statement list —
    exactly the set whose OUTER value a branch closure needs pre-bound.
    Generated inner defs contribute their default-argument expressions
    (evaluated at def site) but not their bodies."""
    _SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    bound, rbw = set(), set()

    def reads_of(node):
        out = set()

        def walk(n):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.add(n.id)
            for c in ast.iter_child_nodes(n):
                if isinstance(c, _SCOPES):
                    if isinstance(c, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        for d in c.args.defaults:
                            walk(d)
                    continue
                walk(c)

        walk(node)
        return out

    def note_reads(names):
        rbw.update(n for n in names if n not in bound)

    for s in stmts:
        if isinstance(s, ast.Assign):
            note_reads(reads_of(s.value))
            bound.update(_assigned_names([s]))
        elif isinstance(s, ast.AugAssign):
            note_reads(reads_of(s))
            bound.update(_assigned_names([s]))
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in s.args.defaults:
                note_reads(reads_of(d))
            bound.add(s.name)
        else:
            # compound / other statements: conservative — everything they
            # read counts, everything they bind becomes bound after
            note_reads(reads_of(s))
            bound.update(_assigned_names([s]))
    return rbw


def _assign_tuple(names, value):
    if not names:
        # still evaluate for side effects
        return ast.Expr(value=value)
    # always a tuple target — the helpers return a tuple even for one name
    target = ast.Tuple(elts=[_store(n) for n in names], ctx=ast.Store())
    return ast.Assign(targets=[target], value=value)


class _CondLogic(ast.NodeTransformer):
    """and/or/not inside a converted condition -> helper calls
    (logical_transformer parity; non-short-circuit on tensors)."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        name = "_dy2st_and" if isinstance(node.op, ast.And) else "_dy2st_or"
        out = node.values[0]
        for v in node.values[1:]:
            out = _call(name, [out, v])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _call("_dy2st_not", [node.operand])
        return node


def _convert_cond_expr(expr):
    return ast.fix_missing_locations(_CondLogic().visit(expr))


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

_LOOP_BLOCKERS = (ast.Return, ast.Yield, ast.YieldFrom, ast.Global,
                  ast.Nonlocal, ast.Try, ast.With)
_IF_BLOCKERS = (ast.Return, ast.Yield, ast.YieldFrom, ast.Global,
                ast.Nonlocal, ast.Break, ast.Continue)


class _BreakContinueElim(ast.NodeTransformer):
    """Replace break/continue with flag assignments and guard the
    remaining statements of each block (break_continue_transformer.py
    scheme). Does not descend into nested loops or function defs."""

    def __init__(self, brk, cont):
        self.brk = brk
        self.cont = cont
        self.used_brk = False
        self.used_cont = False

    def _process_block(self, stmts):
        out = []
        for i, s in enumerate(stmts):
            # containment must be checked BEFORE visit: the transform is
            # in-place and replaces Break/Continue with flag assignments
            had = _contains([s], (ast.Break, ast.Continue))
            out.append(self.visit(s))
            rest = stmts[i + 1:]
            if rest and had:
                guard = _call("_dy2st_not",
                              [_call("_dy2st_or",
                                     [_load(self.brk), _load(self.cont)])])
                out.append(ast.If(test=guard,
                                  body=self._process_block(rest), orelse=[]))
                break
        return out

    def visit_Break(self, node):
        self.used_brk = True
        return ast.Assign(targets=[_store(self.brk)],
                          value=ast.Constant(value=True))

    def visit_Continue(self, node):
        self.used_cont = True
        return ast.Assign(targets=[_store(self.cont)],
                          value=ast.Constant(value=True))

    def visit_While(self, node):  # nested loop owns its own break/continue
        return node

    def visit_For(self, node):
        return node

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node):
        node.body = self._process_block(node.body)
        node.orelse = self._process_block(node.orelse)
        return node


class ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0

    def _uid(self):
        self.n += 1
        return self.n

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _contains([node], _IF_BLOCKERS):
            return node
        uid = self._uid()
        outs = sorted(
            set(_assigned_names(node.body)) | set(_assigned_names(node.orelse))
        )
        t_name, f_name = f"_dy2st_true_{uid}", f"_dy2st_false_{uid}"

        def mk(fname, body):
            body = list(body) or [ast.Pass()]
            # a name both READ and ASSIGNED in the branch would shadow
            # itself as an unbound local; pre-bind the current value as a
            # default argument (ifelse_transformer passes them as inputs)
            pre = sorted(set(_assigned_names(body)) & _read_before_write(body))
            body.append(ast.Return(value=_tuple_load(outs)))
            return _make_fn(fname, pre, body,
                            defaults=[_load(n) for n in pre])

        call = _call("_dy2st_if",
                     [_convert_cond_expr(node.test), _load(t_name),
                      _load(f_name)])
        return [mk(t_name, node.body), mk(f_name, node.orelse),
                _assign_tuple(outs, call)]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _contains([node], _LOOP_BLOCKERS):
            return node
        uid = self._uid()
        pre = []
        test = node.test
        body = list(node.body)
        if _contains(body, (ast.Break, ast.Continue)):
            brk, cont = f"_dy2st_brk_{uid}", f"_dy2st_cont_{uid}"
            elim = _BreakContinueElim(brk, cont)
            body = elim._process_block(body)
            # both flags are initialized whenever either appears: the
            # guards reference them jointly (not (_brk or _cont))
            pre.append(ast.Assign(targets=[_store(brk)],
                                  value=ast.Constant(value=False)))
            pre.append(ast.Assign(targets=[_store(cont)],
                                  value=ast.Constant(value=False)))
            if elim.used_brk:
                test = _call("_dy2st_and",
                             [test, _call("_dy2st_not", [_load(brk)])])
            if elim.used_cont:
                # reset each iteration
                body.insert(0, ast.Assign(targets=[_store(cont)],
                                          value=ast.Constant(value=False)))
            # guards introduced nested Ifs: convert them too
            body = [self.visit(s) for s in body]
            body = [s for grp in body
                    for s in (grp if isinstance(grp, list) else [grp])]
        loop_vars = sorted(set(_assigned_names(body)))
        c_name, b_name = f"_dy2st_cond_{uid}", f"_dy2st_body_{uid}"
        cond_fn = _make_fn(
            c_name, loop_vars,
            [ast.Return(value=_convert_cond_expr(test))],
        )
        body_fn = _make_fn(
            b_name, loop_vars,
            body + [ast.Return(value=_tuple_load(loop_vars))],
        )
        call = _call("_dy2st_while",
                     [_load(c_name), _load(b_name), _tuple_load(loop_vars)])
        return pre + [cond_fn, body_fn, _assign_tuple(loop_vars, call)]

    # -- for over range() -------------------------------------------------
    def visit_For(self, node):
        if not (
            isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and not node.orelse
            and isinstance(node.target, ast.Name)
        ):
            self.generic_visit(node)
            return node
        uid = self._uid()
        r = node.iter.args
        start = r[0] if len(r) >= 2 else ast.Constant(value=0)
        stop = r[1] if len(r) >= 2 else r[0]
        step = r[2] if len(r) >= 3 else ast.Constant(value=1)
        i_name = f"_dy2st_i_{uid}"
        # i = start + _i * step computed at the TOP, then _i advances
        # immediately — `continue` guards never skip the increment
        new_body = [
            ast.Assign(
                targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
                value=ast.BinOp(left=start, op=ast.Add(),
                                right=ast.BinOp(left=_load(i_name),
                                                op=ast.Mult(), right=step)),
            ),
            ast.Assign(targets=[_store(i_name)],
                       value=ast.BinOp(left=_load(i_name), op=ast.Add(),
                                       right=ast.Constant(value=1))),
        ] + list(node.body)
        # trip count (stop-start+step-1)//step — plain arithmetic so a
        # TENSOR stop (for i in range(n_tensor)) stays a tensor and the
        # while condition converts like any tensor condition
        n_name = f"_dy2st_n_{uid}"
        n_stmt = ast.Assign(
            targets=[_store(n_name)],
            value=ast.BinOp(
                left=ast.BinOp(
                    left=ast.BinOp(left=stop, op=ast.Sub(), right=start),
                    op=ast.Add(),
                    right=ast.BinOp(left=step, op=ast.Sub(),
                                    right=ast.Constant(value=1)),
                ),
                op=ast.FloorDiv(), right=step,
            ),
        )
        init = ast.Assign(targets=[_store(i_name)],
                          value=ast.Constant(value=0))
        # pre-bind the loop target (loop vars must exist before the loop)
        tgt_init = ast.Assign(
            targets=[ast.Name(id=node.target.id, ctx=ast.Store())],
            value=start,
        )
        test = ast.Compare(left=_load(i_name), ops=[ast.Lt()],
                           comparators=[_load(n_name)])
        w = ast.While(test=test, body=new_body, orelse=[])
        out = self.visit(w)
        return [n_stmt, init, tgt_init] + (
            out if isinstance(out, list) else [out]
        )


def convert_function(fn):
    """Rewrite fn's plain-Python control flow; returns the converted
    function or None when conversion is unavailable (no source, exotic
    constructs — caller falls back to the original). Bound methods
    convert through their underlying function and re-bind, so
    `declarative(layer.forward)` keeps its `self`."""
    bound_self = fn.__self__ if inspect.ismethod(fn) else None
    if bound_self is not None:
        fn = fn.__func__
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []
    fdef.name = f"_dy2st_{fdef.name}"
    new = ControlFlowTransformer().visit(fdef)
    mod = ast.fix_missing_locations(ast.Module(body=[new], type_ignores=[]))
    # LIVE globals: lookups not shadowed by the helpers fall through to the
    # original function's module dict at CALL time (a snapshot would pin
    # helper functions defined/rebound after decoration — a regression for
    # previously-working @declarative code). Closure cells resolve lazily
    # too, so a self-referential decorated function whose cell is still
    # empty at decoration works once the cell fills.
    cells = dict(zip(fn.__code__.co_freevars, fn.__closure__ or ()))

    class _LiveGlobals(dict):
        def __missing__(self, k):
            if k in cells:
                return cells[k].cell_contents  # ValueError -> NameError-ish
            return fn.__globals__[k]

    ns = _LiveGlobals(HELPERS)
    try:
        code = compile(mod, filename=f"<dy2st {fn.__qualname__}>",
                       mode="exec")
        exec(code, ns)
    except Exception:
        return None
    out = ns[fdef.name]
    out = functools.wraps(fn)(out)
    out._dy2st_converted = True
    if bound_self is not None:
        import types

        out = types.MethodType(out, bound_self)
    return out
