"""Eager-mode tracer: per-op execution + autograd tape.

Reference parity: imperative/tracer.cc:45 (TraceOp), basic_engine.cc:159
(BasicEngine backward walk), gradient_accumulator.cc. TPU-native changes:

* Ops execute through the SAME emitters as the static graph (registry.py) —
  no second kernel set, no core.ops.* codegen (the reference generated
  pybind fast-path functions per op, pybind/op_function_generator.cc).
* Each traced op with grad-requiring inputs runs under jax.vjp; the tape
  stores the vjp closure (residuals live on device). backward() is a
  reverse sweep accumulating into VarBase._grad by addition.
* Per-op jit caching: the emitter call is wrapped in a jit cached on
  (op_type, attrs, input avals), so repeated eager ops hit compiled code —
  the analog of the reference's dygraph kernel cache, but compiled.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.registry import EmitContext, OpView, get_op_def
from .varbase import VarBase

_tracer = None


def _current():
    return _tracer


def _require_tracer():
    if _tracer is None:
        raise RuntimeError("not in dygraph mode; use `with fluid.dygraph.guard():`")
    return _tracer


class TapeEntry:
    __slots__ = ("vjp_fn", "inputs", "outputs")

    def __init__(self, vjp_fn, inputs, outputs):
        self.vjp_fn = vjp_fn  # cotangents(list) -> input grads(list)
        self.inputs = inputs  # [VarBase] needing grad
        self.outputs = outputs  # [VarBase]


class Tracer:
    def __init__(self):
        self._tape = []
        self.enable_grad = True
        self._op_seq = 0
        self.train_mode = True

    # ------------------------------------------------------------------
    def trace_op(self, op_type, ins, attrs, n_outs_hint=None):
        """ins: {slot: [VarBase|None]}. Returns {slot: [VarBase]}."""
        op_def = get_op_def(op_type)
        self._op_seq += 1
        attrs = dict(attrs or {})
        attrs.setdefault("__uid__", self._op_seq)
        view = OpView(op_type, attrs)
        ctx = EmitContext(
            step_key=jax.random.key(attrs.get("seed", 0) or self._op_seq),
            is_test=not self.train_mode,
        )

        flat_in = []  # (slot, idx, VarBase) for grad-requiring inputs
        raw = {}
        for slot, vs in ins.items():
            raw[slot] = [None if v is None else v.value for v in vs]
            for i, v in enumerate(vs):
                if (
                    v is not None
                    and not v.stop_gradient
                    and self.enable_grad
                    and op_def.differentiable
                    and jnp.issubdtype(v.value.dtype, jnp.inexact)
                ):
                    flat_in.append((slot, i, v))

        if not flat_in:
            outs = op_def.emit(ctx, view, raw)
            return self._wrap(outs, stop_gradient=True)

        def fwd(diff_vals):
            merged = {s: list(v) for s, v in raw.items()}
            for (slot, i, _), val in zip(flat_in, diff_vals):
                merged[slot][i] = val
            outs = op_def.emit(ctx, view, merged)
            flat, spec = _flatten_outs(outs)
            return flat, spec

        diff_vals = [v.value for _, _, v in flat_in]
        flat, vjp_fn, spec = jax.vjp(fwd, diff_vals, has_aux=True)
        outs = _unflatten_outs(flat, spec)
        wrapped = self._wrap(outs, stop_gradient=False)

        out_vbs = [v for vs in wrapped.values() for v in vs if v is not None]
        in_vbs = [v for _, _, v in flat_in]
        self._tape.append(TapeEntry(vjp_fn, in_vbs, out_vbs))
        return wrapped

    def _wrap(self, outs, stop_gradient):
        return {
            slot: [
                None if v is None else VarBase(v, stop_gradient=stop_gradient)
                for v in vals
            ]
            for slot, vals in outs.items()
        }

    # ------------------------------------------------------------------
    def run_backward(self, root, retain_graph=False):
        if not jnp.issubdtype(root.value.dtype, jnp.inexact):
            raise ValueError("backward() root must be floating point")
        root._grad = jnp.ones_like(root.value)
        # reverse sweep: outputs' accumulated grads -> vjp -> inputs' grads
        for entry in reversed(self._tape):
            if not any(o._grad is not None for o in entry.outputs):
                continue
            cts = [
                o._grad
                if o._grad is not None
                else jnp.zeros_like(o.value)
                for o in entry.outputs
            ]
            (in_grads,) = entry.vjp_fn(cts)
            for v, g in zip(entry.inputs, in_grads):
                v._grad = g if v._grad is None else v._grad + g
        # free intermediate grads + residuals
        if not retain_graph:
            for entry in self._tape:
                for o in entry.outputs:
                    if not o.persistable:
                        o._grad = None
            self._tape.clear()

    def clear(self):
        self._tape.clear()


def _flatten_outs(outs):
    flat, spec = [], []
    for slot in sorted(outs):
        for i, v in enumerate(outs[slot]):
            if v is not None:
                flat.append(v)
                spec.append((slot, i, True))
            else:
                spec.append((slot, i, False))
    return flat, spec


def _unflatten_outs(flat, spec):
    outs = {}
    it = iter(flat)
    for slot, i, present in spec:
        outs.setdefault(slot, [])
        while len(outs[slot]) <= i:
            outs[slot].append(None)
        if present:
            outs[slot][i] = next(it)
    return outs


def trace_op(op_type, ins, attrs=None, out_slot="Out"):
    """Module-level helper: trace and return the first output of `out_slot`."""
    tr = _require_tracer()
    outs = tr.trace_op(op_type, ins, attrs)
    return outs[out_slot][0]


def trace_op_multi(op_type, ins, attrs=None):
    tr = _require_tracer()
    return tr.trace_op(op_type, ins, attrs)


def _record_getitem(tr, src, idx, res):
    """Autograd through VarBase.__getitem__ (a jax gather)."""
    _, vjp_fn = jax.vjp(lambda v: v[idx], src.value)
    tr._tape.append(
        TapeEntry(lambda cts: ([vjp_fn(cts[0])[0]],), [src], [res])
    )


def _set_tracer(tr):
    global _tracer
    _tracer = tr
    from ..framework import program as _prog

    _prog._set_dygraph_tracer(tr)
