"""Eager-mode tracer: per-op execution + autograd tape.

Reference parity: imperative/tracer.cc:45 (TraceOp), basic_engine.cc:159
(BasicEngine backward walk), gradient_accumulator.cc. TPU-native changes:

* Ops execute through the SAME emitters as the static graph (registry.py) —
  no second kernel set, no core.ops.* codegen (the reference generated
  pybind fast-path functions per op, pybind/op_function_generator.cc).
* Each traced op with grad-requiring inputs runs under jax.vjp; the tape
  stores the vjp closure (residuals live on device). backward() is a
  reverse sweep accumulating into VarBase._grad by addition.
* Per-op jit caching (r4 — measured, not just claimed: the uncached
  tracer paid a fresh jax.vjp trace + op-by-op eager dispatch per op,
  22x the static executor on small shapes, tools/bench_dygraph.py): the
  fused forward+vjp of each op is jax.jit-compiled once per (op_type,
  attrs, input avals) — the vjp closure is a PYTREE (tree_util.Partial
  on older jax, jax._src.api.VJP on 0.9+; detected structurally, never
  by type), so it crosses the jit boundary as residual outputs. backward()
  applies tape closures through one shared jitted apply. This is the
  compiled analog of the reference's generated pybind fast paths
  (op_function_generator.cc) plus its dygraph kernel cache.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.registry import EmitContext, OpView, get_op_def
from .varbase import VarBase

_tracer = None


def _current():
    return _tracer


def _require_tracer():
    if _tracer is None:
        raise RuntimeError("not in dygraph mode; use `with fluid.dygraph.guard():`")
    return _tracer


class TapeEntry:
    __slots__ = ("vjp_fn", "inputs", "outputs")

    def __init__(self, vjp_fn, inputs, outputs):
        self.vjp_fn = vjp_fn  # cotangents(list) -> input grads(list)
        self.inputs = inputs  # [VarBase] needing grad
        self.outputs = outputs  # [VarBase]


class Tracer:
    def __init__(self):
        self._tape = []
        self.enable_grad = True
        self._op_seq = 0
        self.train_mode = True
        # compiled (fwd, fwd+vjp) per (op_type, attrs, avals) — see
        # _build_jitted; False marks signatures jit cannot trace
        self._jit_cache = {}

    # ------------------------------------------------------------------
    def trace_op(self, op_type, ins, attrs, n_outs_hint=None):
        """ins: {slot: [VarBase|None]}. Returns {slot: [VarBase]}."""
        op_def = get_op_def(op_type)
        self._op_seq += 1
        attrs = dict(attrs or {})
        attrs.setdefault("__uid__", self._op_seq)
        seq = int(attrs["__uid__"])
        is_test = not self.train_mode

        flat_in = []  # (slot, idx, VarBase) for grad-requiring inputs
        raw = {}
        for slot, vs in ins.items():
            raw[slot] = [None if v is None else v.value for v in vs]
            for i, v in enumerate(vs):
                if (
                    v is not None
                    and not v.stop_gradient
                    and self.enable_grad
                    and op_def.differentiable
                    and jnp.issubdtype(v.value.dtype, jnp.inexact)
                ):
                    flat_in.append((slot, i, v))
        diff_pos = tuple((slot, i) for slot, i, _ in flat_in)
        diff_vals = [v.value for _, _, v in flat_in]

        # RNG stream: the emitter derives masks from (step_key, uid); the
        # cached trace pins uid=0 and varies the step-key SEED ARGUMENT per
        # occurrence — same per-sequence determinism, one compile
        seed_v = np.uint32(attrs.get("seed", 0) or seq)

        # explicit-seed RNG ops bake seed+uid into the trace
        # (ops/_helpers.py op_key / ctx.key_for) and every occurrence needs
        # a distinct stream — caching would either share one mask across
        # occurrences (uid pinned) or compile per call (uid in the key,
        # _op_seq never repeats). Rare ops; use the uncached path.
        key = (None if attrs.get("seed", 0)
               else self._cache_key(op_type, attrs, is_test, ins, diff_pos))
        entry = self._jit_cache.get(key) if key is not None else None
        if entry is None and key is not None:
            entry = self._build_jitted(op_type, op_def, attrs, is_test,
                                       diff_pos)
            self._jit_cache[key] = entry
        if entry is not None and entry is not False:
            try:
                if flat_in:
                    flat, vjp_fn = entry["fwd_vjp"](diff_vals, raw, seed_v)
                else:
                    flat = entry["fwd"](raw, seed_v)
                    vjp_fn = None
                spec = entry["spec"][0]
            except Exception:
                # an emitter this jit cannot trace (value-dependent python
                # control flow): permanently fall back for this signature
                self._jit_cache[key] = False
                entry = False
        if entry is None or entry is False:
            flat, vjp_fn, spec = self._trace_uncached(
                op_def, op_type, attrs, is_test, raw, flat_in, seq
            )

        outs = _unflatten_outs(flat, spec)
        wrapped = self._wrap(outs, stop_gradient=not flat_in)
        if flat_in:
            out_vbs = [
                v for vs in wrapped.values() for v in vs if v is not None
            ]
            in_vbs = [v for _, _, v in flat_in]
            self._tape.append(TapeEntry(vjp_fn, in_vbs, out_vbs))
        return wrapped

    def _trace_uncached(self, op_def, op_type, attrs, is_test, raw,
                        flat_in, seq):
        """Pre-r4 path: direct (untraced) emitter execution."""
        view = OpView(op_type, attrs)
        ctx = EmitContext(
            step_key=jax.random.key(attrs.get("seed", 0) or seq),
            is_test=is_test,
        )
        if not flat_in:
            outs = op_def.emit(ctx, view, raw)
            flat, spec = _flatten_outs(outs)
            return flat, None, spec

        def fwd(diff_vals):
            merged = {s: list(v) for s, v in raw.items()}
            for (slot, i, _), val in zip(flat_in, diff_vals):
                merged[slot][i] = val
            outs = op_def.emit(ctx, view, merged)
            flat, spec = _flatten_outs(outs)
            return flat, spec

        diff_vals = [v.value for _, _, v in flat_in]
        flat, vjp_fn, spec = jax.vjp(fwd, diff_vals, has_aux=True)
        return flat, vjp_fn, spec

    @staticmethod
    def _cache_key(op_type, attrs, is_test, ins, diff_pos):
        items = []
        for k, v in sorted(attrs.items()):
            # explicit-seed ops never reach here (trace_op routes them to
            # the uncached path), so __uid__ can always be dropped
            if k in ("__uid__", "__loc__"):
                continue
            if isinstance(v, list):
                v = tuple(tuple(e) if isinstance(e, list) else e for e in v)
            elif isinstance(v, np.ndarray):
                v = (v.shape, str(v.dtype), v.tobytes())
            if not isinstance(v, (int, float, bool, str, bytes, tuple,
                                  type(None))):
                return None  # unhashable attr -> uncached path
            items.append((k, v))
        sig = []
        for slot in sorted(ins):
            for i, v in enumerate(ins[slot]):
                sig.append((
                    slot, i,
                    None if v is None
                    else (tuple(v.value.shape), str(v.value.dtype)),
                ))
        key = (op_type, tuple(items), is_test, tuple(sig), diff_pos)
        try:
            hash(key)
        except TypeError:  # nested-unhashable attr survived the guards
            return None
        return key

    def _build_jitted(self, op_type, op_def, attrs, is_test, diff_pos):
        attrs_norm = dict(attrs)
        # one compile serves every occurrence: the RNG stream comes from
        # the seed ARGUMENT (varied per call), not the uid; explicit-seed
        # ops bypass this path entirely (see trace_op)
        attrs_norm["__uid__"] = 0
        view = OpView(op_type, attrs_norm)
        spec_holder = [None]

        def fwd_and_vjp(diff_vals, raw, seed_v):
            ctx = EmitContext(step_key=jax.random.key(seed_v),
                              is_test=is_test)

            def fwd(dv):
                merged = {s: list(v) for s, v in raw.items()}
                for (slot, i), val in zip(diff_pos, dv):
                    merged[slot][i] = val
                outs = op_def.emit(ctx, view, merged)
                flat, spec = _flatten_outs(outs)
                spec_holder[0] = spec  # trace-time capture (static per key)
                return flat

            flat, vjp_fn = jax.vjp(fwd, diff_vals)
            # vjp_fn is a pytree (whatever type this jax returns), so
            # it crosses the jit boundary (residuals as outputs,
            # structure static) — see _apply_vjp's structural detection
            return flat, vjp_fn

        def fwd_only(raw, seed_v):
            ctx = EmitContext(step_key=jax.random.key(seed_v),
                              is_test=is_test)
            outs = op_def.emit(ctx, view, raw)
            flat, spec = _flatten_outs(outs)
            spec_holder[0] = spec
            return flat

        return {
            "fwd_vjp": jax.jit(fwd_and_vjp),
            "fwd": jax.jit(fwd_only),
            "spec": spec_holder,
        }

    def _wrap(self, outs, stop_gradient):
        return {
            slot: [
                None if v is None else VarBase(v, stop_gradient=stop_gradient)
                for v in vals
            ]
            for slot, vals in outs.items()
        }

    # ------------------------------------------------------------------
    def run_backward(self, root, retain_graph=False):
        if not jnp.issubdtype(root.value.dtype, jnp.inexact):
            raise ValueError("backward() root must be floating point")
        root._grad = jnp.ones_like(root.value)
        # reverse sweep: outputs' accumulated grads -> vjp -> inputs' grads
        for entry in reversed(self._tape):
            if not any(o._grad is not None for o in entry.outputs):
                continue
            cts = [
                o._grad
                if o._grad is not None
                else jnp.zeros_like(o.value)
                for o in entry.outputs
            ]
            (in_grads,) = _apply_vjp(entry.vjp_fn, cts)
            for v, g in zip(entry.inputs, in_grads):
                v._grad = g if v._grad is None else v._grad + g
        # free intermediate grads + residuals
        if not retain_graph:
            for entry in self._tape:
                for o in entry.outputs:
                    if not o.persistable:
                        o._grad = None
            self._tape.clear()

    def clear(self):
        self._tape.clear()


# one shared jitted apply for tape closures: jax.vjp's closure is a
# PYTREE (tree_util.Partial historically; jax._src.api.VJP since 0.9 —
# residual arrays as leaves, stable treedef across calls), so jax.jit
# caches per (closure structure, cotangent avals) and the backward sweep
# dispatches compiled code per tape entry. Detection is by pytree-ness,
# not type name: an isinstance(Partial) gate silently routed EVERY
# backward through the eager per-primitive fallback on jax 0.9 (measured
# 87% of the tiny-block step).
_apply_vjp_jit = jax.jit(lambda f, cts: f(cts))
_jittable_closure_types: dict = {}


def _is_pytree_closure(fn):
    t = type(fn)
    ok = _jittable_closure_types.get(t)
    if ok is None:
        # a registered pytree flattens to a non-leaf treedef; a plain
        # python closure is a single opaque leaf
        td = jax.tree_util.tree_structure(fn)
        ok = td != jax.tree_util.tree_structure(0)
        _jittable_closure_types[t] = ok
    return ok


def _apply_vjp(vjp_fn, cts):
    if _is_pytree_closure(vjp_fn):
        return _apply_vjp_jit(vjp_fn, cts)
    return vjp_fn(cts)  # plain python closure (uncached fallback path)


def _flatten_outs(outs):
    flat, spec = [], []
    for slot in sorted(outs):
        for i, v in enumerate(outs[slot]):
            if v is not None:
                flat.append(v)
                spec.append((slot, i, True))
            else:
                spec.append((slot, i, False))
    return flat, spec


def _unflatten_outs(flat, spec):
    outs = {}
    it = iter(flat)
    for slot, i, present in spec:
        outs.setdefault(slot, [])
        while len(outs[slot]) <= i:
            outs[slot].append(None)
        if present:
            outs[slot][i] = next(it)
    return outs


def trace_op(op_type, ins, attrs=None, out_slot="Out"):
    """Module-level helper: trace and return the first output of `out_slot`."""
    tr = _require_tracer()
    outs = tr.trace_op(op_type, ins, attrs)
    return outs[out_slot][0]


def trace_op_multi(op_type, ins, attrs=None):
    tr = _require_tracer()
    return tr.trace_op(op_type, ins, attrs)


def _record_getitem(tr, src, idx, res):
    """Autograd through VarBase.__getitem__ (a jax gather)."""
    _, vjp_fn = jax.vjp(lambda v: v[idx], src.value)
    tr._tape.append(
        TapeEntry(lambda cts: ([vjp_fn(cts[0])[0]],), [src], [res])
    )


def _set_tracer(tr):
    global _tracer
    _tracer = tr
    from ..framework import program as _prog

    _prog._set_dygraph_tracer(tr)
