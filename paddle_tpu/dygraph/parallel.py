"""Eager data parallelism (reference dygraph/parallel.py: DataParallel :225,
scale_loss :292, apply_collective_grads :384; imperative/all_reduce.cc).

TPU-native: the process unit is a host. Within one host, eager DP across
local chips is expressed by running the model per-chip under vmap/shard_map —
but the fluid API contract is per-process: scale the loss by 1/nranks and
allreduce gradients after backward. Multi-host eager jobs hold one process
per host (jax.distributed), and the coalesced allreduce here runs as one
psum over all hosts' devices via a 1-axis mesh; single-process jobs degrade
to identity exactly like the reference with nranks==1.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .layers import Layer


class ParallelEnv:
    """reference dygraph/parallel.py Env: rank info from env vars."""

    def __init__(self):
        import os

        self.nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.local_rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.dev_id = 0
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.trainer_endpoints = [
            e
            for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
            if e
        ]


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()
        self.add_sublayer("_layers", layers)

    @property
    def nranks(self):
        return max(1, getattr(self._strategy, "nranks", 1))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        if self.nranks <= 1:
            return loss
        return loss * (1.0 / self.nranks)

    def apply_collective_grads(self):
        """Coalesced grad allreduce (reference :384 flattens grads into
        buckets before ncclAllReduce; XLA's collective combiner makes
        explicit bucketing unnecessary — one psum per grad is combined by
        the compiler)."""
        if self.nranks <= 1:
            return
        grads = [
            p for p in self.parameters() if p.trainable and p._grad is not None
        ]
        if not grads:
            return
        # Each *process* contributes one gradient, but the mesh spans every
        # device and host-replicated inputs make each process's value appear
        # once per local device — so the psum over-counts by
        # local_device_count; divide it back out to get the per-process sum.
        n_local = jax.local_device_count()
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("hosts",))

        vals = [p._grad for p in grads]

        @jax.jit
        def _psum_all(vs):
            f = jax.shard_map(
                lambda x: [
                    jax.lax.psum(v, "hosts") / n_local for v in x
                ],
                mesh=mesh,
                in_specs=jax.sharding.PartitionSpec(),
                out_specs=jax.sharding.PartitionSpec(),
                check_vma=False,
            )
            return f(vs)

        out = _psum_all(vals)
        for p, g in zip(grads, out):
            p._grad = g

    def state_dict(self, prefix=""):
        return self._layers.state_dict(prefix=prefix)

    def set_dict(self, state, use_structured_name=True):
        self._layers.set_dict(state)

    load_dict = set_dict
