"""Eager data parallelism (reference dygraph/parallel.py: DataParallel :225,
scale_loss :292, apply_collective_grads :384; imperative/all_reduce.cc).

TPU-native: the process unit is a host. Within one host, eager DP across
local chips is expressed by running the model per-chip under vmap/shard_map —
but the fluid API contract is per-process: scale the loss by 1/nranks and
allreduce gradients after backward. Multi-host eager jobs hold one process
per host (jax.distributed), and the coalesced allreduce here runs as one
psum over all hosts' devices via a 1-axis mesh; single-process jobs degrade
to identity exactly like the reference with nranks==1.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .layers import Layer


@functools.lru_cache(maxsize=1)
def _collective_reducer():
    """Module-level (sharding, jitted reducer) pair: one Mesh and one
    compiled reduction per process lifetime — a per-call jit closure would
    retrace every step."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("hosts",))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("hosts")
    )
    replicated = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec()
    )
    n_local = jax.local_device_count()

    @functools.partial(jax.jit, out_shardings=replicated)
    def sum_rows(garr):
        # rows = one copy per device; each process contributed its grad
        # n_local times -> divide to get the per-process SUM
        return jnp.sum(garr, axis=0) / n_local

    return sharding, sum_rows


class ParallelEnv:
    """reference dygraph/parallel.py Env: rank info from env vars."""

    def __init__(self):
        import os

        self.nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.local_rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.dev_id = 0
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.trainer_endpoints = [
            e
            for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
            if e
        ]


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()
        self.add_sublayer("_layers", layers)

    @property
    def nranks(self):
        return max(1, getattr(self._strategy, "nranks", 1))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        if self.nranks <= 1:
            return loss
        return loss * (1.0 / self.nranks)

    def apply_collective_grads(self):
        """Coalesced grad allreduce (reference :384 flattens grads into
        buckets before ncclAllReduce; XLA's collective combiner makes
        explicit bucketing unnecessary — the per-grad reduces below are
        combined by the compiler).

        Multi-process path: each process's local grad becomes one shard of
        a GLOBAL [n_devices, ...] array via
        jax.make_array_from_process_local_data (each local device carries a
        copy of its process's grad), and the cross-process sum is a plain
        axis-0 reduction on the global array — valid on real multi-host
        meshes, no host-replicated-array tricks."""
        if self.nranks <= 1:
            return
        grads = [
            p for p in self.parameters() if p.trainable and p._grad is not None
        ]
        if not grads:
            return
        sharding, sum_rows = _collective_reducer()
        local_devs = jax.local_devices()
        n_total = jax.device_count()
        for p in grads:
            g = jnp.asarray(p._grad)
            # one row per device, each local device holding this process's
            # grad — built from device buffers (device_put fans out without
            # a host round-trip, unlike np.broadcast_to + process_local_data)
            shards = [
                jax.device_put(g[None], d) for d in local_devs
            ]
            garr = jax.make_array_from_single_device_arrays(
                (n_total,) + g.shape, sharding, shards
            )
            out = sum_rows(garr)
            # the reduction output is replicated across ALL processes'
            # devices (non-addressable); downstream eager math needs a
            # process-local array — take this process's replica shard
            # (still a device buffer, no host copy)
            p._grad = jnp.asarray(out.addressable_shards[0].data)

    def state_dict(self, prefix=""):
        return self._layers.state_dict(prefix=prefix)

    def set_dict(self, state, use_structured_name=True):
        self._layers.set_dict(state)

    load_dict = set_dict
