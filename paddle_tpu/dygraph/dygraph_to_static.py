"""dygraph->static translation: @declarative / to_static / ProgramTranslator.

Reference: dygraph/dygraph_to_static/ (19 files) rewrote the Python AST of a
dygraph function into static-graph ops (ProgramTranslator
program_translator.py:252, transformers for if/loop/break/print/call) so
the same source could run eagerly or compile into a ProgramDesc.

TPU-native position: jax.jit *is* the dygraph->static compiler for the
functional subset — tracing the eager emitters once yields the compiled
graph with no source rewriting, and that covers everything the AST
transforms handled EXCEPT data-dependent Python control flow. Data-dependent
control must be expressed with layers.cond / layers.While / StaticRNN (the
structured ops, ops/control_flow.py), which is also what the reference's
transformed AST ultimately lowered to (convert_ifelse -> cond op,
convert_while -> while op). @declarative here:

  * eager mode: traces the function through jax.jit on first call per
    input-shape set and runs the cached executable after (per-call python
    dispatch drops to one jitted call);
  * static mode (no tracer active): runs the function as ordinary
    layer-building code, exactly like the reference's static branch;
  * raises a targeted error when a python `if`/`while` touches a traced
    value, pointing at the structured-control-flow APIs.
"""

from __future__ import annotations

import functools

import jax

from ..framework.program import _current_tracer
from .varbase import VarBase


class ProgramTranslator:
    """reference program_translator.py:252 singleton enable/disable."""

    _instance = None

    def __init__(self):
        self.enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag=True):
        self.enabled = bool(flag)


def declarative(fn=None):
    """Decorator (reference @declarative / @paddle.jit.to_static)."""
    if fn is None:
        return declarative

    cache = {}

    @functools.wraps(fn)
    def wrapper(*args):
        tracer = _current_tracer()
        if tracer is None or not ProgramTranslator.get_instance().enabled:
            # static mode (or translation disabled): plain call
            return fn(*args)

        var_args = [a for a in args if isinstance(a, VarBase)]
        # non-tensor args are baked into the trace: key the cache on them
        # too, or f(x, 2.0) then f(x, 3.0) would replay the 2.0 trace
        sig = (
            tuple(
                (tuple(a.value.shape), str(a.value.dtype)) for a in var_args
            ),
            tuple(
                repr(a) for a in args if not isinstance(a, VarBase)
            ),
        )
        if sig not in cache:
            struct = {}  # filled during the (single) trace of the body

            def pure(vals):
                it = iter(vals)
                inner = [
                    VarBase(next(it)) if isinstance(a, VarBase) else a
                    for a in args
                ]
                from .base import no_grad_ctx

                with no_grad_ctx():
                    out = fn(*inner)
                struct["seq"] = isinstance(out, (list, tuple))
                outs = out if struct["seq"] else [out]
                return [o.value for o in outs]

            cache[sig] = (jax.jit(pure), struct)

        jitted, struct = cache[sig]
        try:
            out_vals = jitted([a.value for a in var_args])
        except (
            jax.errors.TracerBoolConversionError,
            jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError,
        ) as e:
            cache.pop(sig, None)
            raise RuntimeError(
                "declarative: the function depends on concrete traced "
                "values in python (if/while/np conversion over tensors). "
                "Express data-dependent control flow with layers.cond / "
                "layers.While / StaticRNN — the reference's AST transforms "
                "lowered to the same structured ops."
            ) from e
        outs = [VarBase(v) for v in out_vals]
        return outs if struct["seq"] else outs[0]

    wrapper._is_declarative = True
    return wrapper


to_static = declarative
