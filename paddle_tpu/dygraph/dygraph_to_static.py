"""dygraph->static translation: @declarative / to_static / ProgramTranslator.

Reference: dygraph/dygraph_to_static/ (19 files) rewrote the Python AST of a
dygraph function into static-graph ops (ProgramTranslator
program_translator.py:252, transformers for if/loop/break/print/call) so
the same source could run eagerly or compile into a ProgramDesc.

TPU-native position: jax.jit *is* the dygraph->static compiler for the
functional subset — tracing the eager emitters once yields the compiled
graph with no source rewriting. Data-dependent Python control flow must use
layers.cond / layers.While / StaticRNN (ops/control_flow.py), which is also
what the reference's transformed AST lowered to (convert_ifelse -> cond op).

Semantics:
  * static mode (no tracer): plain layer-building call, like the
    reference's static branch;
  * eager inference (no grad-requiring inputs): one cached jitted
    executable per (tensor-shape, static-arg) signature — the python body
    runs once per signature;
  * eager TRAINING: Layer parameters (of Layer args / bound methods) are
    lifted to traced inputs, and a boundary jax.vjp links the whole
    compiled region into the autograd tape, so loss.backward() reaches the
    parameters exactly as in undecorated eager code (the vjp re-traces per
    step; the reference's to_static similarly rebuilt its backward program).
"""

from __future__ import annotations

import functools

import jax

from ..framework.program import _current_tracer
from .varbase import VarBase


class ProgramTranslator:
    """reference program_translator.py:252 singleton enable/disable."""

    _instance = None

    def __init__(self):
        self.enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag=True):
        self.enabled = bool(flag)


_TRACE_ERRORS = (
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerArrayConversionError,
    jax.errors.ConcretizationTypeError,
)

_TRACE_HINT = (
    "declarative: the function depends on concrete traced values in python "
    "(if/while/np conversion over tensors). Express data-dependent control "
    "flow with layers.cond / layers.While / StaticRNN — the reference's AST "
    "transforms lowered to the same structured ops."
)


def _collect_params(args):
    """Parameters of every Layer argument (incl. `self` of bound methods):
    they must be traced INPUTS, not baked constants, or calls after an
    optimizer step would replay stale weights.

    LIMITATION: a Layer reached only through a closure (not passed as an
    argument) cannot be detected — its weights bake into the trace as
    constants and never update/receive grads. Pass the Layer (or use a
    method `@declarative def forward(self, x)`), like the reference's
    to_static which bound to a Layer instance."""
    from .layers import Layer

    params = {}
    for i, a in enumerate(args):
        if isinstance(a, Layer):
            for name, p in a.named_parameters():
                params[f"{i}:{name}"] = p
    return params


def declarative(fn=None):
    """Decorator (reference @declarative / @paddle.jit.to_static).

    Plain-Python control flow over tensors (if/while/for+break/continue)
    is AST-converted up front (ast_transform.py — the reference's
    dygraph_to_static transformer stack): the converted body dispatches
    per condition type, so python conditions run unchanged, tensor
    conditions lower to lax.cond/while_loop under the jit trace and to
    layers.cond/While ops in static mode. When the source is unavailable
    (REPL, C callables) the original function is used — the functional
    subset still works, tensor-dependent python branching then raises
    the hint below."""
    if fn is None:
        return declarative

    from .ast_transform import convert_function

    converted = convert_function(fn)
    run_fn = converted if converted is not None else fn
    # declarative(layer.forward): the Layer is the method's __self__, not
    # an argument — its parameters must still become traced inputs or
    # they bake into the jit as constants (no grads, stale weights after
    # an optimizer step)
    bound_owner = getattr(fn, "__self__", None)

    cache = {}

    @functools.wraps(fn)
    def wrapper(*args):
        tracer = _current_tracer()
        if tracer is None or not ProgramTranslator.get_instance().enabled:
            # static mode: layer-building call (converted so a python `if`
            # over a static Variable builds cond/while ops)
            return run_fn(*args)

        from .layers import Layer

        params = _collect_params(args)
        if isinstance(bound_owner, Layer):
            for name, p in bound_owner.named_parameters():
                params[f"self:{name}"] = p
        var_args = [a for a in args if isinstance(a, VarBase)]
        var_pos = [i for i, a in enumerate(args) if isinstance(a, VarBase)]
        # static (non-tensor) args are captured for the trace closure — but
        # NOT the input VarBases, which would pin the first call's tensors
        static_args = {
            i: a for i, a in enumerate(args) if not isinstance(a, VarBase)
        }
        n_args = len(args)
        # cache key: tensor positions+shapes, static args with their
        # positions, Layer identities (two same-shaped Layers must not share
        # a trace), and the parameter set
        sig = (
            tuple(
                (i, tuple(a.value.shape), str(a.value.dtype))
                for i, a in enumerate(args)
                if isinstance(a, VarBase)
            ),
            tuple(
                (i, id(a) if isinstance(a, Layer) else repr(a))
                for i, a in sorted(static_args.items())
            ),
            tuple(sorted(params)),
        )

        struct = {}

        def pure(param_vals, vals):
            originals = {n: p._value for n, p in params.items()}
            try:
                for n, p in params.items():
                    p._value = param_vals[n]
                it = iter(vals)
                inner = [
                    static_args[i] if i in static_args else VarBase(next(it))
                    for i in range(n_args)
                ]
                from .base import no_grad_ctx

                # no tape entries inside: grads are handled at the boundary
                with no_grad_ctx():
                    out = run_fn(*inner)
                struct["seq"] = isinstance(out, (list, tuple))
                outs = out if struct["seq"] else [out]
                return [o.value for o in outs]
            finally:
                for n, p in params.items():
                    p._value = originals[n]

        param_vals = {n: p.value for n, p in params.items()}
        in_vals = [a.value for a in var_args]

        want_grad = tracer.enable_grad
        grad_pnames = [
            n for n, p in sorted(params.items()) if not p.stop_gradient
        ] if want_grad else []
        grad_var_idx = [
            i for i, a in enumerate(var_args) if not a.stop_gradient
        ] if want_grad else []

        try:
            if not grad_pnames and not grad_var_idx:
                if sig not in cache:
                    cache[sig] = (jax.jit(pure), None, struct)
                jitted, _, struct = cache[sig]  # struct persists across hits
                out_vals = jitted(param_vals, in_vals)
                outs = [VarBase(v) for v in out_vals]
            else:
                # training: both directions XLA-compiled and cached; the
                # backward recomputes the forward inside its own executable
                # (rematerialized boundary vjp — stable cache, no per-step
                # python re-trace)
                if sig not in cache or cache[sig][1] is None:
                    fwd = jax.jit(pure)

                    def bwd(pv, v, cts):
                        _, vjp_fn = jax.vjp(pure, pv, v)
                        return vjp_fn(cts)

                    cache[sig] = (fwd, jax.jit(bwd), struct)
                fwd, bwd, struct = cache[sig]
                out_vals = fwd(param_vals, in_vals)
                outs = [VarBase(v, stop_gradient=False) for v in out_vals]
                grad_inputs = [params[n] for n in grad_pnames] + [
                    var_args[i] for i in grad_var_idx
                ]

                def tape_fn(cts, _pv=param_vals, _iv=in_vals):
                    pg, vg = bwd(_pv, _iv, list(cts))
                    # 1-tuple: Tracer.run_backward unpacks
                    # `(in_grads,) = entry.vjp_fn(cts)` (tracer.py:129)
                    return (
                        [pg[n] for n in grad_pnames]
                        + [vg[i] for i in grad_var_idx],
                    )

                from .tracer import TapeEntry

                tracer._tape.append(TapeEntry(tape_fn, grad_inputs, outs))
        except _TRACE_ERRORS as e:
            cache.pop(sig, None)
            raise RuntimeError(_TRACE_HINT) from e

        return outs if struct.get("seq") else outs[0]

    wrapper._is_declarative = True
    return wrapper


to_static = declarative
