// C inference API over the embedded Python Predictor.
//
// Reference parity: inference/capi/c_api.cc + pd_predictor.cc front the
// C++ AnalysisPredictor; this fronts paddle_tpu.inference.Predictor. The
// C++ side only marshals buffers — tensor conversion and the actual run
// happen in one embedded helper (_HELPER below) so the numpy C API is
// never needed.

#include "paddle_tpu_capi.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  g_last_error = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

const char* kHelper = R"PY(
import numpy as np
import paddle_tpu
from paddle_tpu.inference import AnalysisConfig, PaddleTensor, Predictor

_DT = {0: "float32", 1: "int32", 2: "int64", 3: "uint8"}
_DT_INV = {v: k for k, v in _DT.items()}


def new_predictor(model_dir, model_file, params_file):
    cfg = AnalysisConfig(model_dir)
    if model_file:
        cfg.model_file = model_file
    if params_file:
        cfg.params_file = params_file
    return Predictor(cfg)


def run(predictor, names, dtypes, shapes, views):
    by_name = {}
    for name, dt, shape, view in zip(names, dtypes, shapes, views):
        arr = np.frombuffer(view, dtype=_DT[dt]).reshape(shape)
        by_name[name] = PaddleTensor(arr, name=name)
    # Predictor.run takes tensors in feed order; C callers pass any order
    inputs = [by_name[n] for n in predictor.get_input_names()]
    outs = predictor.run(inputs)
    result = []
    for t in outs:
        a = np.ascontiguousarray(t.as_ndarray())
        if a.dtype.name not in _DT_INV:
            a = a.astype("float32")
        result.append(
            (t.name, _DT_INV[a.dtype.name], list(a.shape), a.tobytes())
        )
    return result


def run_zero_copy(predictor, names, dtypes, shapes, views):
    # inputs: np.frombuffer over the caller's memory — no staging copy
    by_name = {}
    for name, dt, shape, view in zip(names, dtypes, shapes, views):
        arr = np.frombuffer(view, dtype=_DT[dt]).reshape(shape)
        by_name[name] = PaddleTensor(arr, name=name)
    inputs = [by_name[n] for n in predictor.get_input_names()]
    out_names, arrays = predictor.run_zero_copy(inputs)
    kept, result = [], []
    for n, a in zip(out_names, arrays):
        if a.dtype.name not in _DT_INV:
            a = np.ascontiguousarray(a.astype("float32"))
        kept.append(a)
        result.append(
            (n, _DT_INV[a.dtype.name], list(a.shape), a.ctypes.data,
             a.nbytes)
        )
    # outputs stay alive on the predictor until its next run — the C side
    # reads the buffers in place (PD_TensorC.data borrows them)
    predictor._last_outputs = kept
    return result
)PY";

PyObject* g_helper = nullptr;  // module holding kHelper's globals
std::mutex g_init_mutex;

bool ensure_python() {
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // the PyGILState_Ensure/Release pairs below work from ANY thread
    // (otherwise a second thread deadlocks in Ensure forever)
    PyEval_SaveThread();
  }
  if (g_helper == nullptr) {
    PyGILState_STATE gil = PyGILState_Ensure();
    PyObject* mod = PyModule_New("paddle_tpu_capi_helper");
    PyObject* globals = PyModule_GetDict(mod);
    PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
    PyObject* res =
        PyRun_String(kHelper, Py_file_input, globals, globals);
    if (res == nullptr) {
      set_error_from_python();
      Py_DECREF(mod);
      PyGILState_Release(gil);
      return false;
    }
    Py_DECREF(res);
    g_helper = mod;
    PyGILState_Release(gil);
  }
  return true;
}

PyObject* helper_fn(const char* name) {
  return PyObject_GetAttrString(g_helper, name);
}

}  // namespace

struct PD_AnalysisConfig {
  std::string model_dir;
  std::string model_file;
  std::string params_file;
};

struct PD_Predictor {
  PyObject* py;  // paddle_tpu.inference.Predictor
  std::vector<std::string> input_names;
  std::vector<std::string> output_names;
};

extern "C" {

PD_AnalysisConfig* PD_NewAnalysisConfig(void) {
  return new PD_AnalysisConfig();
}

void PD_DeleteAnalysisConfig(PD_AnalysisConfig* config) { delete config; }

void PD_SetModel(PD_AnalysisConfig* config, const char* model_dir,
                 const char* model_file, const char* params_file) {
  config->model_dir = model_dir != nullptr ? model_dir : "";
  config->model_file = model_file != nullptr ? model_file : "";
  config->params_file = params_file != nullptr ? params_file : "";
}

PD_Predictor* PD_NewPredictor(const PD_AnalysisConfig* config) {
  if (!ensure_python()) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PD_Predictor* out = nullptr;
  PyObject* fn = helper_fn("new_predictor");
  PyObject* py = fn != nullptr
                     ? PyObject_CallFunction(
                           fn, "sss", config->model_dir.c_str(),
                           config->model_file.c_str(),
                           config->params_file.c_str())
                     : nullptr;
  if (py == nullptr) {
    set_error_from_python();
  } else {
    out = new PD_Predictor();
    out->py = py;
    bool names_ok = true;
    for (const char* which : {"get_input_names", "get_output_names"}) {
      PyObject* names = PyObject_CallMethod(py, which, nullptr);
      auto& dst = std::strcmp(which, "get_input_names") == 0
                      ? out->input_names
                      : out->output_names;
      if (names == nullptr) {
        set_error_from_python();
        names_ok = false;
        break;
      }
      if (!PyList_Check(names)) {
        g_last_error = "predictor name query did not return a list";
        Py_DECREF(names);
        names_ok = false;
        break;
      }
      for (Py_ssize_t i = 0; names_ok && i < PyList_Size(names); ++i) {
        const char* s = PyUnicode_AsUTF8(PyList_GetItem(names, i));
        if (s == nullptr) {
          set_error_from_python();
          names_ok = false;
          break;
        }
        dst.push_back(s);
      }
      Py_DECREF(names);
      if (!names_ok) break;
    }
    if (!names_ok) {
      // a predictor with unknown inputs/outputs violates the header
      // contract (PD_GetInputName would index an empty vector) — fail loud
      Py_DECREF(py);
      delete out;
      out = nullptr;
    }
  }
  Py_XDECREF(fn);
  PyGILState_Release(gil);
  return out;
}

void PD_DeletePredictor(PD_Predictor* predictor) {
  if (predictor == nullptr) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(predictor->py);
  PyGILState_Release(gil);
  delete predictor;
}

int PD_GetInputNum(const PD_Predictor* p) {
  return static_cast<int>(p->input_names.size());
}

int PD_GetOutputNum(const PD_Predictor* p) {
  return static_cast<int>(p->output_names.size());
}

const char* PD_GetInputName(const PD_Predictor* p, int i) {
  return p->input_names[i].c_str();
}

const char* PD_GetOutputName(const PD_Predictor* p, int i) {
  return p->output_names[i].c_str();
}

bool PD_PredictorRun(PD_Predictor* predictor, const PD_TensorC* inputs,
                     int in_size, PD_TensorC** outputs, int* out_size) {
  PyGILState_STATE gil = PyGILState_Ensure();
  bool ok = false;
  PyObject *names = PyList_New(in_size), *dtypes = PyList_New(in_size),
           *shapes = PyList_New(in_size), *views = PyList_New(in_size);
  for (int i = 0; i < in_size; ++i) {
    const PD_TensorC& t = inputs[i];
    PyList_SetItem(names, i, PyUnicode_FromString(t.name));
    PyList_SetItem(dtypes, i, PyLong_FromLong(t.dtype));
    PyObject* shp = PyTuple_New(t.rank);
    for (int d = 0; d < t.rank; ++d) {
      PyTuple_SetItem(shp, d, PyLong_FromLongLong(t.shape[d]));
    }
    PyList_SetItem(shapes, i, shp);
    PyList_SetItem(
        views, i,
        PyMemoryView_FromMemory(static_cast<char*>(t.data),
                                static_cast<Py_ssize_t>(t.byte_size),
                                PyBUF_READ));
  }
  PyObject* fn = helper_fn("run");
  PyObject* res =
      fn != nullptr ? PyObject_CallFunctionObjArgs(
                          fn, predictor->py, names, dtypes, shapes, views,
                          nullptr)
                    : nullptr;
  if (res == nullptr) {
    set_error_from_python();
  } else {
    int n = static_cast<int>(PyList_Size(res));
    PD_TensorC* outs = new PD_TensorC[n]();
    bool unpack_ok = true;
    for (int i = 0; i < n && unpack_ok; ++i) {
      PyObject* item = PyList_GetItem(res, i);  // (name, dtype, shape, bytes)
      // a malformed helper result must surface as an error, not a
      // strlen(nullptr) crash (advisor r2): null-check every element
      const char* nm =
          item != nullptr && PyTuple_Check(item) && PyTuple_Size(item) == 4
              ? PyUnicode_AsUTF8(PyTuple_GetItem(item, 0))
              : nullptr;
      if (nm == nullptr) {
        set_error_from_python();
        unpack_ok = false;
        break;
      }
      char* nm_copy = new char[std::strlen(nm) + 1];
      std::strcpy(nm_copy, nm);
      outs[i].name = nm_copy;
      outs[i].dtype =
          static_cast<PD_DataType>(PyLong_AsLong(PyTuple_GetItem(item, 1)));
      PyObject* shp = PyTuple_GetItem(item, 2);
      if (shp == nullptr || !PyList_Check(shp)) {
        set_error_from_python();
        unpack_ok = false;
        break;
      }
      outs[i].rank = static_cast<int>(PyList_Size(shp));
      int64_t* sh = new int64_t[outs[i].rank];
      for (int d = 0; d < outs[i].rank; ++d) {
        sh[d] = PyLong_AsLongLong(PyList_GetItem(shp, d));
      }
      outs[i].shape = sh;
      PyObject* payload = PyTuple_GetItem(item, 3);
      char* buf = nullptr;
      Py_ssize_t len = 0;
      if (payload == nullptr ||
          PyBytes_AsStringAndSize(payload, &buf, &len) != 0) {
        set_error_from_python();
        unpack_ok = false;
        break;
      }
      outs[i].byte_size = static_cast<size_t>(len);
      outs[i].data = new char[len];
      std::memcpy(outs[i].data, buf, static_cast<size_t>(len));
    }
    if (unpack_ok) {
      *outputs = outs;
      *out_size = n;
      ok = true;
    } else {
      for (int i = 0; i < n; ++i) {
        delete[] outs[i].name;
        delete[] outs[i].shape;
        delete[] static_cast<char*>(outs[i].data);
      }
      delete[] outs;
    }
    Py_DECREF(res);
  }
  Py_XDECREF(fn);
  Py_XDECREF(names);
  Py_XDECREF(dtypes);
  Py_XDECREF(shapes);
  Py_XDECREF(views);
  PyGILState_Release(gil);
  return ok;
}

void PD_FreeOutputs(PD_TensorC* outputs, int out_size) {
  if (outputs == nullptr) return;
  for (int i = 0; i < out_size; ++i) {
    delete[] outputs[i].name;
    delete[] outputs[i].shape;
    delete[] static_cast<char*>(outputs[i].data);
  }
  delete[] outputs;
}

bool PD_ZeroCopyRun(PD_Predictor* predictor, const PD_TensorC* inputs,
                    int in_size, PD_TensorC** outputs, int* out_size) {
  PyGILState_STATE gil = PyGILState_Ensure();
  bool ok = false;
  PyObject *names = PyList_New(in_size), *dtypes = PyList_New(in_size),
           *shapes = PyList_New(in_size), *views = PyList_New(in_size);
  for (int i = 0; i < in_size; ++i) {
    const PD_TensorC& t = inputs[i];
    PyList_SetItem(names, i, PyUnicode_FromString(t.name));
    PyList_SetItem(dtypes, i, PyLong_FromLong(t.dtype));
    PyObject* shp = PyTuple_New(t.rank);
    for (int d = 0; d < t.rank; ++d) {
      PyTuple_SetItem(shp, d, PyLong_FromLongLong(t.shape[d]));
    }
    PyList_SetItem(shapes, i, shp);
    PyList_SetItem(
        views, i,
        PyMemoryView_FromMemory(static_cast<char*>(t.data),
                                static_cast<Py_ssize_t>(t.byte_size),
                                PyBUF_READ));
  }
  PyObject* fn = helper_fn("run_zero_copy");
  PyObject* res =
      fn != nullptr ? PyObject_CallFunctionObjArgs(
                          fn, predictor->py, names, dtypes, shapes, views,
                          nullptr)
                    : nullptr;
  if (res == nullptr) {
    set_error_from_python();
  } else {
    int n = static_cast<int>(PyList_Size(res));
    PD_TensorC* outs = new PD_TensorC[n]();
    bool unpack_ok = true;
    for (int i = 0; i < n && unpack_ok; ++i) {
      // (name, dtype, shape, addr, nbytes) — addr borrows the buffer the
      // predictor keeps alive until its next run
      PyObject* item = PyList_GetItem(res, i);
      const char* nm =
          item != nullptr && PyTuple_Check(item) && PyTuple_Size(item) == 5
              ? PyUnicode_AsUTF8(PyTuple_GetItem(item, 0))
              : nullptr;
      if (nm == nullptr) {
        set_error_from_python();
        unpack_ok = false;
        break;
      }
      char* nm_copy = new char[std::strlen(nm) + 1];
      std::strcpy(nm_copy, nm);
      outs[i].name = nm_copy;
      outs[i].dtype =
          static_cast<PD_DataType>(PyLong_AsLong(PyTuple_GetItem(item, 1)));
      PyObject* shp = PyTuple_GetItem(item, 2);
      if (shp == nullptr || !PyList_Check(shp)) {
        set_error_from_python();
        unpack_ok = false;
        break;
      }
      outs[i].rank = static_cast<int>(PyList_Size(shp));
      int64_t* sh = new int64_t[outs[i].rank];
      for (int d = 0; d < outs[i].rank; ++d) {
        sh[d] = PyLong_AsLongLong(PyList_GetItem(shp, d));
      }
      outs[i].shape = sh;
      outs[i].data = reinterpret_cast<void*>(
          PyLong_AsUnsignedLongLong(PyTuple_GetItem(item, 3)));
      outs[i].byte_size = static_cast<size_t>(
          PyLong_AsUnsignedLongLong(PyTuple_GetItem(item, 4)));
    }
    if (unpack_ok) {
      *outputs = outs;
      *out_size = n;
      ok = true;
    } else {
      for (int i = 0; i < n; ++i) {
        delete[] outs[i].name;
        delete[] outs[i].shape;
      }
      delete[] outs;
    }
    Py_DECREF(res);
  }
  Py_XDECREF(fn);
  Py_XDECREF(names);
  Py_XDECREF(dtypes);
  Py_XDECREF(shapes);
  Py_XDECREF(views);
  PyGILState_Release(gil);
  return ok;
}

void PD_FreeZeroCopyOutputs(PD_TensorC* outputs, int out_size) {
  if (outputs == nullptr) return;
  for (int i = 0; i < out_size; ++i) {
    delete[] outputs[i].name;
    delete[] outputs[i].shape;
    // data is predictor-owned: NOT freed here
  }
  delete[] outputs;
}

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

}  // extern "C"
