/* C inference API (reference paddle/fluid/inference/capi/paddle_c_api.h).
 *
 * The reference's C API fronts its C++ AnalysisPredictor; here it fronts
 * the Python Predictor (paddle_tpu/inference.py) by embedding CPython —
 * the XLA/TPU runtime lives behind the interpreter, so a C deployment
 * links this library (built with `python3-config --embed` flags) and gets
 * the same compiled-program cache the Python API uses.
 *
 * Threading: calls may come from any thread; the library takes the GIL
 * per call. One interpreter per process (PD_* objects are process-global).
 */

#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdbool.h>
#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum PD_DataType {
  PD_FLOAT32 = 0,
  PD_INT32 = 1,
  PD_INT64 = 2,
  PD_UINT8 = 3,
  PD_UNKDTYPE = 4
} PD_DataType;

typedef struct PD_AnalysisConfig PD_AnalysisConfig;
typedef struct PD_Predictor PD_Predictor;

/* A tensor crossing the C boundary. For inputs, all fields are caller
 * owned. For outputs, name/shape/data are library-allocated; release the
 * whole batch with PD_FreeOutputs. */
typedef struct PD_TensorC {
  const char* name;
  PD_DataType dtype;
  const int64_t* shape;
  int rank;
  void* data;        /* contiguous, C order */
  size_t byte_size;
} PD_TensorC;

/* -- config ------------------------------------------------------------ */
PD_AnalysisConfig* PD_NewAnalysisConfig(void);
void PD_DeleteAnalysisConfig(PD_AnalysisConfig* config);
/* model_dir: directory from save_inference_model; model_file /
 * params_file: optional file names inside it (NULL for defaults). */
void PD_SetModel(PD_AnalysisConfig* config, const char* model_dir,
                 const char* model_file, const char* params_file);

/* -- predictor --------------------------------------------------------- */
/* NULL on failure; PD_GetLastError() describes why. */
PD_Predictor* PD_NewPredictor(const PD_AnalysisConfig* config);
void PD_DeletePredictor(PD_Predictor* predictor);

int PD_GetInputNum(const PD_Predictor* predictor);
int PD_GetOutputNum(const PD_Predictor* predictor);
/* Returned strings are owned by the predictor. */
const char* PD_GetInputName(const PD_Predictor* predictor, int index);
const char* PD_GetOutputName(const PD_Predictor* predictor, int index);

/* Run a batch. On success returns true and fills *outputs (library
 * allocated array of *out_size tensors). */
bool PD_PredictorRun(PD_Predictor* predictor, const PD_TensorC* inputs,
                     int in_size, PD_TensorC** outputs, int* out_size);
void PD_FreeOutputs(PD_TensorC* outputs, int out_size);

/* Zero-copy run (reference ZeroCopyTensor,
 * inference/api/details/zero_copy_tensor.cc): input buffers are read IN
 * PLACE (no staging copy — keep them alive until this call returns), and
 * each output's `data` points INTO a buffer owned by the predictor, valid
 * until the next run on this predictor or PD_DeletePredictor. Release the
 * metadata (NOT the data) with PD_FreeZeroCopyOutputs. */
bool PD_ZeroCopyRun(PD_Predictor* predictor, const PD_TensorC* inputs,
                    int in_size, PD_TensorC** outputs, int* out_size);
void PD_FreeZeroCopyOutputs(PD_TensorC* outputs, int out_size);

/* Last error message for this thread's most recent failed call ("" if
 * none). Owned by the library. */
const char* PD_GetLastError(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H_ */
