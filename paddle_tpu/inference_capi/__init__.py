"""C inference API build helper (reference inference/capi/ is compiled into
libpaddle's CMake build; here the library is built on demand with
`python3-config --embed` link flags, since the C API hosts the Python/XLA
runtime in-process). See paddle_tpu_capi.h for the surface.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "capi.cpp")
_HDR = os.path.join(_HERE, "paddle_tpu_capi.h")
_LIB = os.path.join(_HERE, "libpaddle_tpu_capi.so")


def _embed_flags():
    """Compiler/linker flags to embed this interpreter."""
    inc = sysconfig.get_path("include")
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    cflags = [f"-I{inc}"]
    ldflags = [f"-L{libdir}", f"-lpython{ver}"] if libdir else [
        f"-lpython{ver}"
    ]
    return cflags, ldflags


def header_path():
    return _HDR


def build_capi(force=False):
    """Compile libpaddle_tpu_capi.so (cached by source mtime); returns its
    path. Raises on compile failure (g++ is in the base image)."""
    if (
        not force
        and os.path.exists(_LIB)
        and os.path.getmtime(_LIB) >= max(
            os.path.getmtime(_SRC), os.path.getmtime(_HDR)
        )
    ):
        return _LIB
    cflags, ldflags = _embed_flags()
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{_HERE}", *cflags, _SRC, "-o", _LIB, *ldflags,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _LIB
