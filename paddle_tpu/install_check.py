"""Installation sanity check (reference
python/paddle/fluid/install_check.py:46 run_check): build a tiny linear
model, train two steps on the default device, and — when more than one
device is visible — repeat under data-parallel SPMD, printing a success
message or raising with a pointer at what is broken.
"""

from __future__ import annotations

import numpy as np


def run_check():
    """Verify the installation end-to-end; prints progress like the
    reference and returns True on success."""
    import jax

    print("Running verify paddle_tpu program ... ")
    from . import Executor, Program, data, program_guard
    from . import layers, optimizer
    from .framework.scope import Scope

    np_inp = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)

    def train_once(mesh=None):
        main, startup = Program(), Program()
        main.random_seed = startup.random_seed = 1
        scope = Scope()
        with program_guard(main, startup):
            x = data("inp", [2, 2])
            pred = layers.fc(x, 4)
            loss = layers.reduce_mean(pred)
            optimizer.SGD(0.001).minimize(loss, startup)
            if mesh is not None:
                from .parallel import shard_program

                shard_program(main, mesh, {"inp": ("dp",)})
        exe = Executor()
        exe.run(startup, scope=scope)
        inp = np_inp
        if mesh is not None:
            n = mesh.devices.size
            inp = np.tile(np_inp, (n, 1))
        for _ in range(2):
            (lv,) = exe.run(main, feed={"inp": inp}, fetch_list=[loss],
                            scope=scope)
        assert np.isfinite(np.asarray(lv)).all()

    train_once()
    print(" - single-device program ran 2 steps OK "
          f"(backend: {jax.default_backend()})")
    if len(jax.devices()) > 1:
        from .parallel.mesh import make_mesh

        train_once(make_mesh({"dp": len(jax.devices())}))
        print(f" - data-parallel SPMD over {len(jax.devices())} devices OK")
    print("Your paddle_tpu works well on "
          f"{'multiple devices' if len(jax.devices()) > 1 else 'this device'}.")
    print("Your paddle_tpu is installed successfully!")
    return True
