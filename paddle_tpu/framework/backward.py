"""Static-graph autodiff: append_backward.

Capability parity with the reference's python/paddle/fluid/backward.py
(append_backward at backward.py:1193, gradients at :1727): walks the block's
ops in reverse from the loss, appends grad ops, inserts `sum` ops where a
variable receives multiple gradient contributions, and returns (param, grad)
pairs for the optimizer.

TPU-native twist: instead of 430 hand-written grad kernels + GradOpMaker
registrations (grad_op_desc_maker.h in the reference), a single generic
"__vjp__" op replays the forward emitter under jax.vjp *inside the same XLA
trace*. XLA CSE merges the replayed forward with the original forward, so the
compiled HLO matches what hand-written grads would produce. Ops with custom
grad semantics (control flow, collectives) can still register grad_maker.
"""

from __future__ import annotations

from ..core.dtypes import is_float
from ..framework import unique_name
from .program import grad_var_name
from .registry import OpView, get_op_def, register_op

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# the generic vjp grad op
# ---------------------------------------------------------------------------


@register_op("__vjp__", inputs=[], outputs=[], differentiable=False)
def _vjp_emit(ctx, op, ins):
    fwd_def = get_op_def(op.attr("fwd_type"))
    fwd_op = OpView(op.attr("fwd_type"), op.attr("fwd_attrs"))

    fwd_ins = {
        slot[len("FwdIn:"):]: vals
        for slot, vals in ins.items()
        if slot.startswith("FwdIn:")
    }
    out_grads = {
        slot[len("OutGrad:"):]: vals
        for slot, vals in ins.items()
        if slot.startswith("OutGrad:")
    }
    # positions (slot, idx) we need input grads for
    want = [
        (slot[len("InGrad:"):], i)
        for slot, names in op.outputs.items()
        if slot.startswith("InGrad:")
        for i, n in enumerate(names)
        if n
    ]

    def fwd_full(diff_vals):
        merged = {s: list(v) for s, v in fwd_ins.items()}
        for (slot, idx), val in zip(want, diff_vals):
            merged[slot][idx] = val
        return fwd_def.emit(ctx, fwd_op, merged)

    diff_vals = [fwd_ins[slot][idx] for slot, idx in want]
    # structural pre-pass: which outputs are differentiable (inexact dtype).
    # The duplicate forward trace is merged away by XLA CSE.
    outs0 = fwd_full(diff_vals)
    keys = [
        (slot, i)
        for slot in sorted(outs0)
        for i, v in enumerate(outs0[slot])
        if v is not None and jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)
    ]

    def fwd_flat(dv):
        outs = fwd_full(dv)
        return [outs[slot][i] for slot, i in keys]

    primals, vjp_fn = jax.vjp(fwd_flat, diff_vals)

    cts = []
    for (slot, i), primal in zip(keys, primals):
        g = None
        if slot in out_grads and i < len(out_grads[slot]):
            g = out_grads[slot][i]
        cts.append(
            jnp.zeros_like(primal) if g is None else g.astype(primal.dtype)
        )
    (in_grads,) = vjp_fn(cts)

    result = {}
    for (slot, idx), g in zip(want, in_grads):
        result.setdefault("InGrad:" + slot, {})[idx] = g
    # convert {idx: g} to dense lists matching op.outputs ordering
    out = {}
    for slot, names in op.outputs.items():
        if not slot.startswith("InGrad:"):
            continue
        vals = result.get(slot, {})
        out[slot] = [vals.get(i) for i in range(len(names))]
    return out


# ---------------------------------------------------------------------------
# the graph transform
# ---------------------------------------------------------------------------


def _ensure_var(block, name, like_name):
    if not block.has_var(name):
        src = block.var(like_name)
        block.create_var(name=name, shape=src.shape, dtype=src.dtype)
    return block.var(name)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """Append grad ops for `loss` into its block; return [(param, grad)]."""
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    # 1. which vars can require grads: forward reachability from trainable
    # params and explicitly differentiable (stop_gradient=False) data vars
    from .program import Parameter

    needs_grad = set()
    for v in block.vars.values():
        trainable_param = isinstance(v, Parameter) and v.trainable
        diff_input = not v.stop_gradient and v.is_data
        if (trainable_param or diff_input) and is_float(v.dtype):
            needs_grad.add(v.name)
    needs_grad -= no_grad
    fwd_ops = list(block.ops)  # snapshot before appending backward ops
    for op in fwd_ops:
        op_def = get_op_def(op.type)
        if not op_def.differentiable:
            continue
        if any(n in needs_grad for n in op.input_names()):
            for n in op.output_names():
                if (
                    n
                    and block.has_var(n)
                    and is_float(block.var(n).dtype)
                    and not block.var(n).stop_gradient
                    and n not in no_grad
                ):
                    needs_grad.add(n)

    # 2. seed loss gradient with 1.0 (reference: fill_constant at backward.py:1193)
    loss_grad = grad_var_name(loss.name)
    block.create_var(name=loss_grad, shape=loss.shape, dtype=loss.dtype)
    block.append_op(
        "fill_constant",
        {},
        {"Out": [loss_grad]},
        {"shape": list(loss.shape or (1,)), "dtype": loss.dtype, "value": 1.0},
    )

    # var name -> list of gradient contribution var names
    contribs = {loss.name: [loss_grad]}

    def finalize(name):
        """Collapse contributions into the canonical @GRAD var, summing."""
        c = contribs.get(name)
        if not c:
            return None
        canonical = grad_var_name(name)
        if len(c) == 1:
            if c[0] != canonical:
                _ensure_var(block, canonical, name)
                block.append_op("assign", {"X": [c[0]]}, {"Out": [canonical]})
        else:
            _ensure_var(block, canonical, name)
            block.append_op("sum", {"X": list(c)}, {"Out": [canonical]})
        contribs[name] = [canonical]
        return canonical

    # 3. reverse walk over the forward snapshot
    for op in reversed(fwd_ops):
        op_def = get_op_def(op.type)
        if not op_def.differentiable:
            continue
        out_has_grad = any(n in contribs for n in op.output_names())
        if not out_has_grad:
            continue
        diff_inputs = [
            (slot, i, n)
            for slot, names in op.inputs.items()
            for i, n in enumerate(names)
            if n and n in needs_grad
        ]
        if not diff_inputs:
            continue

        if op_def.grad_maker is not None:
            # a maker may decline (return False) to fall back to the
            # generic __vjp__ path, e.g. when a rarely-differentiated
            # auxiliary output turns out to carry gradients
            if op_def.grad_maker(op, block, contribs, finalize,
                                 needs_grad=needs_grad) is not False:
                continue

        # finalize the grads of this op's outputs
        grad_ins = {}
        for slot, names in op.outputs.items():
            grad_ins["OutGrad:" + slot] = [
                (finalize(n) or "") if n in contribs else "" for n in names
            ]
        fwd_in_slots = {"FwdIn:" + s: list(v) for s, v in op.inputs.items()}

        diff_set = set(diff_inputs)
        grad_outs = {}
        new_contribs = []
        for slot, names in op.inputs.items():
            outs = []
            for i, n in enumerate(names):
                if (slot, i, n) in diff_set:
                    gname = unique_name.generate(grad_var_name(n) + "@RENAME")
                    _ensure_var(block, gname, n)
                    outs.append(gname)
                    new_contribs.append((n, gname))
                else:
                    outs.append("")
            grad_outs["InGrad:" + slot] = outs

        block.append_op(
            "__vjp__",
            {**fwd_in_slots, **grad_ins},
            grad_outs,
            {
                "fwd_type": op.type,
                "fwd_attrs": dict(op.attrs),
            },
        )
        for n, gname in new_contribs:
            contribs.setdefault(n, []).append(gname)

    # 4. finalize parameter grads
    if parameter_list is not None:
        params = [
            block.var(p) if isinstance(p, str) else p for p in parameter_list
        ]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    result = []
    for p in params:
        if p.name in no_grad:
            continue
        g = finalize(p.name)
        if g is not None:
            result.append((p, block.var(g)))
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients parity (backward.py:1727): grads of targets w.r.t inputs."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    assert len(targets) == 1, "multiple targets: sum them first"
    pairs = append_backward(
        targets[0], parameter_list=[v.name for v in inputs], no_grad_set=no_grad_set
    )
    by_name = {p.name: g for p, g in pairs}
    return [by_name.get(v.name) for v in inputs]
