"""Unique-name generator (capability of python/paddle/fluid/unique_name.py in
the reference repo): per-prefix counters, guard() to scope generators."""

from __future__ import annotations

import contextlib


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids = {}

    def __call__(self, key: str) -> str:
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return f"{self.prefix}{key}_{n}"


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    global _generator
    old = _generator
    _generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        _generator = old
