"""Executor: lowers a ProgramDesc block to ONE XLA computation and runs it.

This replaces the reference's interpretive hot loop (Executor::Run at
executor.cc:184/307 running ops one-by-one at executor.cc:469-476, each
through kernel dispatch at operator.cc:1032) with whole-block compilation:

    feed vars + persistable state  ->  traced emitters  ->  fetches + new state

compiled by jax.jit, cached per (program version, feed shapes, fetch set).
Consequences, all TPU-native:
  * XLA fuses across op boundaries (no ir/ fusion pass zoo needed);
  * buffer lifetime is XLA buffer assignment (no GarbageCollector /
    memory_optimize passes needed — reference framework/garbage_collector.cc);
  * mutated persistables (optimizer ParamOut etc.) are donated, so parameter
    updates alias their input HBM buffers (reference relied on Scope mutation
    + share-buffer passes);
  * one host->device dispatch per step instead of per op.

SPMD: if program._mesh is set (by fleet / transpilers / the SPMD API), the
traced block runs under jax.shard_map over that Mesh — collective ops emit
ICI collectives, and feed/state are sharded per program._sharding. This is
the GSPMD replacement for the reference's ParallelExecutor SSA-graph runtime
(parallel_executor.cc:443, details/threaded_ssa_graph_executor.cc).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.place import default_place
from .program import Variable, default_main_program
from .registry import EmitContext, run_op
from .scope import global_scope


class _Compiled:
    __slots__ = ("fn", "state_ro", "state_mut", "fetch_names", "nan_ops",
                 "est", "recent_dts", "recent_segs", "wire_stats")

    def __init__(self, fn, state_ro, state_mut, fetch_names, nan_ops=None,
                 wire_stats=None):
        self.fn = fn
        self.state_ro = state_ro
        self.state_mut = state_mut
        self.fetch_names = fetch_names
        # ops list compiled with per-op NaN/Inf checks (FLAGS_check_nan_inf);
        # the extra trailing fetch indexes into this to name the offender
        self.nan_ops = nan_ops
        # analytic cost estimate for this executable (perf.* telemetry):
        # None = not yet computed, False = estimation failed (never retried)
        self.est = None
        # steady-state step latencies (compile-carrying runs excluded) —
        # the window behind the live perf.mfu gauge
        self.recent_dts = None
        # steady-state (host_seconds, device_seconds) pairs — the window
        # behind the perf.wait_fraction.* attribution gauges
        self.recent_segs = None
        # mutable {"bytes": float} the collective emitters fill at trace
        # time (ops/collective.py): this executable's per-step estimated
        # wire payload, an estimate-independent attribution cross-check
        self.wire_stats = wire_stats


class _PerfEstimate:
    """Digest of a CostTable cached per executable for the per-run
    perf.* updates (the full table is published once, to the
    "perf.cost_table" observability table)."""

    __slots__ = ("flops", "bytes", "peak", "family_shares",
                 "wire_latency", "compute_latency", "wire_total_latency",
                 "overlap_ratio", "step_latency")

    def __init__(self, table):
        self.flops = float(table.total_flops)
        self.bytes = float(table.total_bytes)
        self.peak = float(table.peak_flops)
        total_lat = table.total_latency
        fams = table.by_family()
        self.family_shares = {
            fam: (agg["latency"] / total_lat if total_lat else 0.0)
            for fam, agg in fams.items()
        }
        # the wire split (ROADMAP item 4's denominator, now
        # OVERLAP-AWARE): `wire_total_latency` is the serialized wire —
        # the collective family's roofline, closed forms carrying the
        # ring (n-1)/n factors and quantized element sizes;
        # `wire_latency` is the EXPOSED wire — the part the program's
        # actual collective schedule cannot hide behind compute
        # (CostTable.wire_exposed_latency; == total for a serialized
        # schedule, so pre-overlap programs attribute exactly as before).
        self.wire_total_latency = float(table.wire_latency)
        self.wire_latency = float(table.wire_exposed_latency)
        self.compute_latency = max(
            0.0, float(total_lat) - self.wire_total_latency
        )
        self.step_latency = float(table.step_latency)
        # wire seconds hidden / total wire seconds under the schedule
        self.overlap_ratio = float(table.overlap_ratio)

    @property
    def wire_fraction(self):
        """Share of the estimated step the EXPOSED wire serializes."""
        denom = self.wire_latency + self.compute_latency
        return self.wire_latency / denom if denom > 0 else 0.0


def _analyze_block(block, feed_names, fetch_names):
    """Classify variable names: read-from-scope vs produced; mutated persistables."""
    produced = set()
    from_scope = []  # ordered; membership tracked in seen
    seen = set()
    mutated = set()
    for op in block.ops:
        for n in op.input_names():
            if n and n not in produced and n not in feed_names and n not in seen:
                from_scope.append(n)
                seen.add(n)
        for n in op.output_names():
            if not n:
                continue
            produced.add(n)
            v = block._find_var_recursive(n)
            if v is not None and v.persistable:
                mutated.add(n)
    for n in fetch_names:
        if n not in produced and n not in feed_names and n not in seen:
            from_scope.append(n)
            seen.add(n)
    state_mut = [n for n in from_scope if n in mutated]
    # vars produced without being read first but persistable (e.g. startup
    # program init ops) are still written back
    write_back = sorted(mutated)
    state_ro = [n for n in from_scope if n not in mutated]
    return state_ro, state_mut, write_back


class Executor:
    """fluid.Executor parity (python/paddle/fluid/executor.py:890)."""

    # bound on cached executables; eviction is LRU. The reference's
    # ExecutorPrepareContext cache had the same unbounded-growth hazard — a
    # cap keeps long-lived executors (many programs / shape buckets) sane.
    CACHE_CAPACITY = 128

    def __init__(self, place=None):
        from collections import OrderedDict

        from ..observability import timeline as _timeline

        # telemetry-plane opt-in (PR 16): with PADDLE_TPU_TELEMETRY_DIR
        # set, the first Executor in the process brings up the journal
        # publisher + flight recorder — launched trainers join the fleet
        # telemetry plane with no code changes (idempotent, cheap no-op
        # when the env is absent)
        _timeline.ensure_publisher()
        self.place = place if place is not None else default_place()
        self._cache = OrderedDict()
        # (compiled, fresh_compile, (host_s, device_s) | None) of the
        # last run — consumed once by _note_perf
        self._last_run = None
        self._est_memo = {}  # cache key -> _PerfEstimate | False

    def close(self):
        self._cache.clear()
        self._est_memo.clear()

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
        use_program_cache=True,
    ):
        import time

        from .. import observability as _obs

        _obs.add("executor.run_steps")
        self._last_run = None
        with _obs.span("executor.step"):
            t0 = time.perf_counter()
            try:
                result = self._run_body(
                    program, feed, fetch_list, scope, return_numpy,
                    use_program_cache,
                )
            finally:
                _obs.observe(
                    "executor.step_latency", time.perf_counter() - t0
                )
        self._note_perf(time.perf_counter() - t0)
        return result

    @staticmethod
    def _drop_perf_gauges(_obs):
        for prefix in ("perf.mfu", "perf.step_seconds",
                       "perf.family_time.", "perf.wait_fraction.",
                       "collective.overlap_ratio"):
            _obs.drop_gauges(prefix)
        # the attribution table describes ONE executable, same as the
        # gauges: a snapshot taken right after an executable switch must
        # not pair the old split with the new program
        _obs.drop_tables("perf.step_attribution")

    def _note_perf(self, dt):
        """Per-run perf.* telemetry from the analytic cost estimate: step
        FLOP/byte counters always; the live MFU gauge only from
        steady-state runs (a compile-carrying run would crater it)."""
        from .. import observability as _obs

        noted = self._last_run
        self._last_run = None
        if noted is None or not _obs.enabled():
            return
        compiled, fresh_compile, seg = noted
        est = compiled.est
        if not est:
            # this executable has no estimate: a previous executable's
            # gauges must not read as live for it
            self._drop_perf_gauges(_obs)
            return
        _obs.add("perf.step_flops", int(est.flops))
        _obs.add("perf.step_bytes", int(est.bytes))
        if fresh_compile or dt <= 0:
            # compile-carrying run: no steady-state value for THIS
            # executable yet, and the old gauges describe another one
            self._drop_perf_gauges(_obs)
            return
        if compiled.recent_dts is None:
            from collections import deque

            compiled.recent_dts = deque(maxlen=32)
            compiled.recent_segs = deque(maxlen=32)
        compiled.recent_dts.append(dt)
        mean_dt = sum(compiled.recent_dts) / len(compiled.recent_dts)
        _obs.set_gauge("perf.step_seconds", mean_dt)
        if est.peak > 0 and est.flops > 0:
            _obs.set_gauge("perf.mfu", est.flops / mean_dt / est.peak)
        # attribute the MEASURED step time across op families by each
        # family's share of the estimated roofline; drop first so families
        # only present in a PREVIOUS executable don't survive as stale
        _obs.drop_gauges("perf.family_time.")
        for fam, share in est.family_shares.items():
            _obs.set_gauge(f"perf.family_time.{fam}", share * mean_dt)
        if seg is not None:
            self._note_attribution(_obs, compiled, est, mean_dt, seg)

    @staticmethod
    def _note_attribution(_obs, compiled, est, mean_dt, seg):
        """Per-step compute / collective-wait / host-stall attribution
        (the serialized-wire denominator ROADMAP item 4 measures against):
        the measured step splits into host time (feed/state assembly +
        write-back, directly measured) and device time (dispatch +
        block-until-ready); device time splits into compute vs
        collective-wait by the cost model's wire share — under serialized
        collectives the wire's roofline share of the device step IS the
        time the math waits on the wire."""
        host, device = seg
        compiled.recent_segs.append((host, device))
        n = len(compiled.recent_segs)
        mean_host = sum(s[0] for s in compiled.recent_segs) / n
        mean_device = sum(s[1] for s in compiled.recent_segs) / n
        wire_share = est.wire_fraction
        coll_wait = mean_device * wire_share
        compute = mean_device - coll_wait
        denom = mean_host + mean_device
        if denom <= 0:
            return
        # the wait_fraction gauge SET is fixed (3 names), so unlike the
        # per-family gauges there is nothing stale to drop per step —
        # _drop_perf_gauges clears them on executable switch
        _obs.set_gauge("perf.wait_fraction.collective", coll_wait / denom)
        _obs.set_gauge("perf.wait_fraction.host", mean_host / denom)
        _obs.set_gauge("perf.wait_fraction.compute", compute / denom)
        # wire seconds hidden / total wire seconds under the executable's
        # collective schedule (0 = serialized) — the overlap consumer of
        # the PR-13 attribution split
        _obs.set_gauge("collective.overlap_ratio", est.overlap_ratio)
        _obs.observe("perf.compute_seconds", device * (1.0 - wire_share))
        _obs.observe("perf.collective_wait_seconds", device * wire_share)
        _obs.observe("perf.host_stall_seconds", host)
        wire_stats = compiled.wire_stats or {}
        _obs.set_table("perf.step_attribution", {
            "step_seconds": mean_dt,
            "compute_seconds": compute,
            "collective_wait_seconds": coll_wait,
            "host_stall_seconds": mean_host,
            "wait_fraction_collective": coll_wait / denom,
            "wait_fraction_host": mean_host / denom,
            "est_compute_seconds": est.compute_latency,
            "est_wire_seconds": est.wire_latency,
            "est_wire_total_seconds": est.wire_total_latency,
            "est_wire_hidden_seconds": max(
                0.0, est.wire_total_latency - est.wire_latency
            ),
            "est_overlap_ratio": est.overlap_ratio,
            "est_wait_fraction": wire_share,
            "traced_wire_bytes": float(wire_stats.get("bytes", 0.0)),
            "window_steps": n,
        })

    def _run_body(
        self, program, feed, fetch_list, scope, return_numpy,
        use_program_cache,
    ):
        import time

        from .. import observability as _obs

        t_body = time.perf_counter()
        # the shared prologue keys the cache on the Program OBJECT
        # (identity hash, strong ref) so a freed Program's recycled id
        # cannot produce a stale hit; _prepared is the single source of
        # the key derivation for run/flops/AOT serialize+load
        (program, scope, block, feed_arrays, _feed_sig, fetch_names,
         key) = self._prepared(program, feed, fetch_list, scope)
        compiled = self._cache.get(key) if use_program_cache else None
        fresh_compile = compiled is None
        if compiled is None:
            if use_program_cache:
                _obs.add("executor.cache_misses")
            _obs.add("executor.compile_count")
            with _obs.timed("executor.compile_time"), \
                    _obs.span("executor.compile"):
                compiled = self._compile(
                    program, block, set(feed_arrays), fetch_names, scope
                )
            if use_program_cache:
                self._cache[key] = compiled
                while len(self._cache) > self.CACHE_CAPACITY:
                    self._cache.popitem(last=False)
                    _obs.add("executor.cache_evictions")
        else:
            _obs.add("executor.cache_hits")
            self._cache.move_to_end(key)

        if compiled.est is None and _obs.enabled():
            # memo per cache key so use_program_cache=False callers don't
            # re-walk the graph every step (fresh _Compiled each run)
            est = self._est_memo.get(key)
            if est is None:
                est = self._estimate(program, feed_arrays)
                if len(self._est_memo) >= 64:
                    self._est_memo.pop(next(iter(self._est_memo)))
                self._est_memo[key] = est
            compiled.est = est
        # seg (host vs device split) is filled in below once the dispatch
        # completes; a run that raises before then reports no attribution
        self._last_run = (compiled, fresh_compile, None)

        state_ro = {n: self._from_scope(scope, n, block) for n in compiled.state_ro}
        state_mut = {n: self._from_scope(scope, n, block) for n in compiled.state_mut}

        # Per-step RNG folds in the program's own run counter: a fixed
        # random_seed pins the *sequence* (deterministic re-runs from a fresh
        # Program), while dropout masks still vary step to step — matching
        # the reference, which is deterministic per seed but advances its
        # generator every op execution.
        # seed 0 = nondeterministic (fluid semantics): fall back to the
        # program's own nonce so unseeded Programs are mutually decorrelated.
        # When the mesh spans processes the step key feeds a REPLICATED
        # shard_map input, so every process must derive the same value: use
        # a structural hash of the program instead of the per-process nonce
        # (identically-built programs hash identically on every rank).
        seed = program.random_seed
        if not seed:
            mesh = program._mesh
            multiproc = False
            if mesh is not None:
                cached = getattr(mesh, "_paddle_multiproc", None)
                if cached is None:
                    cached = any(
                        d.process_index != jax.process_index()
                        for d in mesh.devices.flat
                    )
                    try:
                        mesh._paddle_multiproc = cached
                    except AttributeError:
                        pass
                multiproc = cached
            seed = program._structural_seed() if multiproc else program._rng_nonce
        step = program._rng_step
        program._rng_step += 1
        from ..core.random import prng_impl

        step_key = jax.random.fold_in(
            jax.random.key(seed, impl=prng_impl()), step
        )

        # host/device split for the per-step attribution (perf.wait_*):
        # everything up to the dispatch is host prologue; the dispatch is
        # bounded with block_until_ready so the device segment is real
        # device wall time, not async-dispatch return time. ONLY on the
        # return_numpy path: those callers synchronize inside this very
        # call anyway (np.asarray below), so the early block changes
        # nothing — while return_numpy=False callers (bench.py's
        # pipelined timing loops) rely on async dispatch overlapping
        # step N's device work with step N+1's host prologue, and an
        # attribution block there would serialize the accelerator
        # pipeline. Such runs simply publish no attribution sample.
        attributing = return_numpy and _obs.enabled()
        t_dispatch = time.perf_counter()
        fetches, new_state = compiled.fn(feed_arrays, state_mut, state_ro, step_key)
        if attributing:
            # one leaf suffices: XLA materializes every output of the
            # computation together, and walking the whole state pytree
            # (hundreds of arrays) would cost more than the span itself
            leaf = fetches[0] if fetches else next(
                iter(new_state.values()), None
            )
            if leaf is not None:
                jax.block_until_ready(leaf)
        t_device_end = time.perf_counter()
        # write-back FIRST: state_mut buffers were donated, so skipping the
        # write-back on error would leave the scope holding deleted arrays
        # (params irretrievably lost right when the user wants to inspect)
        for n, v in new_state.items():
            scope.set_var(n, v)
        if attributing:
            # host = prologue + write-back epilogue; the trailing numpy
            # conversion is already-on-host copies, charged to the caller
            self._last_run = (compiled, fresh_compile, (
                (t_dispatch - t_body)
                + (time.perf_counter() - t_device_end),
                t_device_end - t_dispatch,
            ))
        if compiled.nan_ops is not None:
            bad = np.asarray(fetches[-1])
            fetches = fetches[:-1]
            if bad.any():
                idx = int(np.argmax(bad))
                op = compiled.nan_ops[idx]
                from ..errors import NonFiniteError

                raise NonFiniteError(
                    f"NaN/Inf detected in outputs of op #{idx} "
                    f"{op.type!r} — FLAGS_check_nan_inf mode "
                    "(reference details/nan_inf_utils_detail.cc)",
                    op=op,
                    outputs=op.output_names(),
                )
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _estimate(self, program, feed_arrays):
        """Analytic cost digest for the executable about to run, pinned at
        the actual feed shapes; publishes the full per-op table as the
        "perf.cost_table" observability table. Returns False on failure so
        the estimate is attempted once per executable, never per step."""
        from .. import observability as _obs

        try:
            table = program.estimate(feed_shapes={
                k: tuple(a.shape) for k, a in feed_arrays.items()
            })
            _obs.set_table("perf.cost_table", table.to_dict(top=50))
            if table.peak_bytes is not None:
                _obs.set_gauge(
                    "perf.peak_bytes_est", float(table.peak_bytes)
                )
                _obs.set_gauge(
                    "perf.resident_bytes_est", float(table.resident_bytes)
                )
            return _PerfEstimate(table)
        except Exception:
            _obs.add("perf.estimate_failures")
            return False

    # ------------------------------------------------------------------
    def flops(self, program=None, feed=None, fetch_list=None, scope=None):
        """XLA's static FLOP count for ONE step of `program` with this
        feed — the compiled executable's cost analysis (reference role:
        the per-op cost tooling of operators/benchmark/op_tester.cc).
        Reuses the executor's compile cache; run the same (program, feed)
        once first for a warm lookup. Pallas custom-call FLOPs are NOT
        visible to XLA — callers benchmarking hand kernels must add that
        term analytically (bench.py does for the attention kernels)."""
        (program, scope, block, feed_arrays, _feed_sig, fetch_names,
         key) = self._prepared(program, feed, fetch_list, scope)
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = self._compile(
                program, block, set(feed_arrays), fetch_names, scope
            )
            self._cache[key] = compiled
        state_ro = {
            n: self._from_scope(scope, n, block) for n in compiled.state_ro
        }
        state_mut = {
            n: self._from_scope(scope, n, block) for n in compiled.state_mut
        }
        from ..core.random import prng_impl

        step_key = jax.random.key(0, impl=prng_impl())
        lowered = compiled.fn.lower(
            feed_arrays, state_mut, state_ro, step_key
        )
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca or "flops" not in ca:
            # "backend reports no cost data" is NOT "zero-FLOP program":
            # callers deriving MFU from this must not read a silent 0.0
            import warnings

            from .. import observability as _obs
            from ..errors import CostAnalysisUnavailableWarning

            _obs.add("perf.cost_analysis_unavailable")
            warnings.warn(
                "XLA cost_analysis() returned no FLOP data for this "
                "executable; falling back to 0.0 — use "
                "Program.estimate() for an analytic count",
                CostAnalysisUnavailableWarning,
                stacklevel=2,
            )
            return 0.0
        return float(ca.get("flops", 0.0))

    # ------------------------------------------------------------------
    def memory_analysis(self, program=None, feed=None, fetch_list=None,
                        scope=None):
        """XLA's buffer-assignment memory breakdown for ONE step of
        `program` with this feed: a dict of ``argument_bytes`` /
        ``output_bytes`` / ``temp_bytes`` / ``alias_bytes`` plus
        ``peak_bytes`` (argument + output + temp − alias: every byte the
        executable holds at once, donated buffers counted once) from the
        compiled executable's ``memory_analysis()``. The ground truth the
        static plan (``Program.estimate().peak_bytes``) is cross-checked
        against (``tools/perf_report.py --check-memory``). Returns None —
        with a counter bump — when the backend reports nothing."""
        (program, scope, block, feed_arrays, _feed_sig, fetch_names,
         key) = self._prepared(program, feed, fetch_list, scope)
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = self._compile(
                program, block, set(feed_arrays), fetch_names, scope
            )
            self._cache[key] = compiled
        state_ro = {
            n: self._from_scope(scope, n, block) for n in compiled.state_ro
        }
        state_mut = {
            n: self._from_scope(scope, n, block) for n in compiled.state_mut
        }
        from ..core.random import prng_impl

        step_key = jax.random.key(0, impl=prng_impl())
        lowered = compiled.fn.lower(
            feed_arrays, state_mut, state_ro, step_key
        )
        try:
            ma = lowered.compile().memory_analysis()
        except Exception:
            ma = None
        if isinstance(ma, (list, tuple)):
            ma = ma[0] if ma else None
        fields = {
            "argument_bytes": "argument_size_in_bytes",
            "output_bytes": "output_size_in_bytes",
            "temp_bytes": "temp_size_in_bytes",
            "alias_bytes": "alias_size_in_bytes",
        }
        out = {
            k: float(getattr(ma, attr, 0.0) or 0.0)
            for k, attr in fields.items()
        }
        if ma is None or not any(out.values()):
            from .. import observability as _obs

            _obs.add("perf.memory_analysis_unavailable")
            return None
        out["peak_bytes"] = (
            out["argument_bytes"] + out["output_bytes"]
            + out["temp_bytes"] - out["alias_bytes"]
        )
        return out

    # ------------------------------------------------------------------
    def _prepared(self, program, feed, fetch_list, scope):
        """Shared prologue of run/flops/AOT paths: resolve the cache key,
        compile if needed, and assemble the argument pytrees."""
        program = program if program is not None else default_main_program()
        program = getattr(program, "program", program)
        scope = scope if scope is not None else global_scope()
        fetch_names = tuple(
            v.name if isinstance(v, Variable) else str(v)
            for v in (fetch_list or [])
        )
        block = program.global_block
        feed_arrays = {k: jnp.asarray(v) for k, v in dict(feed or {}).items()}
        feed_sig = tuple(
            (k, tuple(a.shape), str(a.dtype))
            for k, a in sorted(feed_arrays.items())
        )
        from ..flags import flag

        check_nan = bool(flag("check_nan_inf"))
        key = (program, program._version, feed_sig, fetch_names, check_nan)
        return (program, scope, block, feed_arrays, feed_sig, fetch_names,
                key)

    def serialize_executable(self, path, program=None, feed=None,
                             fetch_list=None, scope=None):
        """AOT-compile ONE step of (program, feed) and write the serialized
        XLA executable to `path` (reference role: AnalysisConfig's
        SetOptimCacheDir + the TRT engine serialization,
        inference/api/paddle_analysis_config.h). `load_executable` in a
        later process skips XLA compilation entirely for the same program
        structure + feed signature + device kind."""
        import pickle

        from jax.experimental import serialize_executable as se

        (program, scope, block, feed_arrays, feed_sig, fetch_names,
         key) = self._prepared(program, feed, fetch_list, scope)
        if key[-1]:  # check_nan flag in the cache key
            from ..errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                "serialize_executable under FLAGS_check_nan_inf is not "
                "supported: the nan-flags fetch and its op table cannot be "
                "serialized; clear the flag around the serialization"
            )
        from ..core.random import prng_impl

        step_key = jax.random.key(0, impl=prng_impl())
        # An executable that was LOADED from the persistent compilation
        # cache serializes incompletely (XLA:CPU leaves backend
        # function-registry entries behind — "Function ... not found" on
        # deserialize). So the serialization pass never touches the serving
        # jit OR the disk cache: disable the persistent cache, reset its
        # module-global handle, re-trace the block into a FRESH jit object,
        # and AOT-compile that.
        from jax._src import compilation_cache as _cc

        cache_dir = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            _cc.reset_cache()
            compiled = self._compile(
                program, block, set(feed_arrays), fetch_names, scope
            )
            state_ro = {
                n: self._from_scope(scope, n, block)
                for n in compiled.state_ro
            }
            state_mut = {
                n: self._from_scope(scope, n, block)
                for n in compiled.state_mut
            }
            lowered = compiled.fn.lower(feed_arrays, state_mut, state_ro,
                                        step_key)
            payload, in_tree, out_tree = se.serialize(lowered.compile())
        finally:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            _cc.reset_cache()
        blob = {
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
            "feed_sig": feed_sig,
            "fetch_names": fetch_names,
            "state_ro": list(compiled.state_ro),
            "state_mut": list(compiled.state_mut),
            "platform": jax.devices()[0].platform,
        }
        with open(path, "wb") as f:
            pickle.dump(blob, f)
        return path

    def load_executable(self, path, program=None, feed=None,
                        fetch_list=None, scope=None):
        """Install a serialized executable (serialize_executable) into this
        executor's cache for (program, feed signature, fetch set) — the
        next `run` dispatches it with NO XLA compilation. Raises
        InvalidArgumentError when the signature does not match."""
        import pickle

        from jax.experimental import serialize_executable as se

        from ..errors import InvalidArgumentError

        (program, scope, block, feed_arrays, feed_sig, fetch_names,
         key) = self._prepared(program, feed, fetch_list, scope)
        if key[-1]:
            raise InvalidArgumentError(
                "load_executable under FLAGS_check_nan_inf is not "
                "supported (serialized executables carry no nan-check op "
                "table); clear the flag for AOT serving"
            )
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if blob["feed_sig"] != feed_sig or blob["fetch_names"] != fetch_names:
            raise InvalidArgumentError(
                f"serialized executable at {path!r} was built for feed "
                f"{blob['feed_sig']} / fetches {blob['fetch_names']}, got "
                f"{feed_sig} / {fetch_names}"
            )
        if blob["platform"] != jax.devices()[0].platform:
            raise InvalidArgumentError(
                f"serialized executable targets platform "
                f"{blob['platform']!r}; this process runs "
                f"{jax.devices()[0].platform!r}"
            )
        # pin the execution devices to the single default device the
        # executable was jit-compiled for — the default (all local devices)
        # breaks under a forced multi-device CPU (test mesh) topology
        loaded = se.deserialize_and_load(
            blob["payload"], blob["in_tree"], blob["out_tree"],
            execution_devices=[jax.devices()[0]],
        )
        self._cache[key] = _Compiled(
            loaded, blob["state_ro"], blob["state_mut"], fetch_names
        )
        return self._cache[key]

    # ------------------------------------------------------------------
    def train_from_dataset(
        self, program=None, dataset=None, scope=None, thread=0,
        debug=False, fetch_list=None, fetch_info=None, print_period=100,
    ):
        """Train over a fluid Dataset (reference executor.py:1323 ->
        TrainerFactory -> HogwildWorker op loops, hogwild_worker.cc:189).
        Here each parsed batch feeds the ONE jitted step — the per-thread
        op interpreter the reference needed is subsumed by XLA, so
        `thread` only tunes the host-side parse (dataset.set_thread)."""
        if dataset is None:
            from ..errors import InvalidArgumentError

            raise InvalidArgumentError(
                "train_from_dataset requires a dataset"
            )
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [
            getattr(v, "name", str(v)) for v in fetch_list
        ]
        step = 0
        for feed in dataset.batches():
            outs = self.run(
                program, feed=feed, fetch_list=fetch_list, scope=scope,
            )
            step += 1
            if debug and fetch_list and step % print_period == 0:
                import numpy as _np

                vals = ", ".join(
                    f"{n}={_np.asarray(v).reshape(-1)[0]:.6g}"
                    for n, v in zip(fetch_info, outs)
                )
                print(f"step {step}: {vals}")
        return step

    def infer_from_dataset(self, program=None, dataset=None, **kw):
        """Like train_from_dataset but refuses programs containing update
        ops — the reference guarantees no parameter mutation here; pass a
        clone(for_test=True)/pruned inference program."""
        prog = program if program is not None else default_main_program()
        prog = getattr(prog, "program", prog)
        update_ops = {
            "sgd", "momentum", "lars_momentum", "adam", "adamw", "lamb",
            "adagrad", "decayed_adagrad", "adadelta", "rmsprop", "ftrl",
            "adamax", "dpsgd",
        }
        bad = [op.type for op in prog.global_block.ops
               if op.type in update_ops]
        if bad:
            from ..errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"infer_from_dataset got a program with update ops {bad}; "
                "pass an inference program (clone(for_test=True) before "
                "minimize, or load_inference_model output)"
            )
        return self.train_from_dataset(program, dataset, **kw)

    # ------------------------------------------------------------------
    def _from_scope(self, scope, name, block):
        v = scope.find_var(name)
        if v is None:
            from ..errors import NotFoundError, PreconditionNotMetError

            var = block._find_var_recursive(name)
            if var is not None and var.is_data:
                raise NotFoundError(
                    f"feed variable {name!r} was not provided in `feed`"
                )
            raise PreconditionNotMetError(
                f"variable {name!r} is not initialized in the scope; "
                "run the startup program first (exe.run(startup_program))"
            )
        return v

    def _compile(self, program, block, feed_names, fetch_names, scope):
        from ..flags import flag

        # pre-trace static verification (PADDLE_TPU_VERIFY=strict|warn|0):
        # a malformed graph fails HERE with per-op provenance — strict mode
        # refuses to trace at all, so a rank-divergent collective schedule
        # can never reach the mesh and deadlock it
        from ..analysis import check_before_compile

        check_before_compile(program, feed_names, fetch_names)

        check_nan = bool(flag("check_nan_inf"))
        state_ro, state_mut, write_back = _analyze_block(
            block, feed_names, fetch_names
        )
        ops = list(block.ops)
        mesh = program._mesh
        spmd_mode = getattr(program, "_spmd_mode", "shard_map")
        # under gspmd there is no axis binding: collectives degrade to
        # identity and XLA derives cross-shard comms from shardings instead.
        # hybrid: only the program's manual axes are bound; the rest are
        # gspmd-Auto (emitters must not issue collectives over them)
        if mesh is None:
            mesh_axes = ()
        elif spmd_mode == "shard_map":
            mesh_axes = tuple(mesh.axis_names)
        elif spmd_mode == "hybrid":
            mesh_axes = tuple(getattr(program, "_manual_axes", ()))
        else:
            mesh_axes = ()

        # frozen inference programs (serving.freeze_program /
        # load_inference_model marks them _is_inference) trace in test
        # mode: even an op that missed its is_test attr flip must not run
        # train-only behavior (dropout masks, batch-norm stat updates)
        # while serving requests
        is_test = bool(getattr(program, "_is_inference", False))

        # filled by the collective emitters at trace time with this
        # executable's estimated per-step wire bytes (ops/collective.py);
        # reset at each (re)trace so retraces never double-count
        wire_stats = {"bytes": 0.0}

        def traced(feeds, smut, sro, step_key):
            env = {}
            env.update(sro)
            env.update(smut)
            env.update(feeds)
            axis_sizes = dict(mesh.shape) if mesh is not None else {}
            wire_stats["bytes"] = 0.0
            ctx = EmitContext(
                step_key=step_key, is_test=is_test, mesh_axes=mesh_axes,
                axis_sizes=axis_sizes, program=program,
            )
            ctx.wire_stats = wire_stats
            nan_flags = []
            for i, op in enumerate(ops):
                try:
                    run_op(ctx, op, env)
                except KeyError as e:
                    from ..errors import NotFoundError

                    raise NotFoundError(
                        f"op #{i} references undefined variable {e}",
                        op=op,
                    ) from None
                except Exception as e:
                    # attach op provenance to trace-time failures
                    # (reference framework/op_call_stack.cc); add_note keeps
                    # the original exception intact — many jax error classes
                    # cannot be reconstructed from a single message string
                    note = (
                        f"[while tracing op #{i} {op.type!r} created at "
                        f"{op.attr('__loc__', '<unknown>')}]"
                    )
                    if hasattr(e, "add_note"):  # PEP 678, python >= 3.11
                        e.add_note(note)
                    elif e.args and isinstance(e.args[0], str):
                        e.args = (e.args[0] + "\n" + note,) + e.args[1:]
                    raise
                if check_nan:
                    bad = jnp.zeros((), bool)
                    for n in op.output_names():
                        v = env.get(n)
                        if v is not None and jnp.issubdtype(
                            jnp.asarray(v).dtype, jnp.inexact
                        ):
                            bad = bad | ~jnp.all(jnp.isfinite(v))
                    nan_flags.append(bad)
            fetches = tuple(env[n] for n in fetch_names)
            if check_nan and nan_flags:
                flags_arr = jnp.stack(nan_flags).astype(jnp.int32)
                # a NaN may live on one shard only (e.g. a row-sharded
                # table): reduce over every mesh axis so the replicated
                # fetch sees it regardless of which device it hit
                for ax in mesh_axes:
                    flags_arr = jax.lax.pmax(flags_arr, ax)
                fetches = fetches + (flags_arr,)
            new_state = {n: env[n] for n in write_back if n in env}
            return fetches, new_state

        if mesh is not None:
            from ..parallel.spmd import wrap_gspmd, wrap_shard_map

            wrap = wrap_gspmd if spmd_mode == "gspmd" else wrap_shard_map
            # the nan-check mode appends one extra (replicated) fetch; the
            # wrapper's out_specs must match the traced arity
            wrapped_fetches = (
                fetch_names + ("__nan_flags__",)
                if (check_nan and ops) else fetch_names
            )
            fn = wrap(
                traced, program, mesh, state_ro, state_mut, write_back,
                wrapped_fetches,
                manual_axes=(
                    mesh_axes if spmd_mode == "hybrid" else None
                ),
            )
        else:
            fn = jax.jit(traced, donate_argnums=(1,))
        return _Compiled(
            fn, state_ro, state_mut, fetch_names,
            nan_ops=ops if (check_nan and ops) else None,
            wire_stats=wire_stats,
        )


# fluid-parity helper: exe.run on the startup program is the "init" step;
# initializer ops (gaussian_random/fill_constant) produce the persistables.
