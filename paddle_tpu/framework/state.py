"""Shared helper for persistable state vars (counters, accumulators, EMA
shadows) registered in both the main and startup programs.

One definition serves the LR schedulers (layers/learning_rate_scheduler.py),
the meta-optimizers (optimizer.py) and anything else needing a scope-resident
var initialized by the startup program — the reference scattered this pattern
across optimizer accumulators and learning_rate_scheduler counters.
"""

from __future__ import annotations

from . import unique_name
from .program import default_main_program, default_startup_program


def create_persistable_var(
    name_hint, shape, dtype, init=0.0, unique=True, main=None, startup=None
):
    """Create a non-trainable persistable var in main+startup with a
    fill_constant init op in startup. Returns the main-program Variable."""
    blk = (main or default_main_program()).global_block
    startup = (startup or default_startup_program()).global_block
    name = unique_name.generate(name_hint) if unique else name_hint
    v = blk.create_parameter(name, list(shape), dtype, trainable=False)
    v.stop_gradient = True
    startup.create_parameter(name, list(shape), dtype, trainable=False)
    startup.append_op(
        "fill_constant",
        {},
        {"Out": [name]},
        {"shape": list(shape), "dtype": dtype, "value": float(init)},
    )
    return v


def create_step_counter(name_hint, init=0.0, unique=True):
    """int32 [1] counter + an in-graph `increment` op (int32 because a
    float32 counter saturates at 2^24 steps; the reference used int64)."""
    v = create_persistable_var(name_hint, [1], "int32", init, unique=unique)
    default_main_program().global_block.append_op(
        "increment", {"X": [v.name]}, {"Out": [v.name]}, {"step": 1.0}
    )
    return v
