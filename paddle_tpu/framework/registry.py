"""Op registry: every op type is a JAX *emitter*, not a kernel.

TPU-native replacement for the reference's operator system (OpRegistry /
REGISTER_OP_CPU_KERNEL / REGISTER_OP_CUDA_KERNEL, op_registry.h:223-268, and
kernel dispatch at operator.cc:1032): instead of choosing a device kernel per
op at runtime, each op registers a pure function over jax arrays. The Executor
calls emitters inside a single jax.jit trace, so XLA sees the whole block and
fuses across op boundaries (the reference needed bespoke IR fusion passes for
this, ir/fuse_elewise_add_act_pass etc. — here the compiler does it).

Gradients: ops do NOT hand-write grad kernels. append_backward (backward.py)
emits a generic "__vjp__" op that re-applies the forward emitter under
jax.vjp inside the same trace; XLA CSE merges the re-traced forward with the
original, so cost matches a hand-written grad. Ops may still register a
custom grad maker (control flow, collectives) via grad_maker=.

Shape inference reuses the emitter through jax.eval_shape (abstract eval, no
compute) — one definition serves execution, shapes, and dtypes. -1 batch dims
are mapped through a prime sentinel.
"""

from __future__ import annotations

import itertools
import zlib

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtypes import to_numpy_dtype

# prime sentinel standing in for the -1 (batch) dim during abstract eval
BATCH_SENTINEL = 12289


class EmitContext:
    """Per-trace state handed to emitters: deterministic RNG, mode flags, mesh.

    RNG design (TPU-native): every Operator instance owns a stable uid; the key
    for op U at step S is fold_in(fold_in(seed_key, S), U). A "__vjp__" grad op
    replays its forward op under the *forward op's* uid, so e.g. the dropout
    mask in backward matches forward exactly — the reference saves an explicit
    mask tensor instead (dropout_op.cc); here determinism makes that free.
    """

    def __init__(
        self, step_key=None, is_test=False, mesh_axes=(), scope=None,
        abstract=False, axis_sizes=None, program=None,
    ):
        self.step_key = step_key
        self.is_test = is_test
        self.mesh_axes = tuple(mesh_axes)  # axis names visible inside shard_map
        # static axis sizes {name: size} (ring collectives need the step
        # count at trace time; mesh topology is static under SPMD)
        self.axis_sizes = dict(axis_sizes or {})
        self.scope = scope
        # True only during infer_shapes' eval_shape pass: emitters may then
        # substitute BATCH_SENTINEL for -1 dims; at run time -1 is an error
        self.abstract = abstract
        # the Program being traced: control-flow emitters resolve their
        # sub_block attr through it (while/cond/scan_block, ops/control_flow.py)
        self.program = program
        # >1 only while tracing inside pipeline_block stage bodies: runtime
        # batches are 1/divisor of graph-build shapes (microbatching), and
        # batch-shape-baking ops (reshape2) may re-derive their leading dim
        self.batch_divisor = 1
        # mutable {"bytes": float} the Executor attaches so the collective
        # emitters (ops/collective.py) can accumulate the executable's
        # estimated per-step wire payload for the perf.* attribution;
        # None outside an Executor trace (infer_shapes replay etc.)
        self.wire_stats = None

    def with_batch_divisor(self, divisor):
        c = EmitContext.__new__(EmitContext)
        c.__dict__.update(self.__dict__)
        c.batch_divisor = int(divisor)
        return c

    def with_key(self, new_key):
        """Shallow copy with a different step_key (loop bodies fold the
        iteration index in so dropout masks vary across iterations)."""
        c = EmitContext.__new__(EmitContext)
        c.__dict__.update(self.__dict__)
        c.step_key = new_key
        return c

    def key_for(self, op_uid: int, op_type: str = ""):
        # salt by op type: uids are per-Program, so two programs sharing a
        # random_seed (e.g. main + startup built together) could otherwise
        # collide at (seed, step=0, uid) — gaussian init correlating with a
        # dropout mask. Same-structure programs still get identical streams.
        salt = zlib.crc32(op_type.encode()) & 0x7FFFFFFF
        base = (
            jax.random.key(op_uid)
            if self.step_key is None
            else jax.random.fold_in(self.step_key, op_uid)
        )
        return jax.random.fold_in(base, salt)


class OpView:
    """Lightweight stand-in for an Operator (used when a grad op replays its
    forward op's emitter: same attrs, same uid => same RNG stream)."""

    def __init__(self, op_type, attrs, inputs=None, outputs=None):
        self.type = op_type
        self.attrs = dict(attrs or {})
        self.inputs = inputs or {}
        self.outputs = outputs or {}

    @property
    def uid(self):
        return self.attrs.get("__uid__", 0)

    def attr(self, name, default=None):
        return self.attrs.get(name, default)


class OpDef:
    def __init__(
        self,
        type,
        emit,
        input_slots,
        output_slots,
        differentiable=True,
        grad_maker=None,
        infer_shape=None,
        mutates=(),
    ):
        self.type = type
        self.emit = emit  # fn(ctx, op, ins) -> outs (dict slot -> list)
        self.input_slots = tuple(input_slots)
        self.output_slots = tuple(output_slots)
        self.differentiable = differentiable
        self.grad_maker = grad_maker  # fn(op, grad_out_names, block) -> ...
        self.infer_shape = infer_shape
        self.mutates = tuple(mutates)  # output slots aliasing an input slot


_REGISTRY: dict[str, OpDef] = {}


def register_op(type, inputs, outputs, **kw):
    """Decorator: @register_op("relu", inputs=["X"], outputs=["Out"])."""

    def deco(fn):
        _REGISTRY[type] = OpDef(type, fn, inputs, outputs, **kw)
        return fn

    return deco


def get_op_def(op_type: str) -> OpDef:
    if op_type not in _REGISTRY:
        from ..errors import UnimplementedError

        raise UnimplementedError(
            f"op type {op_type!r} is not registered"
        )
    return _REGISTRY[op_type]


def registered_ops():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# shape inference via abstract eval of the emitter
# ---------------------------------------------------------------------------


def _spec_from_var(var):
    shape = []
    for s in var.shape or ():
        if s in (-1, None):
            shape.append(BATCH_SENTINEL)
        else:
            s = int(s)
            if s != 0 and s % BATCH_SENTINEL == 0:
                # a real dim that is a multiple of the sentinel would silently
                # round-trip to -1 in _shape_back; refuse instead of corrupting
                raise ValueError(
                    f"variable {var.name!r} has dim {s}, a multiple of the "
                    f"internal batch sentinel {BATCH_SENTINEL}; pad the dim "
                    "by one or use explicit infer_shape for this op"
                )
            shape.append(s)
    return jax.ShapeDtypeStruct(tuple(shape), to_numpy_dtype(var.dtype))


def _shape_back(shape):
    return tuple(
        -1 if (d != 0 and d % BATCH_SENTINEL == 0) else int(d) for d in shape
    )


def infer_shapes(op_type, block, inputs, attrs):
    """Return {slot: [(shape, dtype_name), ...]} for op outputs.

    inputs: {slot: [var names]}. Uses eval_shape over the emitter, so any
    registered op gets shape/dtype inference for free.
    """
    from ..core.dtypes import convert_dtype
    from .program import Operator

    op_def = get_op_def(op_type)
    if op_def.infer_shape is not None:
        return op_def.infer_shape(block, inputs, attrs)

    in_specs = {
        slot: [
            _spec_from_var(block.var(n)) if n else None for n in names
        ]
        for slot, names in (inputs or {}).items()
    }
    fake_op = Operator(block, op_type, inputs, {}, attrs)
    ctx = EmitContext(
        step_key=None, is_test=True, abstract=True, program=block.program
    )

    def absfn(specs):
        return op_def.emit(ctx, fake_op, specs)

    out = jax.eval_shape(absfn, in_specs)
    result = {}
    for slot, vals in out.items():
        result[slot] = [
            (None, None)
            if v is None
            else (_shape_back(v.shape), convert_dtype(v.dtype))
            for v in vals
        ]
    return result


# ---------------------------------------------------------------------------
# flat-call helper used by the executor and by the generic vjp grad op
# ---------------------------------------------------------------------------


def flatten_ins(op):
    """[(slot, idx, name)] for every non-empty input of an op, stable order."""
    out = []
    for slot in sorted(op.inputs):
        for i, n in enumerate(op.inputs[slot]):
            if n:
                out.append((slot, i, n))
    return out


def flatten_outs(op):
    out = []
    for slot in sorted(op.outputs):
        for i, n in enumerate(op.outputs[slot]):
            if n:
                out.append((slot, i, n))
    return out


def run_op(ctx, op, env):
    """Execute one op's emitter against an env (name -> jax value)."""
    op_def = get_op_def(op.type)
    ins = {
        slot: [env[n] if n else None for n in names]
        for slot, names in op.inputs.items()
    }
    outs = op_def.emit(ctx, op, ins)
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for n, v in itertools.zip_longest(names, vals):
            if n and v is not None:
                env[n] = v
    return outs
