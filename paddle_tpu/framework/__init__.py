"""Framework core: Program IR, registry, executor, backward, scope."""

from . import unique_name  # noqa: F401
from .program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    device_guard,
    grad_var_name,
    in_dygraph_mode,
    program_guard,
)
from .scope import Scope, global_scope, scope_guard  # noqa: F401
