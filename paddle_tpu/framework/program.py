"""Program / Block / Operator / Variable — the static-graph IR.

Capability parity with the reference's ProgramDesc/BlockDesc/OpDesc/VarDesc
(framework/framework.proto:42-216 and python/paddle/fluid/framework.py in the
reference repo), re-designed for the XLA compilation model:

* The IR is a pure Python graph (no protobuf round-trip needed on the hot
  path): an Operator names its input/output Variables per slot; a Block is an
  ordered op list + var map; a Program is a list of Blocks.
* There are NO per-op kernels. Every op type registers a JAX *emitter*
  (see registry.py); the Executor lowers a whole block to one XLA computation
  (jit) instead of interpreting ops one-by-one (the reference's hot loop at
  executor.cc:469-476). This is the TPU-native analogue of the reference's
  ChooseKernel dispatch (operator.cc:1032).
* Values live in a Scope (name -> array), exactly as the reference's
  Scope/Variable (scope.h:46) — but buffers are jax Arrays already resident
  on device, and the Executor donates mutated persistables back, so optimizer
  updates are in-place at the XLA buffer level.

Shapes use -1 for the batch (data-dependent) dimension at graph-build time;
at Executor.run the feed arrays pin concrete shapes and the whole block is
compiled static-shape (XLA requirement). Recompiles are cached per shape set.
"""

from __future__ import annotations

import contextlib
import copy

import numpy as np

from ..core.dtypes import convert_dtype, to_numpy_dtype
from . import unique_name


class Variable:
    """Graph-time variable metadata. Runtime values live in a Scope."""

    def __init__(
        self,
        block,
        name,
        shape=None,
        dtype="float32",
        persistable=False,
        stop_gradient=False,
        is_data=False,
        lod_level=0,
        initializer=None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level  # kept for API parity; ragged data is packed at the pipeline edge
        self.initializer = initializer

    # --- fluid-style operator sugar (builds ops in the variable's block) ---
    def _elementwise(self, other, op_type, reverse=False):
        from .. import layers

        fn = {
            "elementwise_add": layers.elementwise_add,
            "elementwise_sub": layers.elementwise_sub,
            "elementwise_mul": layers.elementwise_mul,
            "elementwise_div": layers.elementwise_div,
            "less_than": layers.less_than,
            "less_equal": layers.less_equal,
            "greater_than": layers.greater_than,
            "greater_equal": layers.greater_equal,
            "elementwise_floordiv": layers.elementwise_floordiv,
            "elementwise_mod": layers.elementwise_mod,
        }[op_type]
        if not isinstance(other, Variable):
            other = layers.fill_constant([1], self.dtype, float(other))
        a, b = (other, self) if reverse else (self, other)
        return fn(a, b)

    def __add__(self, other):
        return self._elementwise(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._elementwise(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._elementwise(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._elementwise(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._elementwise(other, "elementwise_div")

    def __floordiv__(self, other):
        return self._elementwise(other, "elementwise_floordiv")

    def __rfloordiv__(self, other):
        return self._elementwise(other, "elementwise_floordiv", reverse=True)

    def __mod__(self, other):
        return self._elementwise(other, "elementwise_mod")

    # comparisons build compare ops (fluid math_op_patch parity) — used by
    # @declarative-converted tensor conditions in static mode
    def __lt__(self, other):
        return self._elementwise(other, "less_than")

    def __le__(self, other):
        return self._elementwise(other, "less_equal")

    def __gt__(self, other):
        return self._elementwise(other, "greater_than")

    def __ge__(self, other):
        return self._elementwise(other, "greater_equal")

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={self.shape}, "
            f"dtype={self.dtype}, persistable={self.persistable})"
        )

    @property
    def grad_name(self):
        return grad_var_name(self.name)


class Parameter(Variable):
    """A trainable persistable variable (framework.py:4962 in the reference)."""

    def __init__(self, block, name, shape, dtype, trainable=True, **kw):
        kw.setdefault("persistable", True)
        kw.setdefault("stop_gradient", not trainable)
        super().__init__(block, name, shape=shape, dtype=dtype, **kw)
        self.trainable = trainable
        self.regularizer = kw.get("regularizer")
        self.need_clip = True


def _user_frame():
    """file:line of the first stack frame outside paddle_tpu (cheap: walks
    raw frames, no traceback formatting). Disabled by FLAGS_op_provenance."""
    from ..flags import flag

    if not flag("op_provenance"):
        return None
    import sys

    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if "paddle_tpu" not in fn:
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return None


_program_counter = 0

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class Operator:
    """One op instance: type + named input/output slots + attrs.

    Slots map slot-name -> list of variable names, mirroring the reference's
    OpDesc (framework.proto:42). Attrs must stay picklable (plain python data)
    so Programs serialize for save_inference_model.
    """

    def __init__(self, block, op_type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = op_type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # ambient placement annotation (reference op_device attr honored at
        # operator.cc:1050-1075; set by device_guard, consumed by
        # PipelineOptimizer's stage slicing)
        if _current_device is not None and "op_device" not in self.attrs:
            self.attrs["op_device"] = _current_device
        # creation provenance: the user frame that built this op, attached
        # to trace/runtime errors (reference framework/op_call_stack.cc)
        if "__loc__" not in self.attrs:
            loc = _user_frame()
            if loc:
                self.attrs["__loc__"] = loc
        # stable identity used to derive per-op RNG keys (registry.EmitContext);
        # per-Program (not global) so two identically-built programs get
        # identical RNG streams; survives deepcopy/clone so test-mode
        # programs keep the same streams
        self.uid = self.attrs.setdefault("__uid__", block.program._next_uid())

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op({self.type}: {ins} -> {outs})"


class Block:
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            from ..errors import NotFoundError

            raise NotFoundError(self._not_found_message(name))
        return v

    def _not_found_message(self, name):
        """Lookup-failure diagnostic: nearest existing names (did-you-mean)
        plus the block's feed and persistable sets, so a typo'd fetch/feed
        is obvious without dumping the whole Program."""
        import difflib

        names, feeds, persist = [], [], []
        blk = self
        while blk is not None:
            for n, v in blk.vars.items():
                names.append(n)
                if v.is_data:
                    feeds.append(n)
                elif v.persistable:
                    persist.append(n)
            blk = blk.parent_block
        msg = [f"variable {name!r} not found in block {self.idx}"]
        close = difflib.get_close_matches(name, names, n=3, cutoff=0.6)
        if close:
            msg.append(
                "did you mean " + " / ".join(repr(c) for c in close) + "?"
            )

        def _fmt(group, cap=8):
            shown = ", ".join(sorted(group)[:cap])
            more = len(group) - cap
            return shown + (f", ... +{more} more" if more > 0 else "")

        msg.append(
            f"block declares {len(names)} vars"
            + (f"; feeds: [{_fmt(feeds)}]" if feeds else "; no feed vars")
            + (
                f"; persistables: [{_fmt(persist)}]"
                if persist else "; no persistables"
            )
        )
        return "; ".join(msg)

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def create_var(self, name=None, **kw):
        if name is None:
            name = unique_name.generate("tmp")
        v = Variable(self, name, **kw)
        if name in self.vars:
            self._note_redefinition(name, v)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name, shape, dtype, **kw):
        p = Parameter(self, name, shape, dtype, **kw)
        if name in self.vars:
            self._note_redefinition(name, p)
        self.vars[name] = p
        self.program._bump()
        return p

    def _note_redefinition(self, name, new_v):
        """create_var/create_parameter used to overwrite an existing entry
        in self.vars with no signal — orphaning the old Variable while ops
        keep referencing the name. Record the event for the static
        verifier (analysis/structural.py reports it; ERROR under strict)
        and warn immediately when the respec is observable (shape, dtype,
        persistability, or Parameter-ness changed)."""
        old = self.vars[name]
        changes = []
        if tuple(old.shape or ()) != tuple(new_v.shape or ()):
            changes.append(f"shape {old.shape} -> {new_v.shape}")
        if old.dtype != new_v.dtype:
            changes.append(f"dtype {old.dtype} -> {new_v.dtype}")
        if bool(old.persistable) != bool(new_v.persistable):
            changes.append(
                f"persistable {old.persistable} -> {new_v.persistable}"
            )
        if type(old) is not type(new_v):
            changes.append(
                f"class {type(old).__name__} -> {type(new_v).__name__}"
            )
        detail = ", ".join(changes) if changes else "identical spec"
        self.__dict__.setdefault("_redefinitions", []).append({
            "name": name,
            "spec_changed": bool(changes),
            "detail": detail,
            "loc": _user_frame(),
        })
        if changes:
            import warnings

            from ..errors import ProgramVerifyWarning

            warnings.warn(
                f"variable {name!r} in block {self.idx} silently redefined "
                f"({detail}); the previous Variable object is orphaned but "
                "existing ops still reference this name",
                ProgramVerifyWarning,
                stacklevel=3,
            )

    def append_op(self, type, inputs=None, outputs=None, attrs=None, index=None):
        op = Operator(self, type, inputs, outputs, attrs)
        if index is None:
            self.ops.append(op)
        else:
            self.ops.insert(index, op)
        self.program._bump()
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def infer_and_create_output(
        self, op_type, inputs, attrs, out_name=None, out_slot="Out", **var_kw
    ):
        """Create the output Variable for slot `out_slot` of an op, inferring
        shape and dtype from the op's JAX emitter (registry.infer_shapes)."""
        from .registry import infer_shapes

        out_specs = infer_shapes(op_type, self, inputs, attrs)
        shape, dtype = out_specs[out_slot][0]
        name = out_name or unique_name.generate(op_type)
        return self.create_var(name=name, shape=shape, dtype=dtype, **var_kw)


class Program:
    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._mesh = None  # set by parallel transpilers / SPMD mode
        self._sharding = {}  # var name -> PartitionSpec-like tuple
        # "shard_map": explicit collective ops see mesh axis names (the
        # transpiled/fleet path). "gspmd": sharding annotations only, XLA
        # inserts collectives by propagation (the TP/auto path).
        self._spmd_mode = "shard_map"
        self._pipeline = None  # set by PipelineOptimizer
        self._op_uid = 0
        # per-program run counter folded into the step RNG key; advances on
        # every Executor.run so seeded programs still vary dropout per step
        self._rng_step = 0
        # fluid treats random_seed=0 as "nondeterministic": unseeded programs
        # draw a per-instance nonce so independent Programs (and restarted
        # processes) get decorrelated RNG streams. clone() deep-copies the
        # nonce, so a for_test clone keeps its parent's streams.
        import random as _random

        self._rng_nonce = _random.SystemRandom().getrandbits(31) | 1
        # process-wide creation ordinal: rank-consistent when every process
        # builds its programs in the same order (see _structural_seed)
        global _program_counter
        _program_counter += 1
        self._creation_ordinal = _program_counter

    def _next_uid(self):
        uid = self._op_uid
        self._op_uid += 1
        return uid

    def _bump(self):
        self._version += 1

    def _structural_seed(self):
        """Deterministic seed from program structure + a process-wide
        creation ordinal: identical on every process of a multi-controller
        job that built its programs in the same order (the rank-consistency
        contract multi-controller SPMD already requires), while two
        same-structured programs in ONE job still get distinct streams.
        Cached per program version (executor hot path)."""
        cached = self.__dict__.get("_structural_seed_cache")
        if cached is not None and cached[0] == self._version:
            return cached[1]
        import zlib

        sig = ",".join(
            f"{op.type}:{op.uid}" for b in self.blocks for op in b.ops
        )
        sig += f"#{self._creation_ordinal}"
        seed = (zlib.crc32(sig.encode()) & 0x7FFFFFFF) | 1
        self._structural_seed_cache = (self._version, seed)
        return seed

    def rng_state(self):
        """Snapshot of the per-program RNG stream position: the seed mode,
        the per-run step counter the executor folds into each step key,
        and the unseeded-program nonce. Together with the scope's
        persistables this makes `Executor.run` bitwise replayable — the
        exact-resume checkpoint (TrainStatus v2) carries it."""
        return {
            "random_seed": int(self.random_seed),
            "rng_step": int(self._rng_step),
            "rng_nonce": int(self._rng_nonce),
        }

    def set_rng_state(self, state):
        """Restore :meth:`rng_state`. A rebuilt program in a restarted
        process draws a fresh nonce; restoring the saved one re-aligns the
        unseeded stream with the run that wrote the checkpoint."""
        if not state:
            return
        if "random_seed" in state:
            self.random_seed = int(state["random_seed"])
        if "rng_step" in state:
            self._rng_step = int(state["rng_step"])
        nonce = state.get("rng_nonce")
        if nonce:
            self._rng_nonce = int(nonce)

    def estimate(self, feed_shapes=None, peak_tflops=None, peak_gbps=None):
        """Analytic per-op FLOPs / bytes / roofline-latency table for ONE
        step of this program (analysis/cost.py): the observability half of
        the IR cost model — ``bench.py`` derives MFU from it, the executor
        feeds the live ``perf.*`` gauges with it, and the planned autotuner
        consumes it as its objective. `feed_shapes` ({var: shape}) pins -1
        batch dims; peaks default from ``PADDLE_TPU_PEAK_TFLOPS`` /
        ``PADDLE_TPU_PEAK_GBPS`` (TPU v5e bf16). The table also carries
        the static HBM plan (``peak_bytes`` / ``resident_bytes`` /
        ``memory`` — analysis/memory.py's live-interval walk), the number
        ``serving.Server.warmup`` budgets against. Pure graph walk over
        declared Variable shapes — no tracing, no compilation."""
        from ..analysis.cost import estimate_program

        return estimate_program(
            self, feed_shapes=feed_shapes, peak_tflops=peak_tflops,
            peak_gbps=peak_gbps,
        )

    @property
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        self._bump()
        return blk

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx
        if self.current_block_idx < 0:
            self.current_block_idx = 0

    def all_parameters(self):
        return [p for b in self.blocks for p in b.all_parameters()]

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def __getstate__(self):
        # the Mesh holds live device handles — never serialized; a loaded
        # Program is re-attached to a mesh by the caller (shard_program).
        # The verifier's per-version report cache is transient state.
        state = self.__dict__.copy()
        state["_mesh"] = None
        state.pop("_verify_cache", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        # fields added after a model file was saved get their defaults
        self.__dict__.setdefault("_rng_step", 0)
        if "_rng_nonce" not in self.__dict__:
            import random as _random

            self._rng_nonce = _random.SystemRandom().getrandbits(31) | 1
        if "_creation_ordinal" not in self.__dict__:
            global _program_counter
            _program_counter += 1
            self._creation_ordinal = _program_counter
        self.__dict__.setdefault("_spmd_mode", "shard_map")
        self.__dict__.setdefault("_pipeline", None)

    def clone(self, for_test=False):
        """Deep copy. for_test=True flips is_test on ops that honor it
        (dropout/batch_norm), matching fluid Program.clone semantics."""
        p = copy.deepcopy(self)
        if for_test:
            for b in p.blocks:
                for op in b.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
        p._bump()
        return p

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.parent_idx}):")
            for op in b.ops:
                lines.append(f"  {op}")
        return "\n".join(lines)


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_main, old_startup


# --- device_guard (reference fluid.device_guard; op_device attr) ---
_current_device = None


@contextlib.contextmanager
def device_guard(device=None):
    """Annotate appended ops with a placement string. For pipeline
    parallelism use "pipeline:K" stage tags (reference PipelineOptimizer
    contract, optimizer.py:3556)."""
    global _current_device
    old = _current_device
    _current_device = device
    try:
        yield
    finally:
        _current_device = old


# --- dygraph mode switch (framework.py:180 in the reference) ---
_dygraph_tracer = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer is not None


def _set_dygraph_tracer(tracer):
    global _dygraph_tracer
    _dygraph_tracer = tracer


def _current_tracer():
    return _dygraph_tracer
