"""Scope: hierarchical name -> value store (reference: framework/scope.h:46).

Values are jax Arrays (usually already resident in TPU HBM) or numpy arrays.
The Executor reads persistables from the scope, runs the compiled block, and
writes mutated persistables back — donation makes that an in-place HBM update.
"""

from __future__ import annotations

import contextlib


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self.kids = []

    def new_scope(self):
        s = Scope(self)
        self.kids.append(s)
        return s

    def drop_kids(self):
        self.kids = []

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name):
        return self.find_var(name) is not None

    def set_var(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()
_current_scope = _global_scope


def global_scope() -> Scope:
    """The active scope. scope_guard() swaps it, matching fluid semantics
    (executor.py in the reference resolves global_scope() per run)."""
    return _current_scope


current_scope = global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _current_scope
    old = _current_scope
    _current_scope = scope
    try:
        yield
    finally:
        _current_scope = old
