"""Runtime stat counters — compatible facade over `paddle_tpu.observability`.

The seed's minimal StatRegistry API (named monotonic counters + gauges,
snapshot/reset; reference platform/monitor.h STAT_ADD) is preserved
verbatim for existing callers; the real registry now lives in
`observability/` and also provides histograms, `timed()` wall-clock
timers, host spans with Chrome-trace export, and Prometheus/JSON
exporters — import `paddle_tpu.observability` for those (the most-used
ones are re-exported here).

Wired-in producers (see README.md §Observability for the full name
catalog): the Executor bumps `executor.run_steps`, `executor.compile_count`
and the cache hit/miss/eviction counters and records the
`executor.step_latency` / `executor.compile_time` histograms; the
dataloader bumps `dataloader.batches` and records `dataloader.batch_wait`;
collectives count ops and payload bytes under `collective.*`. Anything
else can `monitor.add("my.counter", n)`.
"""

from __future__ import annotations

from . import observability as _obs
from .observability import (  # noqa: F401  (convenience re-exports)
    dump,
    observe,
    prometheus_text,
    snapshot,
    span,
    timed,
)


def add(name: str, value: int = 1) -> None:
    """STAT_ADD: bump the integer counter `name` by value."""
    _obs.add(name, value)


def set_float(name: str, value: float) -> None:
    """Gauge write (STAT_RESET/float stat)."""
    _obs.set_gauge(name, value)


def get_int_stats() -> dict[str, int]:
    return _obs.get_counters()


def get_float_stats() -> dict[str, float]:
    return _obs.get_gauges()


def reset() -> None:
    _obs.reset()
