"""Runtime stat counters (VERDICT r3 missing item 3 "runtime
observability utilities"; the reference grew an equivalent StatRegistry /
STAT_ADD layer in platform/monitor.h in later releases — absent from this
v1.8 vintage, so the API here is the minimal registry that layer
provides: named monotonic counters + gauges, snapshot/reset).

Wired-in producers: the Executor bumps `executor.run_steps` and
`executor.compile_count`; the dataloader bumps `dataloader.batches`.
Anything else can `monitor.add("my.counter", n)`.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_int_stats: dict[str, int] = {}
_float_stats: dict[str, float] = {}


def add(name: str, value: int = 1) -> None:
    """STAT_ADD: bump the integer counter `name` by value."""
    with _lock:
        _int_stats[name] = _int_stats.get(name, 0) + int(value)


def set_float(name: str, value: float) -> None:
    """Gauge write (STAT_RESET/float stat)."""
    with _lock:
        _float_stats[name] = float(value)


def get_int_stats() -> dict[str, int]:
    with _lock:
        return dict(_int_stats)


def get_float_stats() -> dict[str, float]:
    with _lock:
        return dict(_float_stats)


def reset() -> None:
    with _lock:
        _int_stats.clear()
        _float_stats.clear()
