"""Composite network helpers (reference python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from __future__ import annotations

import math

from . import layers


def simple_img_conv_pool(
    input, num_filters, filter_size, pool_size, pool_stride,
    pool_padding=0, pool_type="max", global_pooling=False,
    conv_stride=1, conv_padding=0, conv_dilation=1, conv_groups=1,
    param_attr=None, bias_attr=None, act=None, use_cudnn=True,
):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(
    input, conv_num_filter, pool_size, conv_padding=1, conv_filter_size=3,
    conv_act=None, param_attr=None, conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0.0, pool_stride=1, pool_type="max",
    use_cudnn=True,
):
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]

    def bcast(v, n):
        return v if isinstance(v, (list, tuple)) else [v] * n

    n = len(conv_num_filter)
    paddings = bcast(conv_padding, n)
    fsizes = bcast(conv_filter_size, n)
    with_bn = bcast(conv_with_batchnorm, n)
    drops = bcast(conv_batchnorm_drop_rate, n)
    attrs = param_attr if isinstance(param_attr, (list, tuple)) else [param_attr] * n

    tmp = input
    for i in range(n):
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=fsizes[i], padding=paddings[i],
            param_attr=attrs[i],
            act=None if with_bn[i] else conv_act,
        )
        if with_bn[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if drops[i] > 0:
                tmp = layers.dropout(tmp, dropout_prob=drops[i])
    return layers.pool2d(
        input=tmp, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride,
    )


def glu(input, dim=-1):
    """Gated linear unit: split in half on `dim`, a * sigmoid(b)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(
    queries, keys, values, num_heads=1, dropout_rate=0.0,
):
    """Multi-head attention over [B, S, D] inputs (reference nets.py:503)."""
    b, sq, d = queries.shape
    _, sk, _ = keys.shape
    if d % num_heads:
        raise ValueError("hidden size must divide num_heads")
    dh = d // num_heads

    def split_heads(x, s):
        x = layers.reshape(x, [b, s, num_heads, dh])
        return layers.transpose(x, [0, 2, 1, 3])

    q = split_heads(queries, sq)
    k = split_heads(keys, sk)
    v = split_heads(values, sk)
    scores = layers.matmul(
        q, k, transpose_y=True, alpha=1.0 / math.sqrt(dh)
    )
    weights = layers.softmax(scores, axis=-1)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    return layers.reshape(ctx, [b, sq, d])
