"""CompiledProgram shim (reference python/paddle/fluid/compiler.py:87,
with_data_parallel :160).

The reference's CompiledProgram constructed a C++ ParallelExecutor; here
data parallelism is mesh + shard_map (parallel/spmd.py), so
with_data_parallel attaches a dp mesh, inserts the grad allreduce if a
loss_name is given, and Executor.run accepts the CompiledProgram wherever a
Program goes (it unwraps .program)."""

from __future__ import annotations

from .framework.program import Program


class BuildStrategy:
    """Knob bag parity (details/build_strategy.h:38-135). XLA owns
    scheduling/fusion/memory, so knobs are accepted and recorded only."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_reduce_ops = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0  # XLA owns threading
        self.num_iteration_per_drop_scope = 1


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        if isinstance(program_or_graph, CompiledProgram):
            program_or_graph = program_or_graph.program
        if not isinstance(program_or_graph, Program):
            raise TypeError("CompiledProgram expects a Program")
        self.program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        from .parallel.mesh import DATA_AXIS, make_mesh
        from .parallel.spmd import shard_program
        from .parallel.transpiler import GradAllReduce

        if build_strategy is not None:
            self._build_strategy = build_strategy
        mesh = make_mesh(devices=places)
        dp = mesh.shape.get(DATA_AXIS, 1)
        if loss_name is not None and dp > 1:
            blk = self.program.global_block
            pgs = []
            seen = set()
            for op in blk.ops:
                if op.type in ("sgd", "momentum", "adam", "adamw", "lamb"):
                    p = op.inputs["Param"][0]
                    g = op.inputs["Grad"][0]
                    if g not in seen:
                        pgs.append((blk.var(p), blk.var(g)))
                        seen.add(g)
            GradAllReduce(dp).transpile(self.program, pgs)
        shardings = {
            v.name: (DATA_AXIS,)
            for v in self.program.list_vars()
            if v.is_data
        }
        shard_program(self.program, mesh, shardings)
        self._is_data_parallel = True
        return self
