"""paddle.tensor.creation (reference python/paddle/tensor/creation.py aliases)."""

from ..layers import fill_constant  # noqa: F401
from ..layers import fill_constant as full  # noqa: F401
from ..layers import ones  # noqa: F401
from ..layers import ones_like  # noqa: F401
from ..layers import tril  # noqa: F401
from ..layers import triu  # noqa: F401
from ..layers import zeros  # noqa: F401
from ..layers import zeros_like  # noqa: F401

from ._helper import op_fn as _op_fn
full_like = None  # assigned below (fill_any_like op bridge)

create_tensor = None  # assigned below
crop_tensor = _op_fn("crop_tensor")
diag = _op_fn("diag")
eye = _op_fn("eye")
linspace = _op_fn("linspace")
meshgrid = _op_fn("meshgrid")
get_tensor_from_selected_rows = _op_fn("get_tensor_from_selected_rows")
arange = _op_fn("range")
full_like = _op_fn("fill_any_like")


def _create_tensor(dtype="float32", name=None):
    from ..framework.program import default_main_program
    from ..framework import unique_name

    return default_main_program().current_block().create_var(
        name=name or unique_name.generate("tensor"), shape=[1], dtype=dtype
    )


create_tensor = _create_tensor
