"""paddle.tensor.attribute (reference python/paddle/tensor/attribute.py aliases)."""

from ..layers import shape  # noqa: F401

def rank(input):
    from ..layers import fill_constant

    return fill_constant([1], "int32", float(len(input.shape or ())))
