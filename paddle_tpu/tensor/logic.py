"""paddle.tensor.logic (reference python/paddle/tensor/logic.py aliases)."""

from ..layers import equal  # noqa: F401
from ..layers import greater_equal  # noqa: F401
from ..layers import greater_than  # noqa: F401
from ..layers import less_equal  # noqa: F401
from ..layers import less_than  # noqa: F401
from ..layers import logical_and  # noqa: F401
from ..layers import logical_not  # noqa: F401
from ..layers import logical_or  # noqa: F401
from ..layers import not_equal  # noqa: F401

from ._helper import op_fn as _op_fn

allclose = _op_fn("allclose")
is_empty = _op_fn("is_empty")
isfinite = _op_fn("isfinite")
logical_xor = _op_fn("logical_xor")
reduce_all = _op_fn("reduce_all")
reduce_any = _op_fn("reduce_any")


def isnan(x, name=None):
    from ..layers import logical_not

    return logical_not(_op_fn("isfinite")(x))
