"""paddle.tensor.stat (reference python/paddle/tensor/stat.py aliases)."""

from ..layers import reduce_mean as mean  # noqa: F401
from ..layers import reduce_mean  # noqa: F401

def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    from ..layers import sqrt

    return sqrt(var(x, axis=axis, unbiased=unbiased, keepdim=keepdim))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    from ..layers import reduce_mean, square
    import numpy as _np

    m = reduce_mean(x, dim=axis, keep_dim=True)
    v = reduce_mean(square(x - m), dim=axis, keep_dim=keepdim)
    if unbiased:
        if axis is None:
            n = int(_np.prod(x.shape))
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            n = int(_np.prod([x.shape[a] for a in axes]))
        if n > 1:
            v = v * (n / (n - 1))
    return v
