"""paddle.tensor.math (reference python/paddle/tensor/math.py aliases)."""

from ..layers import abs  # noqa: F401
from ..layers import elementwise_add as add  # noqa: F401
from ..layers import ceil  # noqa: F401
from ..layers import clip as clamp  # noqa: F401
from ..layers import cos  # noqa: F401
from ..layers import cumsum  # noqa: F401
from ..layers import elementwise_div as div  # noqa: F401
from ..layers import elementwise_add  # noqa: F401
from ..layers import elementwise_div  # noqa: F401
from ..layers import elementwise_max  # noqa: F401
from ..layers import elementwise_min  # noqa: F401
from ..layers import elementwise_mod  # noqa: F401
from ..layers import elementwise_mul  # noqa: F401
from ..layers import elementwise_pow  # noqa: F401
from ..layers import elementwise_sub  # noqa: F401
from ..layers import sums as elementwise_sum  # noqa: F401
from ..layers import erf  # noqa: F401
from ..layers import exp  # noqa: F401
from ..layers import floor  # noqa: F401
from ..layers import increment  # noqa: F401
from ..layers import log  # noqa: F401
from ..layers import reduce_max as max  # noqa: F401
from ..layers import reduce_min as min  # noqa: F401
from ..layers import matmul as mm  # noqa: F401
from ..layers import mul  # noqa: F401
from ..layers import elementwise_pow as pow  # noqa: F401
from ..layers import reciprocal  # noqa: F401
from ..layers import reduce_max  # noqa: F401
from ..layers import reduce_min  # noqa: F401
from ..layers import reduce_prod  # noqa: F401
from ..layers import reduce_sum  # noqa: F401
from ..layers import round  # noqa: F401
from ..layers import rsqrt  # noqa: F401
from ..layers import scale  # noqa: F401
from ..layers import sign  # noqa: F401
from ..layers import sin  # noqa: F401
from ..layers import sqrt  # noqa: F401
from ..layers import square  # noqa: F401
from ..layers import stanh  # noqa: F401
from ..layers import reduce_sum as sum  # noqa: F401
from ..layers import sums  # noqa: F401
from ..layers import tanh  # noqa: F401

from ._helper import op_fn as _op_fn

elementwise_floordiv = _op_fn("elementwise_floordiv")
inverse = _op_fn("inverse")
kron = _op_fn("kron")
log1p = _op_fn("log1p")
multiplex = _op_fn("multiplex")
trace = _op_fn("trace")
addmm = _op_fn("addmm")


def acos(x, name=None):
    from ..layers.tensor import _simple

    return _simple("acos", {"X": [x]}, {})


def asin(x, name=None):
    from ..layers.tensor import _simple

    return _simple("asin", {"X": [x]}, {})


def atan(x, name=None):
    from ..layers.tensor import _simple

    return _simple("atan", {"X": [x]}, {})


def logsumexp(x, dim=None, keepdim=False, name=None):
    from ..layers import exp, log, reduce_sum

    return log(reduce_sum(exp(x), dim=dim, keep_dim=keepdim))


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    return input + value * (tensor1 * tensor2)
