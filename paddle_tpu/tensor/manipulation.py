"""paddle.tensor.manipulation (reference python/paddle/tensor/manipulation.py aliases)."""

from ..layers import cast  # noqa: F401
from ..layers import concat  # noqa: F401
from ..layers import expand  # noqa: F401
from ..layers import flatten  # noqa: F401
from ..layers import gather  # noqa: F401
from ..layers import gather_nd  # noqa: F401
from ..layers import reshape  # noqa: F401
from ..layers import scatter  # noqa: F401
from ..layers import slice  # noqa: F401
from ..layers import split  # noqa: F401
from ..layers import squeeze  # noqa: F401
from ..layers import stack  # noqa: F401
from ..layers import transpose  # noqa: F401
from ..layers import unsqueeze  # noqa: F401

from ._helper import op_fn as _op_fn

expand_as = _op_fn("expand_as")
flip = _op_fn("flip")
reverse = _op_fn("reverse")
roll = _op_fn("roll")
scatter_nd_add = _op_fn("scatter_nd_add")
shard_index = _op_fn("shard_index")
strided_slice = _op_fn("strided_slice")
unbind = _op_fn("unbind", n_out=1)
unstack = _op_fn("unstack")
unique = _op_fn("unique", n_out=2)
unique_with_counts = _op_fn("unique_with_counts", n_out=3)


def scatter_nd(index, updates, shape, name=None):
    from ..layers import fill_constant

    zero = fill_constant(list(shape), "float32", 0.0)
    return scatter_nd_add(zero, index, updates)
