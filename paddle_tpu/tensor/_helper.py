"""Registry-driven function bridge for the 2.0 tensor namespace: any
registered op with the standard single-output contract becomes a python
function whose positional args fill the op's input slots in order and
whose kwargs become attrs (the reference's alias tree hand-writes each of
these; the registry makes them mechanical)."""

from __future__ import annotations

from ..framework.registry import get_op_def
from ..layers.helper import LayerHelper


def op_fn(op_type, out_slot=None, n_out=1):
    od = get_op_def(op_type)
    slots = list(od.input_slots)
    out = out_slot or od.output_slots[0]

    def fn(*args, name=None, **attrs):
        ins = {s: [None] for s in slots}
        for s, a in zip(slots, args):
            ins[s] = [a]
        helper = LayerHelper(op_type, name=name)
        res = helper.create_and_append(
            {k: v for k, v in ins.items() if v[0] is not None},
            attrs, op_type=op_type,
            out_slots=tuple(od.output_slots[:max(n_out, 1)]),
        )
        if n_out == 1 and isinstance(res, tuple):
            return res[0]
        return res

    fn.__name__ = op_type
    fn.__doc__ = f"Auto-bridged {op_type} op (framework.registry)."
    return fn
