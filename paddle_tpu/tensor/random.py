"""paddle.tensor.random (reference python/paddle/tensor/random.py aliases)."""

from ..layers import uniform_random as rand  # noqa: F401
from ..layers import gaussian_random as randn  # noqa: F401
from ..layers import uniform_random as uniform  # noqa: F401

from ._helper import op_fn as _op_fn

randint = _op_fn("randint")
randperm = _op_fn("randperm")
shuffle = _op_fn("shuffle_batch")
