"""paddle.tensor.linalg (reference python/paddle/tensor/linalg.py aliases)."""

from ..layers import matmul  # noqa: F401
from ..layers import transpose as t  # noqa: F401
from ..layers import transpose  # noqa: F401

from ._helper import op_fn as _op_fn

bmm = _op_fn("bmm")
cholesky = _op_fn("cholesky")
cross = _op_fn("cross")
dist = _op_fn("dist")
dot = _op_fn("dot")
histogram = _op_fn("histogram")


def einsum(equation, *operands):
    from ..layers.tensor import _simple

    return _simple("einsum", {"Operands": list(operands)},
                   {"equation": equation})


def tensordot(x, y, axes=2, name=None):
    # composition: flatten the contracted axes into one matmul
    from ..layers import matmul, reshape, transpose

    if isinstance(axes, int):
        ax = list(range(len(x.shape) - axes, len(x.shape)))
        ay = list(range(axes))
    else:
        ax, ay = list(axes[0]), list(axes[1])
    keep_x = [i for i in range(len(x.shape)) if i not in ax]
    keep_y = [i for i in range(len(y.shape)) if i not in ay]
    import numpy as _np

    kx = int(_np.prod([x.shape[i] for i in keep_x] or [1]))
    cx = int(_np.prod([x.shape[i] for i in ax] or [1]))
    ky = int(_np.prod([y.shape[i] for i in keep_y] or [1]))
    xm = reshape(transpose(x, keep_x + ax), [kx, cx])
    ym = reshape(transpose(y, ay + keep_y), [cx, ky])
    out = matmul(xm, ym)
    return reshape(out, [x.shape[i] for i in keep_x]
                   + [y.shape[i] for i in keep_y])
from ..layers.tensor import _simple as __simple


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    """paddle.norm: frobenius via the frobenius_norm op, p-norms via
    p_norm."""
    if p in ("fro", "FRO", None):
        return __simple(
            "frobenius_norm", {"X": [x]},
            {"reduce_all": axis is None,
             "dim": [axis] if isinstance(axis, int) else (axis or [0]),
             "keep_dim": keepdim},
        )
    return __simple(
        "p_norm", {"X": [x]},
        {"porder": float(p), "axis": axis if axis is not None else -1,
         "keepdim": keepdim},
    )
