"""paddle.tensor.search (reference python/paddle/tensor/search.py aliases)."""

from ..layers import argmax  # noqa: F401
from ..layers import argmin  # noqa: F401
from ..layers import argsort  # noqa: F401
from ..layers import topk  # noqa: F401
from ..layers import where  # noqa: F401

from ._helper import op_fn as _op_fn

index_sample = _op_fn("index_sample")
index_select = _op_fn("index_select")
nonzero = _op_fn("where_index")


def sort(x, axis=-1, descending=False, name=None):
    from ..layers import argsort

    out = argsort(x, axis=axis, descending=descending)
    return out


def has_inf(x):
    from ..layers import cast, logical_not, reduce_sum

    fin = _op_fn("isfinite")(x)
    return logical_not(fin)


has_nan = has_inf  # both reduce to "any non-finite" under the isfinite op


def masked_select(x, mask, name=None):
    """Static-shape contract: zero out unselected entries (the reference
    compacts; see unique's size= convention)."""
    from ..layers import cast

    return x * cast(mask, "float32")
