"""paddle.tensor 2.0-preview namespace (reference
python/paddle/tensor/__init__.py): creation / linalg / logic /
manipulation / math / random / search / stat / attribute alias trees.
Import parity against the reference __all__ lists is enforced by
tests/test_namespaces.py."""

from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from . import (  # noqa: F401
    attribute,
    creation,
    linalg,
    logic,
    manipulation,
    math,
    random,
    search,
    stat,
)

# forward-looking 2.x names kept from the round-2 namespace (the reference
# 1.8 preview exposes the elementwise_* forms; both spellings work here)
from ..layers import (  # noqa: F401
    elementwise_add as add,
    elementwise_div as divide,
    elementwise_max as maximum,
    elementwise_min as minimum,
    elementwise_mul as multiply,
    elementwise_sub as subtract,
)
