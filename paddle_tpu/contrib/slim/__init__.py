from . import analysis, distillation, nas, prune, quantization  # noqa: F401
