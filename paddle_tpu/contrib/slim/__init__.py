from . import distillation, quantization  # noqa: F401
