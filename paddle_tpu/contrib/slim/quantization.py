"""Quantization-aware training + post-training quantization (reference
python/paddle/fluid/contrib/slim/quantization/: QuantizationTransformPass
inserts fake_quantize/dequantize around conv/mul inputs+weights on the IR
graph; PostTrainingQuantization collects activation scales from calibration
batches).

TPU-native: the "pass" is a Program rewrite (like AMP's rewrite_program) —
each quantizable op's inputs are routed through
fake_quantize_dequantize_abs_max ops (straight-through gradients), so QAT
runs inside the same jitted step. INT8 *execution* is out of scope for TPU
v5e's bf16 MXU; the deliverable is quantization-error-aware training and
exported scales, matching what the reference's QAT produces before its
int8 kernel swap.
"""

from __future__ import annotations

from ...framework import unique_name

QUANTIZABLE_OPS = ("mul", "matmul", "conv2d", "depthwise_conv2d")
_WEIGHT_SLOTS = {"Y", "Filter"}


def _weight_quant_axis(op_type, var):
    # output-channel axis: 0 for conv filters [out,in,kh,kw], last for
    # matmul weights [in,out] (reference QuantizationTransformPass)
    return 0 if "conv" in op_type else len(var.shape or (1,)) - 1


def _rewrite_quantizable_inputs(program, quantizable_ops, insert):
    """Shared program walker for QAT transpile and PTQ apply: for every
    float input of every quantizable op, call
    insert(blk, index, op, name, var, is_weight) -> (quantized_name or
    None, ops_inserted); rewires the op input to the quantized name and
    reuses it for later consumers. Returns the quant-dequant count."""
    blk = program.global_block
    quantized = {}  # original name -> quantized name (reuse per block)
    i = 0
    n_inserted = 0
    while i < len(blk.ops):
        op = blk.ops[i]
        if op.type not in quantizable_ops:
            i += 1
            continue
        for slot, names in list(op.inputs.items()):
            new_names = []
            for n in names:
                v = blk._find_var_recursive(n)
                if v is None or v.dtype not in ("float32", "bfloat16"):
                    new_names.append(n)
                    continue
                if n in quantized:
                    new_names.append(quantized[n])
                    continue
                is_weight = slot in _WEIGHT_SLOTS or getattr(
                    v, "persistable", False
                )
                qname, added = insert(blk, i, op, n, v, is_weight)
                if qname is None:
                    new_names.append(n)
                    continue
                i += added
                n_inserted += 1
                quantized[n] = qname
                new_names.append(qname)
            op.inputs[slot] = new_names
        i += 1
    program._bump()
    return n_inserted


class QuantizationTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_ops=QUANTIZABLE_OPS):
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.quantizable_ops = tuple(quantizable_ops)

    def transpile(self, program):
        """Insert fake quant-dequant before every quantizable op's float
        inputs. Weights get channel-wise scales (reference
        QuantizationTransformPass behavior); activations per-tensor."""

        def insert(blk, i, op, n, v, is_weight):
            qname = unique_name.generate(n + ".quantized")
            blk.create_var(name=qname, shape=v.shape, dtype=v.dtype)
            sname = unique_name.generate(n + ".quant_scale")
            blk.create_var(name=sname, shape=(1,), dtype="float32")
            if is_weight:
                blk.append_op(
                    "fake_channel_wise_quantize_dequantize_abs_max",
                    {"X": [n]},
                    {"Out": [qname], "OutScale": [sname]},
                    {"bit_length": self.weight_bits,
                     "quant_axis": _weight_quant_axis(op.type, v)},
                    index=i,
                )
            else:
                blk.append_op(
                    "fake_quantize_dequantize_abs_max",
                    {"X": [n]},
                    {"Out": [qname], "OutScale": [sname]},
                    {"bit_length": self.activation_bits},
                    index=i,
                )
            return qname, 1

        return _rewrite_quantizable_inputs(
            program, self.quantizable_ops, insert
        )


def quant_aware(program, weight_bits=8, activation_bits=8):
    """Convenience: rewrite `program` for QAT; call BEFORE
    optimizer.minimize so the fake-quant ops get differentiated."""
    t = QuantizationTranspiler(weight_bits, activation_bits)
    t.transpile(program)
    return program


# ops whose float outputs get a moving-average scale observer during
# training (reference quantization_pass.py _out_scale_op_list subset that
# exists here)
OUT_SCALE_OPS = (
    "conv2d", "depthwise_conv2d", "mul", "matmul", "relu", "leaky_relu",
    "relu6", "sigmoid", "tanh", "swish", "softmax", "batch_norm",
    "elementwise_add", "elementwise_mul", "pool2d", "concat",
    "reshape2", "transpose2", "dropout",
)


class OutScaleForTrainingPass:
    """Attach a `moving_average_abs_max_scale` observer to every float
    output of the target ops so output ranges are recorded DURING training
    (reference quantization_pass.py OutScaleForTrainingPass: inference
    engines consume these as out_threshold). The observer op's
    accum/state live as persistable scope vars updated in the same jitted
    step (mutates aliasing); `scales()` reads them back."""

    def __init__(self, moving_rate=0.9, op_types=OUT_SCALE_OPS):
        self.moving_rate = float(moving_rate)
        self.op_types = tuple(op_types)

    @staticmethod
    def _state_names(var_name):
        return (f"{var_name}@out_scale.accum", f"{var_name}@out_scale.state")

    # the op's real activation output; auxiliary outputs (batch_norm
    # running stats, dropout Mask, reshape2 XShape) are NOT activations —
    # exporting ranges for them would hand inference engines wrong clip
    # points (reference pass observes the single activation output too)
    _PRIMARY_SLOTS = ("Out", "Y", "Output")

    def apply(self, program, startup_program):
        blk = program.global_block
        sblk = startup_program.global_block
        observed = set()
        i = 0
        n_observers = 0
        while i < len(blk.ops):
            op = blk.ops[i]
            if op.type not in self.op_types:
                i += 1
                continue
            slot = next(
                (s for s in self._PRIMARY_SLOTS if op.outputs.get(s)), None
            )
            for names in ([op.outputs[slot]] if slot else []):
                for name in names:
                    v = blk._find_var_recursive(name)
                    if (v is None or name in observed
                            or v.dtype not in ("float32", "bfloat16")):
                        continue
                    observed.add(name)
                    accum_n, state_n = self._state_names(name)
                    for prog_blk in (blk, sblk):
                        for sn in (accum_n, state_n):
                            prog_blk.create_var(
                                name=sn, shape=(1,), dtype="float32",
                                persistable=True,
                            )
                    for sn in (accum_n, state_n):
                        sblk.append_op(
                            "fill_constant", {}, {"Out": [sn]},
                            {"shape": [1], "dtype": "float32", "value": 0.0},
                        )
                    out_n = unique_name.generate(name + "@out_scale.out")
                    scale_n = unique_name.generate(name + "@out_scale.scale")
                    blk.create_var(name=out_n, shape=v.shape, dtype=v.dtype)
                    blk.create_var(name=scale_n, shape=(1,), dtype="float32")
                    blk.append_op(
                        "moving_average_abs_max_scale",
                        {"X": [name], "InAccum": [accum_n],
                         "InState": [state_n]},
                        {"Out": [out_n], "OutScale": [scale_n],
                         "OutAccum": [accum_n], "OutState": [state_n]},
                        {"moving_rate": self.moving_rate},
                        index=i + 1,
                    )
                    i += 1
                    n_observers += 1
            i += 1
        program._bump()
        return n_observers

    def scales(self, program, scope):
        """{var_name: recorded moving-average abs-max} after training."""
        import numpy as np

        out = {}
        for op in program.global_block.ops:
            if op.type != "moving_average_abs_max_scale":
                continue
            name = op.inputs["X"][0]
            accum = scope.find_var(op.inputs["InAccum"][0])
            state = scope.find_var(op.inputs["InState"][0])
            if accum is None or state is None:
                continue
            s = float(np.asarray(accum).reshape(-1)[0])
            c = float(np.asarray(state).reshape(-1)[0])
            out[name] = s / max(c, 1e-9)
        return out


def _rescale_hist(hist, old_max, new_max, bins):
    """Re-grid a [0, old_max] histogram onto [0, new_max] (new_max >
    old_max), spreading each source bin proportionally over the <=2
    destination bins it covers — the reference's combine_histogram
    rescale, vectorized. O(bins) memory and time."""
    import numpy as np

    f = old_max / new_max  # < 1: each source bin spans at most 2 dst bins
    lo = np.arange(bins, dtype=np.float64) * f
    d0 = np.minimum(lo.astype(np.int64), bins - 1)
    w1 = np.clip(lo + f - (d0 + 1), 0.0, None) / f
    out = np.zeros(bins, np.float64)
    np.add.at(out, d0, hist * (1.0 - w1))
    np.add.at(out, np.minimum(d0 + 1, bins - 1), hist * w1)
    return out


def _kl_threshold(hist, bin_width, quant_bins=255):
    """TensorRT-style KL calibration threshold (reference
    post_training_quantization.py _get_kl_scaling_factor semantics):
    given the |x| histogram, pick the clip point i in the top 30% of bins
    minimizing KL(P_clipped || Q_quantized), where P folds the outlier
    mass into its last bin and Q redistributes i bins merged to
    `quant_bins` levels back over P's nonzero support."""
    import numpy as np

    hist = np.asarray(hist, np.float64)
    bins = hist.shape[0]
    total = hist.sum()
    if total <= 0.0:
        return 0.0
    start = int((bins - 1) * 0.7)
    best_kl, best_i = None, 0
    for i in range(start, bins):
        if hist[i - 1] == 0:
            continue
        p = hist[:i].astype(np.float64)
        p[i - 1] += hist[i:].sum()  # clip: outliers fold into last bin
        # quantize: merge i bins into quant_bins levels, then expand each
        # level's mass uniformly over its nonzero source bins
        merged = i // quant_bins
        q = np.zeros(i, np.float64)
        src = hist[:i].astype(np.float64)
        for b in range(quant_bins):
            j0 = b * merged
            j1 = i if b == quant_bins - 1 else (b + 1) * merged
            seg = src[j0:j1]
            nz = seg > 0
            if nz.any():
                view = q[j0:j1]
                view[nz] = seg.sum() / nz.sum()
        nz = (p > 0) & (q > 0)
        if not nz.any():
            continue
        kl = float(np.sum(
            p[nz] / total * np.log((p[nz] / total) / (q[nz] / q.sum()))
        ))
        if best_kl is None or kl < best_kl:
            best_kl, best_i = kl, i
    if best_i == 0:
        best_i = start
    return (best_i + 0.5) * bin_width


class PostTrainingQuantization:
    """Collect activation scales over calibration batches (reference
    post_training_quantization.py). algo:

    * "abs_max"  — running max of |x| (the r4 behavior)
    * "avg"      — mean of per-batch abs-max
    * "min_max"  — (min, max) pairs per var
    * "hist"     — percentile of the pooled |x| distribution
                   (hist_percent, default 0.99999)
    * "KL"       — TensorRT-style KL-divergence clip point over the
                   pooled |x| histogram; candidates span the top 30% of
                   bins (reference semantics), so the clip floor is
                   0.7*abs_max — use "hist" for aggressive outlier clips
    """

    def __init__(self, executor, program, feed_names, fetch_vars,
                 scope=None, algo="abs_max", hist_percent=0.99999):
        if algo not in ("abs_max", "avg", "min_max", "hist", "KL"):
            raise ValueError(f"unsupported PTQ algo {algo!r}")
        self._exe = executor
        self._program = program
        self._feed_names = feed_names
        self._fetch = fetch_vars
        self._scope = scope
        self._algo = algo
        self._hist_percent = float(hist_percent)

    _BINS = 2048

    def quantize(self, calibration_feeds, var_names):
        import numpy as np

        var_names = list(var_names)  # a generator must survive re-iteration
        # hist/KL keep ONE running histogram per var (rescaled in place
        # when a batch extends the range) — never the raw activations (a
        # conv feature map over 100 calibration batches would be GBs)
        hists = {n: np.zeros(self._BINS, np.float64) for n in var_names}
        hist_max = {n: 0.0 for n in var_names}
        batch_max = {n: [] for n in var_names}
        mins = {n: np.inf for n in var_names}
        maxs = {n: -np.inf for n in var_names}
        n_batches = 0
        for feed in calibration_feeds:
            n_batches += 1
            outs = self._exe.run(
                self._program, feed=feed, fetch_list=var_names,
                scope=self._scope,
            )
            for n, v in zip(var_names, outs):
                a = np.asarray(v)
                amax = float(np.abs(a).max())
                if self._algo in ("hist", "KL"):
                    if amax > hist_max[n]:
                        if hist_max[n] > 0.0:
                            hists[n] = _rescale_hist(
                                hists[n], hist_max[n], amax, self._BINS
                            )
                        hist_max[n] = amax
                    h, _ = np.histogram(
                        np.abs(a).ravel(), bins=self._BINS,
                        range=(0.0, max(hist_max[n], 1e-30)),
                    )
                    hists[n] += h
                batch_max[n].append(amax)
                mins[n] = min(mins[n], float(a.min()))
                maxs[n] = max(maxs[n], float(a.max()))
        if n_batches == 0:
            raise ValueError(
                "PostTrainingQuantization.quantize: calibration_feeds is "
                "empty (exhausted generator or empty calibration set?)"
            )
        if self._algo == "abs_max":
            return {n: max(batch_max[n]) for n in var_names}
        if self._algo == "avg":
            return {n: float(np.mean(batch_max[n])) for n in var_names}
        if self._algo == "min_max":
            return {n: (mins[n], maxs[n]) for n in var_names}
        out = {}
        for n in var_names:
            hist, max_val = hists[n], hist_max[n]
            bin_width = max_val / self._BINS if max_val > 0 else 0.0
            if self._algo == "hist":
                if hist.sum() <= 0:
                    out[n] = 0.0
                    continue
                cdf = np.cumsum(hist) / hist.sum()
                idx = int(np.searchsorted(cdf, self._hist_percent))
                out[n] = (min(idx, self._BINS - 1) + 1) * bin_width
            else:
                out[n] = _kl_threshold(hist, bin_width)
        return out

    def apply(self, program, scales, quantizable_ops=QUANTIZABLE_OPS,
              activation_bits=8, weight_bits=8):
        """Bake calibrated activation scales into an INFERENCE program
        (reference save_quantized_model flow: QuantizationTransformPass in
        test mode + scale load + freeze): every quantizable op's float
        activation input with a calibrated scale routes through a
        FIXED-scale quant-dequant (the moving-average qdq op in is_test
        mode consumes InScale verbatim); weights quantize channel-wise by
        abs-max at apply time (they are constants at inference, so
        data-derived == calibrated). Returns the number of quant-dequant
        ops inserted; pair with io.save_inference_model to export."""
        return bake_ptq_scales(
            program, scales, quantizable_ops=quantizable_ops,
            activation_bits=activation_bits, weight_bits=weight_bits,
        )


def bake_ptq_scales(program, scales, quantizable_ops=QUANTIZABLE_OPS,
                    activation_bits=8, weight_bits=8):
    """Module-level scale baking (the body of
    :meth:`PostTrainingQuantization.apply`, shared with
    ``serving.freeze_program(int8_scales=...)`` — freezing a served graph
    must not require constructing a calibrator)."""
    def norm_scale(s):
        # min_max returns (min, max); scalar algos return a float
        if isinstance(s, (tuple, list)):
            s = max(abs(s[0]), abs(s[1]))
        return float(s)

    blk = program.global_block
    # ONE shared zero var feeds every qdq op's unused accum/state
    # (read-only in is_test mode)
    zero_n = unique_name.generate("ptq_zero")
    blk.create_var(name=zero_n, shape=(1,), dtype="float32")
    blk.append_op(
        "fill_constant", {}, {"Out": [zero_n]},
        {"shape": [1], "dtype": "float32", "value": 0.0}, index=0,
    )

    def insert(blk, i, op, n, v, is_weight):
        if not is_weight:
            sval = norm_scale(scales[n]) if n in scales else 0.0
            if sval <= 0.0:
                # uncalibrated or degenerate (all-zero activation):
                # a 0 InScale would divide to NaN at inference — skip
                return None, 0
        qname = unique_name.generate(n + ".ptq_quantized")
        blk.create_var(name=qname, shape=v.shape, dtype=v.dtype)
        oscale = unique_name.generate(n + ".ptq_scale_out")
        blk.create_var(name=oscale, shape=(1,), dtype="float32")
        if is_weight:
            blk.append_op(
                "fake_channel_wise_quantize_dequantize_abs_max",
                {"X": [n]},
                {"Out": [qname], "OutScale": [oscale]},
                {"bit_length": weight_bits,
                 "quant_axis": _weight_quant_axis(op.type, v)},
                index=i,
            )
            return qname, 1
        sn = unique_name.generate(n + ".ptq_in_scale")
        acc_out = unique_name.generate(n + ".ptq_acc_out")
        st_out = unique_name.generate(n + ".ptq_st_out")
        for aux_n in (sn, acc_out, st_out):
            blk.create_var(name=aux_n, shape=(1,), dtype="float32")
        blk.append_op(
            "fill_constant", {}, {"Out": [sn]},
            {"shape": [1], "dtype": "float32", "value": sval},
            index=i,
        )
        blk.append_op(
            "fake_quantize_dequantize_moving_average_abs_max",
            {"X": [n], "InScale": [sn], "InAccum": [zero_n],
             "InState": [zero_n]},
            {"Out": [qname], "OutScale": [oscale],
             "OutAccum": [acc_out], "OutState": [st_out]},
            {"bit_length": activation_bits, "is_test": True},
            index=i + 1,
        )
        return qname, 2

    return _rewrite_quantizable_inputs(program, quantizable_ops,
                                       insert)
