"""Quantization-aware training + post-training quantization (reference
python/paddle/fluid/contrib/slim/quantization/: QuantizationTransformPass
inserts fake_quantize/dequantize around conv/mul inputs+weights on the IR
graph; PostTrainingQuantization collects activation scales from calibration
batches).

TPU-native: the "pass" is a Program rewrite (like AMP's rewrite_program) —
each quantizable op's inputs are routed through
fake_quantize_dequantize_abs_max ops (straight-through gradients), so QAT
runs inside the same jitted step. INT8 *execution* is out of scope for TPU
v5e's bf16 MXU; the deliverable is quantization-error-aware training and
exported scales, matching what the reference's QAT produces before its
int8 kernel swap.
"""

from __future__ import annotations

from ...framework import unique_name

QUANTIZABLE_OPS = ("mul", "matmul", "conv2d", "depthwise_conv2d")
_WEIGHT_SLOTS = {"Y", "Filter"}


class QuantizationTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_ops=QUANTIZABLE_OPS):
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.quantizable_ops = tuple(quantizable_ops)

    def transpile(self, program):
        """Insert fake quant-dequant before every quantizable op's float
        inputs. Weights get channel-wise scales (reference
        QuantizationTransformPass behavior); activations per-tensor."""
        blk = program.global_block
        quantized = {}  # original name -> quantized name (reuse per block)
        i = 0
        n_inserted = 0
        while i < len(blk.ops):
            op = blk.ops[i]
            if op.type not in self.quantizable_ops:
                i += 1
                continue
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    v = blk._find_var_recursive(n)
                    if v is None or v.dtype not in ("float32", "bfloat16"):
                        new_names.append(n)
                        continue
                    if n in quantized:
                        new_names.append(quantized[n])
                        continue
                    is_weight = slot in _WEIGHT_SLOTS or getattr(
                        v, "persistable", False
                    )
                    qname = unique_name.generate(n + ".quantized")
                    blk.create_var(
                        name=qname, shape=v.shape, dtype=v.dtype,
                    )
                    sname = unique_name.generate(n + ".quant_scale")
                    blk.create_var(name=sname, shape=(1,), dtype="float32")
                    if is_weight:
                        # output-channel axis: 0 for conv filters
                        # [out,in,kh,kw], last for matmul weights [in,out]
                        # (reference QuantizationTransformPass convention)
                        axis = 0 if "conv" in op.type else len(
                            v.shape or (1,)
                        ) - 1
                        blk.append_op(
                            "fake_channel_wise_quantize_dequantize_abs_max",
                            {"X": [n]},
                            {"Out": [qname], "OutScale": [sname]},
                            {"bit_length": self.weight_bits,
                             "quant_axis": axis},
                            index=i,
                        )
                    else:
                        blk.append_op(
                            "fake_quantize_dequantize_abs_max",
                            {"X": [n]},
                            {"Out": [qname], "OutScale": [sname]},
                            {"bit_length": self.activation_bits},
                            index=i,
                        )
                    i += 1
                    n_inserted += 1
                    quantized[n] = qname
                    new_names.append(qname)
                op.inputs[slot] = new_names
            i += 1
        program._bump()
        return n_inserted


def quant_aware(program, weight_bits=8, activation_bits=8):
    """Convenience: rewrite `program` for QAT; call BEFORE
    optimizer.minimize so the fake-quant ops get differentiated."""
    t = QuantizationTranspiler(weight_bits, activation_bits)
    t.transpile(program)
    return program


class PostTrainingQuantization:
    """Collect abs-max activation scales over calibration batches
    (reference post_training_quantization.py) and return {var: scale}."""

    def __init__(self, executor, program, feed_names, fetch_vars,
                 scope=None):
        self._exe = executor
        self._program = program
        self._feed_names = feed_names
        self._fetch = fetch_vars
        self._scope = scope

    def quantize(self, calibration_feeds, var_names):
        import numpy as np

        var_names = list(var_names)  # a generator must survive re-iteration
        scales = {n: 0.0 for n in var_names}
        for feed in calibration_feeds:
            outs = self._exe.run(
                self._program, feed=feed, fetch_list=var_names,
                scope=self._scope,
            )
            for n, v in zip(var_names, outs):
                scales[n] = max(scales[n], float(np.abs(np.asarray(v)).max()))
        return scales
