"""Neural architecture search (reference python/paddle/fluid/contrib/slim/:
searcher/controller.py SAController, nas/search_space.py SearchSpace,
nas/controller_server.py + nas/search_agent.py socket protocol,
nas/light_nas_strategy.py orchestration).

TPU-native framing: a candidate architecture is just a token vector that a
SearchSpace turns into a fresh Program; "evaluate" is a handful of jitted
train/eval steps on the chip (whole-block compile makes small candidate nets
cheap to stand up), and the latency constraint is scored with the static
FLOPs estimator (analysis.flops) instead of wall-clock on a shared chip.
The controller is plain simulated annealing over tokens; a tiny TCP
server/agent pair lets multiple hosts search against one annealing chain
(the reference's ControllerServer pattern — its "d;a;r" wire format is
replaced with a JSON line protocol)."""

from __future__ import annotations

import json
import math
import socket
import socketserver
import threading

import numpy as np


class SearchSpace:
    """Subclass contract (reference nas/search_space.py): tokens <-> nets."""

    def init_tokens(self):
        raise NotImplementedError

    def range_table(self):
        """list<int>: tokens[i] ranges over [0, range_table[i])."""
        raise NotImplementedError

    def create_net(self, tokens):
        """tokens -> (startup_program, train_program, eval_program,
        train_fetch, eval_fetch)."""
        raise NotImplementedError

    def get_model_latency(self, program):
        """Proxy latency score; default = static FLOPs (analysis.flops)."""
        from .analysis import flops

        return float(flops(program))


class EvolutionaryController:
    def update(self, tokens, reward):
        raise NotImplementedError

    def reset(self, range_table, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing over token vectors (reference
    searcher/controller.py:59): mutate one position, accept worse solutions
    with prob exp(-(best - reward) / T), geometric cooling."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=None):
        self._range_table = list(range_table or [])
        self._reduce_rate = float(reduce_rate)
        self._init_temperature = float(init_temperature)
        self._max_iter_number = int(max_iter_number)
        self._reward = -np.inf
        self._tokens = None
        self._constrain_func = None
        self._iter = 0
        self._rng = np.random.RandomState(seed)
        self.best_tokens = None
        self.best_reward = -np.inf

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._tokens = list(init_tokens)
        self._constrain_func = constrain_func
        self._reward = -np.inf
        self._iter = 0

    def update(self, tokens, reward):
        """Accept/reject `tokens` given its measured reward."""
        self._iter += 1
        temperature = self._init_temperature * (
            self._reduce_rate ** self._iter
        )
        if (reward > self._reward) or (
            self._rng.uniform()
            < math.exp(min((reward - self._reward) / max(temperature, 1e-9),
                           0.0))
        ):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self.best_reward:
            self.best_reward = reward
            self.best_tokens = list(tokens)

    def next_tokens(self):
        for _ in range(self._max_iter_number):
            tokens = list(self._tokens)
            i = int(self._rng.randint(len(tokens)))
            tokens[i] = int(self._rng.randint(self._range_table[i]))
            if self._constrain_func is None or self._constrain_func(tokens):
                return tokens
        return list(self._tokens)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        line = self.rfile.readline()
        if not line:
            return
        msg = json.loads(line)
        server = self.server.nas_server
        with server._lock:
            if msg["cmd"] == "next_tokens":
                out = {"tokens": server.controller.next_tokens()}
            elif msg["cmd"] == "update":
                server.controller.update(msg["tokens"], float(msg["reward"]))
                out = {"ok": True,
                       "best_reward": server.controller.best_reward}
            elif msg["cmd"] == "best":
                out = {"tokens": server.controller.best_tokens,
                       "reward": server.controller.best_reward}
            else:
                out = {"error": f"unknown cmd {msg['cmd']!r}"}
        self.wfile.write((json.dumps(out) + "\n").encode())


class ControllerServer:
    """Serve one annealing chain to remote SearchAgents over TCP (reference
    nas/controller_server.py, JSON-lines instead of its ad-hoc format)."""

    def __init__(self, controller, address=("127.0.0.1", 0)):
        self.controller = controller
        self._lock = threading.Lock()
        self._srv = socketserver.ThreadingTCPServer(
            address, _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.nas_server = self
        self._thread = None

    @property
    def address(self):
        return self._srv.server_address

    def start(self):
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


class SearchAgent:
    """Client side (reference nas/search_agent.py)."""

    def __init__(self, server_address):
        self.server_address = tuple(server_address)

    def _call(self, payload):
        with socket.create_connection(self.server_address, timeout=30) as s:
            s.sendall((json.dumps(payload) + "\n").encode())
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(4096)
                if not chunk:
                    break
                buf += chunk
        return json.loads(buf)

    def next_tokens(self):
        return self._call({"cmd": "next_tokens"})["tokens"]

    def update(self, tokens, reward):
        return self._call(
            {"cmd": "update", "tokens": list(tokens), "reward": float(reward)}
        )

    def best(self):
        return self._call({"cmd": "best"})


class LightNAS:
    """Search loop (reference nas/light_nas_strategy.py): draw tokens,
    build + briefly train the candidate, reward = metric - latency penalty,
    anneal. `eval_candidate(tokens) -> (metric, latency)` is supplied by the
    caller (it owns programs/executors/data); when `agent` is given the
    controller lives in a remote ControllerServer."""

    def __init__(self, search_space, controller=None, agent=None,
                 max_latency=None, latency_weight=0.0):
        self.space = search_space
        self.agent = agent
        self.controller = controller
        if controller is None and agent is None:
            self.controller = SAController(search_space.range_table())
        if self.controller is not None:
            self.controller.reset(
                search_space.range_table(), search_space.init_tokens()
            )
        self.max_latency = max_latency
        self.latency_weight = float(latency_weight)

    def _next(self):
        return (
            self.agent.next_tokens()
            if self.agent is not None
            else self.controller.next_tokens()
        )

    def _update(self, tokens, reward):
        if self.agent is not None:
            self.agent.update(tokens, reward)
        else:
            self.controller.update(tokens, reward)

    def search(self, eval_candidate, steps=10):
        for _ in range(steps):
            tokens = self._next()
            metric, latency = eval_candidate(tokens)
            reward = float(metric)
            if self.max_latency is not None and latency > self.max_latency:
                reward -= self.latency_weight * (
                    latency / self.max_latency - 1.0
                )
            self._update(tokens, reward)
        if self.agent is not None:
            best = self.agent.best()
            return best["tokens"], best["reward"]
        return self.controller.best_tokens, self.controller.best_reward
