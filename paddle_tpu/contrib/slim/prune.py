"""Structured channel pruning (reference python/paddle/fluid/contrib/slim/
prune/: StructurePruner l1-norm group selection in pruner.py,
SensitivePruneStrategy / UniformPruneStrategy graph surgery in
prune_strategy.py, sensitivity-driven ratio selection in
auto_prune_strategy.py).

TPU-native: pruning is a *Program rewrite + Scope array slice*. There is no
kernel work at all — once the weight arrays shrink and the dependent ops'
params are sliced to match, the next Executor.run re-traces and XLA compiles
the smaller model (the reference instead had to rebuild its IR graph and
re-bind kernels). The dependency walk matches the reference's: pruning conv
output channels propagates through channel-preserving ops (activations,
batch_norm params, pooling, dropout, per-channel bias adds) until the next
channel-consuming conv/fc, whose input-channel axis is sliced.
"""

from __future__ import annotations

import numpy as np

_ACT_OPS = {
    "relu", "relu6", "sigmoid", "tanh", "gelu", "leaky_relu", "swish",
    "hard_swish", "elu", "softplus", "dropout", "pool2d",
}


class Pruner:
    """Base class (reference pruner.py:22)."""

    def prune(self, param):
        pass


class StructurePruner(Pruner):
    """Group (channel) pruner: picks the lowest-norm slices of a weight
    along an axis (reference pruner.py:34)."""

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def _criterion(self, name):
        return self.criterions.get(name, self.criterions.get("*", "l1_norm"))

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get("*", 0))
        param = np.asarray(param)
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        crit = self._criterion(name)
        if crit == "l1_norm":
            scores = np.sum(np.abs(param), axis=reduce_dims)
        elif crit == "l2_norm":
            scores = np.sqrt(np.sum(param * param, axis=reduce_dims))
        else:
            raise ValueError(f"unknown criterion {crit!r}")
        return np.sort(np.argsort(scores)[:prune_num])

    def prune_tensor(self, tensor, pruned_idx, pruned_axis, lazy=False):
        tensor = np.asarray(tensor)
        mask = np.zeros(tensor.shape[pruned_axis], dtype=bool)
        mask[np.asarray(pruned_idx, dtype=np.int64)] = True
        if lazy:
            out = tensor.copy()
            sl = [slice(None)] * tensor.ndim
            sl[pruned_axis] = mask
            out[tuple(sl)] = 0
            return out
        sl = [slice(None)] * tensor.ndim
        sl[pruned_axis] = ~mask
        return tensor[tuple(sl)]


def _consumers(block, var_name):
    out = []
    for op in block.ops:
        for slot, names in op.inputs.items():
            if var_name in names:
                out.append((op, slot))
    return out


# optimizer ops whose state tensors mirror the param's shape and must be
# sliced with it (reference: the slim strategies retrain from a fresh
# optimizer; here the train program keeps working in place)
_OPT_STATE_SLOTS = {
    "momentum": ("Velocity",),
    "lars_momentum": ("Velocity",),
    "adam": ("Moment1", "Moment2"),
    "lamb": ("Moment1", "Moment2"),
    "adamax": ("Moment", "InfNorm"),
    "adagrad": ("Moment",),
    "decayed_adagrad": ("Moment",),
    "adadelta": ("AvgSquaredGrad", "AvgSquaredUpdate"),
    "rmsprop": ("Moment", "MeanSquare", "MeanGrad"),
    "ftrl": ("SquaredAccumulator", "LinearAccumulator"),
}


def _slice_param(block, scope, name, keep, axis):
    arr = scope.find_var(name)
    if arr is None:
        raise ValueError(f"parameter {name!r} not initialized in scope")
    arr = np.take(np.asarray(arr), keep, axis=axis)
    scope.set_var(name, arr)
    v = block._find_var_recursive(name)
    if v is not None and v.shape is not None:
        shape = list(v.shape)
        shape[axis] = len(keep)
        v.shape = tuple(shape)
    # keep optimizer state aligned with the pruned param
    for op in block.ops:
        slots = _OPT_STATE_SLOTS.get(op.type)
        if not slots or (op.inputs.get("Param") or [None])[0] != name:
            continue
        for slot in slots:
            for st in op.inputs.get(slot) or []:
                st_arr = scope.find_var(st)
                if st_arr is not None:
                    scope.set_var(
                        st, np.take(np.asarray(st_arr), keep, axis=axis)
                    )
                _set_channel_dim(block, st, len(keep), axis=axis)


def _set_channel_dim(block, var_name, n, axis=1):
    v = block._find_var_recursive(var_name)
    if v is not None and v.shape is not None and len(v.shape) > axis:
        shape = list(v.shape)
        shape[axis] = n
        v.shape = tuple(shape)


def prune_conv_output(program, scope, filter_name, keep_idx):
    """Keep only `keep_idx` output channels of the conv2d owning
    `filter_name`, propagating the shape change through consumers.

    Supported downstream ops: per-channel bias add, batch_norm (all four
    channel params sliced), activations/dropout/pool2d, depthwise_conv2d,
    the next conv2d (input-channel slice), and mul/fc (row-group slice).
    Residual adds joining two pruned branches are rejected — prune both
    branches identically via uniform ratios instead.
    """
    block = program.global_block
    keep = np.asarray(sorted(int(i) for i in keep_idx), dtype=np.int64)
    conv = None
    for op in block.ops:
        if op.type in ("conv2d", "conv2d_transpose") and filter_name in (
            op.inputs.get("Filter") or []
        ):
            conv = op
            break
    if conv is None:
        raise ValueError(f"no conv2d consumes Filter {filter_name!r}")
    if conv.attr("groups", 1) not in (1, None):
        raise ValueError("grouped conv pruning is not supported")
    oc_axis = 0 if conv.type == "conv2d" else 1
    old_oc = block.var(filter_name).shape[oc_axis]
    _slice_param(block, scope, filter_name, keep, oc_axis)
    out_var = conv.outputs["Output"][0]
    _walk_channel_consumers(block, scope, out_var, keep, old_oc)
    program._bump()


def _walk_channel_consumers(block, scope, var_name, keep, old_c):
    _set_channel_dim(block, var_name, len(keep))
    for op, slot in _consumers(block, var_name):
        if op.type == "__vjp__" or op.type.endswith("_grad"):
            # backward ops replay the (now pruned) forward emitters and
            # re-derive their shapes from the live arrays — nothing to do
            continue
        if op.type == "elementwise_add" and slot == "X":
            other = op.inputs["Y"][0]
            ov = block._find_var_recursive(other)
            if ov is not None and ov.shape is not None and len(ov.shape) == 1:
                _slice_param(block, scope, other, keep, 0)  # per-channel bias
                _walk_channel_consumers(
                    block, scope, op.outputs["Out"][0], keep, old_c
                )
            else:
                raise ValueError(
                    "pruning across a residual elementwise_add is not "
                    "supported; prune both branches with equal ratios"
                )
        elif op.type in _ACT_OPS:
            _walk_channel_consumers(
                block, scope, op.outputs["Out"][0], keep, old_c
            )
        elif op.type == "batch_norm":
            for s in ("Scale", "Bias", "Mean", "Variance"):
                _slice_param(block, scope, op.inputs[s][0], keep, 0)
            for s in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
                names = op.outputs.get(s) or []
                if names:
                    _set_channel_dim(block, names[0], len(keep), axis=0)
            _walk_channel_consumers(
                block, scope, op.outputs["Y"][0], keep, old_c
            )
        elif op.type == "depthwise_conv2d":
            _slice_param(block, scope, op.inputs["Filter"][0], keep, 0)
            if op.attr("groups") is not None:
                op.attrs["groups"] = len(keep)
            _walk_channel_consumers(
                block, scope, op.outputs["Output"][0], keep, old_c
            )
        elif op.type == "conv2d":
            if op.attr("groups", 1) not in (1, None):
                raise ValueError(
                    "pruning into a grouped conv2d consumer is not "
                    "supported (its filter's input-channel axis covers "
                    "only one group)"
                )
            _slice_param(block, scope, op.inputs["Filter"][0], keep, 1)
        elif op.type == "mul":
            w_name = op.inputs["Y"][0]
            w = block._find_var_recursive(w_name)
            rows = w.shape[0]
            if rows % old_c:
                raise ValueError(
                    f"fc weight rows {rows} not divisible by channel count "
                    f"{old_c}; cannot group-slice {w_name!r}"
                )
            per = rows // old_c  # spatial positions per channel (C*H*W rows)
            row_idx = (keep[:, None] * per + np.arange(per)[None, :]).ravel()
            _slice_param(block, scope, w_name, row_idx, 0)
        elif op.type == "reshape2":
            raise ValueError(
                "pruning through reshape2 with baked shapes is not "
                "supported; use fc(num_flatten_dims=...) directly"
            )
        else:
            raise ValueError(
                f"op {op.type!r} consuming pruned channels is not supported"
            )


def prune_program(program, scope, ratios, criterion="l1_norm", lazy=False):
    """Prune conv output channels by per-filter ratios
    ({filter_param_name: ratio}). `lazy` zeroes channels instead of removing
    them (reference pruner.py prune_tensor lazy mode) — shapes stay intact,
    useful for trial evaluation without re-tracing."""
    pruner = StructurePruner(criterions={"*": criterion})
    block = program.global_block
    for name, ratio in ratios.items():
        w = scope.find_var(name)
        if w is None:
            raise ValueError(f"parameter {name!r} not in scope")
        idx = pruner.cal_pruned_idx(name, w, float(ratio), axis=0)
        if lazy:
            scope.set_var(name, pruner.prune_tensor(w, idx, 0, lazy=True))
            continue
        oc = np.asarray(w).shape[0]
        keep = [i for i in range(oc) if i not in set(idx.tolist())]
        prune_conv_output(program, scope, name, keep)
    if lazy:
        program._bump()


def sensitivity(program, scope, eval_func, param_names, ratios=(0.1, 0.3, 0.5)):
    """Per-parameter accuracy sensitivity (reference
    auto_prune_strategy.py / prune_strategy.py SensitivePruneStrategy):
    lazily zero each param's lowest-norm channels at each ratio and measure
    the metric drop. Returns {param: {ratio: loss_fraction}}."""
    baseline = float(eval_func(program, scope))
    pruner = StructurePruner()
    out = {}
    for name in param_names:
        orig = np.asarray(scope.find_var(name)).copy()
        out[name] = {}
        for r in ratios:
            idx = pruner.cal_pruned_idx(name, orig, float(r), axis=0)
            scope.set_var(name, pruner.prune_tensor(orig, idx, 0, lazy=True))
            program._bump()  # zeroed weights: same shapes, new executable
            metric = float(eval_func(program, scope))
            out[name][float(r)] = (
                (baseline - metric) / abs(baseline) if baseline else 0.0
            )
        scope.set_var(name, orig)
        program._bump()
    return out


def get_ratios_by_sensitivity(sensitivities, target_loss=0.05):
    """Pick, per parameter, the largest trial ratio whose measured metric
    loss stays under `target_loss` (greedy per-param rule, the shape of the
    reference's auto strategy). Returns {param: ratio}."""
    picked = {}
    for name, table in sensitivities.items():
        best = 0.0
        for r, loss in sorted(table.items()):
            if loss <= target_loss and r > best:
                best = r
        if best > 0.0:
            picked[name] = best
    return picked


class UniformPruneStrategy:
    """Same ratio on every listed conv filter (reference
    prune_strategy.py UniformPruneStrategy)."""

    def __init__(self, pruner=None, target_ratio=0.5, pruned_params=None):
        self.pruner = pruner or StructurePruner()
        self.target_ratio = float(target_ratio)
        self.pruned_params = list(pruned_params or [])

    def apply(self, program, scope):
        prune_program(
            program,
            scope,
            {n: self.target_ratio for n in self.pruned_params},
            criterion=self.pruner._criterion("*"),
        )


class SensitivePruneStrategy:
    """Sensitivity-scan then prune (reference prune_strategy.py:
    SensitivePruneStrategy), compressed to the TPU design: one scan with
    lazy zeroing, greedy ratio pick, one structural prune."""

    def __init__(self, pruner=None, eval_func=None, pruned_params=None,
                 sensitivity_ratios=(0.1, 0.3, 0.5), target_loss=0.05):
        self.pruner = pruner or StructurePruner()
        self.eval_func = eval_func
        self.pruned_params = list(pruned_params or [])
        self.sensitivity_ratios = tuple(sensitivity_ratios)
        self.target_loss = float(target_loss)
        self.sensitivities = None

    def apply(self, program, scope):
        self.sensitivities = sensitivity(
            program, scope, self.eval_func, self.pruned_params,
            self.sensitivity_ratios,
        )
        ratios = get_ratios_by_sensitivity(
            self.sensitivities, self.target_loss
        )
        if ratios:
            prune_program(program, scope, ratios,
                          criterion=self.pruner._criterion("*"))
        return ratios
