"""Knowledge distillation (reference contrib/slim/distillation/
distiller.py + graph merging in slim's GraphWrapper.merge: teacher ops are
copied into the student graph under a name prefix, then soft-label / FSP /
L2 distill losses connect matched layers).

TPU-native: `merge` is a Program transform — teacher ops+vars re-emitted
into the student program with a "teacher_" prefix and stop_gradient
teacher parameters (the whole merged graph still compiles to ONE XLA
computation, so teacher and student run fused in the same step — the
reference ran two executors). Loss builders mirror the reference's
DistillationStrategy losses.
"""

from __future__ import annotations

from ... import layers
from ...framework.program import default_startup_program

TEACHER_PREFIX = "teacher_"


def merge(
    teacher_program,
    student_program,
    data_name_map,
    scope=None,
    name_prefix=TEACHER_PREFIX,
    teacher_scope=None,
):
    """Copy the teacher's (inference) program into the student program.

    data_name_map: {teacher_feed_name: student_var_name} — teacher feeds
    rebind to student vars; every other teacher var is renamed with
    `name_prefix`. Teacher parameters must already be loaded in
    `teacher_scope` (or the global scope); they are re-registered under
    the prefixed name as non-trainable. Returns the student program."""
    from ...framework.scope import global_scope

    scope = scope or global_scope()
    teacher_scope = teacher_scope or scope
    sblk = student_program.global_block

    def rename(n):
        return data_name_map.get(n, name_prefix + n)

    for tvar in teacher_program.list_vars():
        if tvar.name in data_name_map:
            continue
        new_name = rename(tvar.name)
        if sblk.has_var(new_name):
            raise ValueError(
                f"merge: student program already has a var {new_name!r} "
                "(merging two teachers? pass a distinct name_prefix)"
            )
        if tvar.persistable:
            p = sblk.create_parameter(
                new_name, tvar.shape, tvar.dtype, trainable=False
            )
            p.stop_gradient = True
            val = teacher_scope.find_var(tvar.name)
            if val is None:
                raise ValueError(
                    f"teacher parameter {tvar.name!r} not found in scope; "
                    "load the teacher model first"
                )
            scope.set_var(new_name, val)
        else:
            sblk.create_var(
                name=new_name, shape=tvar.shape, dtype=tvar.dtype,
                stop_gradient=True,
            )
    for top in teacher_program.global_block.ops:
        sblk.append_op(
            top.type,
            {s: [rename(n) if n else n for n in ns]
             for s, ns in top.inputs.items()},
            {s: [rename(n) if n else n for n in ns]
             for s, ns in top.outputs.items()},
            {k: v for k, v in top.attrs.items() if k != "__uid__"},
        )
    student_program._bump()
    return student_program


def soft_label_loss(
    teacher_var_name, student_var_name, program=None,
    teacher_temperature=1.0, student_temperature=1.0,
):
    """Cross-entropy between softened teacher and student logits
    (reference distiller.py soft_label_loss)."""
    from ...framework.program import default_main_program

    program = program or default_main_program()
    blk = program.global_block
    t = blk.var(teacher_var_name)
    s = blk.var(student_var_name)
    t_soft = layers.softmax(layers.scale(t, scale=1.0 / teacher_temperature))
    s_log = layers.log_softmax(
        layers.scale(s, scale=1.0 / student_temperature)
    )
    ce = layers.reduce_sum(
        layers.elementwise_mul(t_soft, s_log), -1, keep_dim=False
    )
    return layers.scale(layers.reduce_mean(ce), scale=-1.0)


def l2_loss(teacher_var_name, student_var_name, program=None):
    from ...framework.program import default_main_program

    program = program or default_main_program()
    blk = program.global_block
    diff = layers.elementwise_sub(
        blk.var(student_var_name), blk.var(teacher_var_name)
    )
    return layers.reduce_mean(layers.square(diff))


def fsp_loss(
    teacher_var1_name, teacher_var2_name, student_var1_name,
    student_var2_name, program=None,
):
    """Flow-of-solution-procedure loss (reference distiller.py fsp_loss):
    L2 between teacher and student Gram matrices of two feature maps."""
    from ...framework.program import default_main_program

    program = program or default_main_program()
    blk = program.global_block

    def gram(a_name, b_name):
        a, b = blk.var(a_name), blk.var(b_name)
        n, ca = a.shape[0], a.shape[1]
        cb = b.shape[1]
        hw = int(a.shape[2]) * int(a.shape[3])
        fa = layers.reshape(a, [n, ca, hw])
        fb = layers.transpose(layers.reshape(b, [n, cb, hw]), [0, 2, 1])
        return layers.scale(layers.matmul(fa, fb), scale=1.0 / hw)

    diff = layers.elementwise_sub(
        gram(student_var1_name, student_var2_name),
        gram(teacher_var1_name, teacher_var2_name),
    )
    return layers.reduce_mean(layers.square(diff))
