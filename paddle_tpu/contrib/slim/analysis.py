"""Model analysis: FLOPs estimation over a Program.

Reference parity: PaddleSlim's flops() util (the slim strategies in
contrib/slim/prune/prune_strategy.py steer on a FLOPs budget). Counts
multiply-accumulates of the MXU-bound ops from recorded var shapes,
including static batch dims; -1 batch dims count as 1, so fully
batch-agnostic programs report per-sample FLOPs.
"""

from __future__ import annotations

import numpy as np


def _d(x):
    return 1 if x in (-1, None) else int(x)


def flops(program, detail=False):
    """Per-sample forward multiply-add FLOPs (2*MACs) of conv2d / mul /
    matmul ops in the program. detail=True also returns {op_idx: flops}."""
    total = 0
    per_op = {}
    block = program.global_block
    for i, op in enumerate(block.ops):
        f = 0
        if op.type in ("conv2d", "depthwise_conv2d"):
            w = block._find_var_recursive(op.inputs["Filter"][0])
            out = block._find_var_recursive(op.outputs["Output"][0])
            if w is None or out is None or not out.shape:
                continue
            oc, ic_g, kh, kw = (int(s) for s in w.shape)
            batch, oh, ow = _d(out.shape[0]), _d(out.shape[-2]), _d(out.shape[-1])
            f = 2 * batch * oc * ic_g * kh * kw * oh * ow
        elif op.type == "mul":
            w = block._find_var_recursive(op.inputs["Y"][0])
            x = block._find_var_recursive(op.inputs["X"][0])
            if w is None or x is None or not w.shape:
                continue
            k = int(np.prod([_d(s) for s in w.shape[:-1]]))
            n = _d(w.shape[-1])
            ncol = op.attr("x_num_col_dims", 1)
            m = int(np.prod([_d(s) for s in (x.shape or ())[:ncol]]))
            f = 2 * m * k * n
        elif op.type == "matmul":
            a = block._find_var_recursive(op.inputs["X"][0])
            b = block._find_var_recursive(op.inputs["Y"][0])
            if a is None or b is None or not a.shape or not b.shape:
                continue
            ash = [_d(s) for s in a.shape]
            bsh = [_d(s) for s in b.shape]
            m = ash[-1] if op.attr("transpose_x", False) else ash[-2]
            k = ash[-2] if op.attr("transpose_x", False) else ash[-1]
            n = bsh[-2] if op.attr("transpose_y", False) else bsh[-1]
            batch = int(np.prod(ash[:-2])) if len(ash) > 2 else 1
            f = 2 * batch * m * k * n
        if f:
            total += f
            per_op[i] = f
    return (total, per_op) if detail else total
