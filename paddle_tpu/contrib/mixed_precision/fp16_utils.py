"""Program rewriting for AMP (reference: fp16_utils.py rewrite_program).

Walks the forward ops and inserts `cast` ops so white-list ops consume the
low-precision dtype and black-list ops consume fp32. Parameters stay fp32
masters in the Scope; the per-use casts are fused into the consuming matmul
by XLA (on TPU a bf16 cast is free on the MXU path). Must run BEFORE
append_backward so the casts get differentiated (grad of cast casts back).
"""

from __future__ import annotations

from ...core.dtypes import is_float
from ...framework import unique_name
from .fp16_lists import AutoMixedPrecisionLists


def _insert_cast(block, op_idx, op, name, dest_dtype, force=False):
    """Insert cast(name)->new var before op_idx; rewire op's input.

    force=True casts even when the var's *declared* dtype already matches:
    after white-op rewriting, declared dtypes can lag the runtime dtype, so
    black-list fp32 casts are emitted unconditionally (a f32->f32 cast is
    free in XLA)."""
    src = block._find_var_recursive(name)
    if src is None or not is_float(src.dtype):
        return 0
    if src.dtype == dest_dtype and not force:
        return 0
    cast_name = unique_name.generate(f"{name}.cast_{dest_dtype}")
    block.create_var(
        name=cast_name, shape=src.shape, dtype=dest_dtype,
        stop_gradient=src.stop_gradient,
    )
    block.append_op(
        "cast",
        {"X": [name]},
        {"Out": [cast_name]},
        {"in_dtype": src.dtype, "out_dtype": dest_dtype},
        index=op_idx,
    )
    for slot, names in op.inputs.items():
        op.inputs[slot] = [cast_name if n == name else n for n in names]
    return 1


# gray ops that must keep their inputs untouched: control flow re-enters
# sub-blocks (casts would break capture analysis), cast has an explicit
# out_dtype contract
_GRAY_SKIP = {"while", "conditional_block", "cast", "print", "py_func",
              "assign", "share_data", "pipeline_block", "pipeline_uniform",
              "pipeline_gate_loss", "recompute_segment"}

# input slots that carry TARGETS, not activations: never downcast them.
# A soft-label fp32 Label is data — it does not ride the activation
# stream the bandwidth rule targets, and bf16 quantizes it for no win.
_LABEL_SLOTS = {"Label", "Target", "GTBox", "GTLabel", "GTScore"}

# per-(op, slot) gray-downcast exemptions: LN affine params stay fp32 like
# black-listed layer_norm's (the fused kernel computes in fp32 internally,
# and the bf16 cast of these [H] vectors is what trips the XLA partitioner
# crash under gspmd-Auto sharding — see pipeline_uniform composition)
_GRAY_SLOT_KEEP = {
    ("fused_dropout_add_ln", "Scale"),
    ("fused_dropout_add_ln", "LnBias"),
}


def rewrite_program(program, amp_lists=None, dest_dtype="bfloat16"):
    """Insert casts per black/white lists into the (forward-only) program.

    Gray ops (neither list) FOLLOW their inputs, as in the reference
    rewrite (fp16_lists.py gray set + fp16_utils.py process rule): once any
    float input is low-precision, the remaining fp32 float inputs (fp32
    master params, typically a bias) are cast down too. Without this,
    jnp's type promotion silently lifts every bias-add back to fp32 — the
    activation stream between matmuls then crosses custom-call fusion
    barriers at twice the bytes (profiled on BERT-base, BASELINE.md r4).

    Pipeline composition (reference meta-optimizer stacking,
    optimizer.py:3556 + incubate/fleet/collective/__init__.py:384): when
    the forward has already been sliced into pipeline stage sub-blocks,
    the rewrite recurses into each stage block, then re-records the
    pipeline boundary dtype from the rewritten boundary vars — stage
    hand-offs ride ICI in bf16 (half the ppermute bytes), matching the
    declared dtypes the next stage's cast checks rely on."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    _rewrite_block(program, program.global_block, amp_lists, dest_dtype)
    for op in program.global_block.ops:
        if op.type == "pipeline_block":
            for bi in op.attr("stage_blocks"):
                _rewrite_block(program, program.blocks[bi], amp_lists,
                               dest_dtype)
            # boundary activations may now be low-precision: sync the
            # recorded dtype so the emitter's inter-stage cast matches
            # declared dtypes
            b_names = op.attr("boundary_names")
            dts = set()
            for n in b_names:
                v = program.global_block._find_var_recursive(n)
                if v is None:
                    for bi in op.attr("stage_blocks"):
                        v = program.blocks[bi]._find_var_recursive(n)
                        if v is not None:
                            break
                if v is not None:
                    dts.add(str(v.dtype))
            if len(dts) == 1:
                op.attrs["boundary_dtype"] = dts.pop()
        elif op.type == "pipeline_uniform":
            bi = op.attr("stage_block")
            blk = program.blocks[bi]
            _rewrite_block(program, blk, amp_lists, dest_dtype)
            # boundary_dtype deliberately NOT lowered: a low-precision
            # carry through ppermute/scan/psum composed with gspmd-Auto
            # (mp) sharded weights trips an XLA partitioner check failure
            # ("Invalid binary instruction opcode copy", reproduced
            # minimally in jax 0.9 — bf16 carry crashes, f32 passes).
            # Stage compute still runs bf16 (casts above); only the
            # inter-stage hand-off pays f32 bytes.
    program._bump()
    return program


def _rewrite_block(program, block, amp_lists, dest_dtype):
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type in amp_lists.white_list:
            target, force = dest_dtype, False
        elif op.type in amp_lists.black_list:
            target, force = "float32", True
        elif op.type not in _GRAY_SKIP:
            dts = set()
            for n in op.input_names():
                v = block._find_var_recursive(n)
                if v is not None and is_float(v.dtype):
                    dts.add(str(v.dtype))
            if dest_dtype not in dts:
                i += 1
                continue
            target, force = dest_dtype, False
        else:
            i += 1
            continue
        inserted = 0
        skip = set()
        if target == dest_dtype:
            for slot in op.inputs:
                if slot in _LABEL_SLOTS or (op.type, slot) in _GRAY_SLOT_KEEP:
                    skip.update(op.inputs[slot])
        for name in list(dict.fromkeys(op.input_names())):
            if name in skip:
                continue
            inserted += _insert_cast(block, i, op, name, target, force)
        if target == dest_dtype:
            # declared output dtypes follow the compute dtype so later
            # white-op cast checks see the truth. Replay the emitter's
            # abstract eval instead of blindly stamping dest_dtype:
            # fp32-accumulating emitters (softmax_with_cross_entropy
            # reduces in fp32 from bf16 logits) keep fp32 outputs, and
            # stamping them bf16 desyncs declaration from emitter — the
            # drift the static verifier (paddle_tpu/analysis) flags.
            specs = None
            try:
                from ...framework.registry import infer_shapes

                specs = infer_shapes(op.type, block, op.inputs, op.attrs)
            except Exception:
                pass  # fall back to the compute dtype
            for slot, names in op.outputs.items():
                slot_specs = (specs or {}).get(slot, [])
                for j, n in enumerate(names):
                    v = block._find_var_recursive(n)
                    if v is None or not is_float(v.dtype):
                        continue
                    inferred = (
                        slot_specs[j][1] if j < len(slot_specs) else None
                    )
                    v.dtype = (
                        inferred
                        if inferred is not None and is_float(inferred)
                        else dest_dtype
                    )
        i += 1 + inserted
    return block
