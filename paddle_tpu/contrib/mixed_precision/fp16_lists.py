"""AMP op lists (reference: contrib/mixed_precision/fp16_lists.py:28-39).

white: compute in the low-precision dtype (MXU-bound ops — matmuls/convs).
black: always compute in fp32 (reductions/losses/normalizations, where
low-precision accumulation visibly hurts).
gray (everything else): follow their inputs.

On TPU the default low dtype is bfloat16 — same exponent range as fp32, so
(unlike the reference's fp16 CUDA path) loss scaling is optional.
"""

WHITE_LIST = {
    "matmul",
    "mul",
    "bmm",
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    # Pallas flash kernels: bf16 in/out, fp32 softmax internally
    "fused_multihead_attention",
    "fused_qkv_attention",
}

BLACK_LIST = {
    # NOT softmax_with_cross_entropy: its emitter reduces in fp32 from
    # bf16 logits (ops/nn.py) — black-listing it forced a full fp32
    # [N, V] logits cast+materialization at LM heads
    "cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "mean",
    "reduce_mean",
    "reduce_sum",
    "sum",
    "exp",
    "log",
    "square",
    "layer_norm",
    "batch_norm",
    "group_norm",
    "instance_norm",
    "softmax",
    "log_softmax",
}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
