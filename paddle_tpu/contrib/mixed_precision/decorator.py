"""AMP optimizer decorator (reference: contrib/mixed_precision/decorator.py:
decorate :218, OptimizerWithMixedPrecision :27).

Pipeline (matching the reference's): rewrite forward program with casts →
scale loss → backward → check grads finite → unscale/zero grads → update
the dynamic loss scale → inner optimizer update.

TPU default is bfloat16 compute where loss scaling is unnecessary (same
exponent range as fp32); pass dest_dtype="float16" + dynamic scaling for
strict reference parity.
"""

from __future__ import annotations

from ...framework import unique_name
from ...framework.program import default_startup_program, program_guard
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program


class OptimizerWithMixedPrecision:
    def __init__(
        self,
        optimizer,
        amp_lists=None,
        init_loss_scaling=2.0**15,
        use_dynamic_loss_scaling=True,
        incr_every_n_steps=1000,
        decr_every_n_nan_or_inf=2,
        incr_ratio=2.0,
        decr_ratio=0.5,
        dest_dtype="bfloat16",
    ):
        self._inner = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = init_loss_scaling
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dest_dtype = dest_dtype
        self._loss_scaling = None

    def _make_state(self, main, startup):
        from ...framework.state import create_persistable_var

        def persist(name, shape, dtype, value):
            return create_persistable_var(
                name, shape, dtype, value, unique=False,
                main=main, startup=startup,
            )

        self._loss_scaling = persist(
            unique_name.generate("loss_scaling"), [1], "float32",
            self._init_loss_scaling,
        )
        self._good_steps = persist(
            unique_name.generate("good_steps"), [1], "int32", 0
        )
        self._bad_steps = persist(
            unique_name.generate("bad_steps"), [1], "int32", 0
        )

    def get_loss_scaling(self):
        return self._loss_scaling

    def _state_vals(self, scope=None):
        from ...framework.scope import global_scope

        if self._loss_scaling is None:
            return None, None
        scope = scope or global_scope()
        names = (
            self._loss_scaling.name,
            self._good_steps.name,
            self._bad_steps.name,
        )
        vals = [scope.find_var(n) for n in names]
        if any(v is None for v in vals):
            return None, None
        return names, vals

    def state_dict(self, scope=None):
        """Dynamic loss-scale automaton state (scale + good/bad step
        counters) as plain floats/ints, for TrainStatus v2 capture. Empty
        dict before `minimize` built the state vars (nothing to save)."""
        import numpy as np

        names, vals = self._state_vals(scope)
        if names is None:
            return {}
        return {
            "loss_scaling": float(np.asarray(vals[0]).reshape(-1)[0]),
            "good_steps": int(np.asarray(vals[1]).reshape(-1)[0]),
            "bad_steps": int(np.asarray(vals[2]).reshape(-1)[0]),
        }

    def load_state_dict(self, state, scope=None):
        """Restore :meth:`state_dict` into the scope vars. No-op when the
        state vars are not built/resident yet or `state` is empty, so a
        v1 (epoch-only) checkpoint restores cleanly with defaults."""
        import numpy as np

        if not state:
            return
        names, _ = self._state_vals(scope)
        if names is None:
            return
        from ...framework.scope import global_scope

        scope = scope or global_scope()
        scope.set_var(
            names[0],
            np.asarray([state.get("loss_scaling", self._init_loss_scaling)],
                       dtype=np.float32),
        )
        scope.set_var(
            names[1], np.asarray([state.get("good_steps", 0)], dtype=np.int32)
        )
        scope.set_var(
            names[2], np.asarray([state.get("bad_steps", 0)], dtype=np.int32)
        )

    def note_step(self, good, scope=None):
        """Host-side dynamic-loss-scale feedback for good/bad steps
        detected OUTSIDE the compiled block (TrainGuard's fused finite
        check on fetches). Runs the same automaton as the in-graph
        `update_loss_scaling` op against the persistable state vars:
        a bad step zeroes good_steps and decays the scale after
        `decr_every_n_nan_or_inf` consecutive bad ones; a good step
        grows it after `incr_every_n_steps`. No-op before `minimize`
        built the state or when the vars are not in `scope` yet."""
        import numpy as np

        from ...framework.scope import global_scope

        names, vals = self._state_vals(scope)
        if names is None:
            return None
        scope = scope or global_scope()
        scale = float(np.asarray(vals[0]).reshape(-1)[0])
        good_n = int(np.asarray(vals[1]).reshape(-1)[0])
        bad_n = int(np.asarray(vals[2]).reshape(-1)[0])
        if good:
            good_n, bad_n = good_n + 1, 0
            if self._use_dynamic and good_n >= self._incr_every:
                scale, good_n = scale * self._incr_ratio, 0
        else:
            good_n, bad_n = 0, bad_n + 1
            if self._use_dynamic and bad_n >= self._decr_every:
                scale, bad_n = scale * self._decr_ratio, 0
        scope.set_var(names[0], np.asarray([scale], dtype=np.float32))
        scope.set_var(names[1], np.asarray([good_n], dtype=np.int32))
        scope.set_var(names[2], np.asarray([bad_n], dtype=np.int32))
        return scale

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        main = loss.block.program
        startup = startup_program or default_startup_program()
        rewrite_program(main, self._amp_lists, self._dest_dtype)
        with program_guard(main, startup):
            self._make_state(main, startup)
            scaled = loss * self._loss_scaling
            params_grads = self._inner.backward(
                scaled, startup, parameter_list, no_grad_set
            )
            blk = main.global_block
            gnames = [g.name for _, g in params_grads]
            found = blk.create_var(
                name=unique_name.generate("found_inf"), shape=(1,), dtype="bool"
            )
            # FoundInfinite only; the unscale outputs land in fresh names the
            # update op ignores (update_loss_scaling unscales X itself)
            blk.append_op(
                "check_finite_and_unscale",
                {"X": gnames, "Scale": [self._loss_scaling.name]},
                {
                    "Out": [
                        blk.create_var(
                            name=unique_name.generate(n + "@UNS")
                        ).name
                        for n in gnames
                    ],
                    "FoundInfinite": [found.name],
                },
                {},
            )
            # Static scaling reuses the same op with ratios pinned to 1.0:
            # the scale never moves, but non-finite grads are still zeroed
            # so the optimizer update is a no-op on overflow steps (the
            # reference's static path keeps the check too).
            incr_ratio = self._incr_ratio if self._use_dynamic else 1.0
            decr_ratio = self._decr_ratio if self._use_dynamic else 1.0
            blk.append_op(
                "update_loss_scaling",
                {
                    "X": gnames,
                    "FoundInfinite": [found.name],
                    "PrevLossScaling": [self._loss_scaling.name],
                    "InGoodSteps": [self._good_steps.name],
                    "InBadSteps": [self._bad_steps.name],
                },
                {
                    "Out": gnames,
                    "LossScaling": [self._loss_scaling.name],
                    "OutGoodSteps": [self._good_steps.name],
                    "OutBadSteps": [self._bad_steps.name],
                },
                {
                    "incr_every_n_steps": self._incr_every,
                    "decr_every_n_nan_or_inf": self._decr_every,
                    "incr_ratio": incr_ratio,
                    "decr_ratio": decr_ratio,
                },
            )
        return params_grads

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        main = loss.block.program
        with program_guard(main, startup_program or default_startup_program()):
            params_grads = self.backward(
                loss, startup_program, parameter_list, no_grad_set
            )
            ops = self.apply_gradients(params_grads)
        return ops, params_grads


def decorate(
    optimizer,
    amp_lists=None,
    init_loss_scaling=2.0**15,
    use_dynamic_loss_scaling=True,
    incr_every_n_steps=1000,
    decr_every_n_nan_or_inf=2,
    incr_ratio=2.0,
    decr_ratio=0.5,
    dest_dtype="bfloat16",
):
    """fluid.contrib.mixed_precision.decorate parity (decorator.py:218)."""
    return OptimizerWithMixedPrecision(
        optimizer,
        amp_lists=amp_lists,
        init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling,
        incr_every_n_steps=incr_every_n_steps,
        decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
        incr_ratio=incr_ratio,
        decr_ratio=decr_ratio,
        dest_dtype=dest_dtype,
    )
