"""contrib: mixed precision (AMP), quantization-aware training (slim), etc."""

from . import mixed_precision  # noqa: F401
from . import slim  # noqa: F401
