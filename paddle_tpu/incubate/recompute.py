"""Recompute / activation checkpointing (reference: RecomputeOptimizer
optimizer.py:3858 + _append_backward_ops_with_checkpoints_ backward.py:629).

TPU-native design: instead of re-emitting forward ops into the backward
program (the reference re-runs segments between user checkpoints), the ops
of each segment are folded into ONE `recompute_segment` op whose emitter
runs them under `jax.checkpoint` (rematerialization). jax.vjp of a
checkpointed function saves only the segment inputs and recomputes the
segment in the backward pass — XLA schedules the recompute right where the
reference's re-inserted ops would run, but with compiler-chosen overlap.
"""

from __future__ import annotations

import jax

from ..framework.program import default_startup_program, program_guard
from ..framework.registry import OpView, get_op_def, register_op


@register_op("recompute_segment", inputs=["X"], outputs=["Out"])
def _recompute_segment(ctx, op, ins):
    sub_ops = op.attr("sub_ops")  # [(type, inputs, outputs, attrs), ...]
    in_names = op.attr("in_names")
    out_names = op.attr("out_names")

    def seg(*vals):
        env = dict(zip(in_names, vals))
        for op_type, op_ins, op_outs, op_attrs in sub_ops:
            view = OpView(op_type, op_attrs, op_ins, op_outs)
            sub_def = get_op_def(op_type)
            sin = {
                slot: [env[n] if n else None for n in names]
                for slot, names in op_ins.items()
            }
            souts = sub_def.emit(ctx, view, sin)
            for slot, names in op_outs.items():
                vals_ = souts.get(slot, [])
                for n, v in zip(names, vals_):
                    if n and v is not None:
                        env[n] = v
        return tuple(env[n] for n in out_names)

    outs = jax.checkpoint(seg)(*ins["X"])
    return {"Out": list(outs)}


def _segment_io(ops, block, later_reads):
    """External inputs & outputs of an op-span."""
    produced, read = set(), []
    read_seen = set()
    for op in ops:
        for n in op.input_names():
            if n and n not in produced and n not in read_seen:
                read.append(n)
                read_seen.add(n)
        produced.update(n for n in op.output_names() if n)
    outs = []
    for op in ops:
        for n in op.output_names():
            if not n or n in outs:
                continue
            v = block._find_var_recursive(n)
            if n in later_reads or (v is not None and v.persistable):
                outs.append(n)
    return read, outs


def apply_recompute(program, checkpoint_names):
    """Fold ops between consecutive checkpoints into recompute_segment ops.

    checkpoint_names: ordered var names marking segment boundaries. Ops up to
    the producer of checkpoint[0] form segment 1, ... The tail after the last
    checkpoint stays as-is (its activations feed backward immediately —
    reference behavior).

    Composes with pipeline parallelism (reference RecomputeOptimizer under
    PipelineOptimizer, optimizer.py:3858+3556): if the forward has been
    sliced into pipeline stage sub-blocks, recompute recurses into each
    stage block — checkpoints land inside the stage that produced them, and
    the stage's boundary var + loss are protected segment outputs (the
    pipeline scheduler reads them by name between stages)."""
    did = _apply_recompute_block(program, program.global_block,
                                 checkpoint_names)
    for op in program.global_block.ops:
        if op.type != "pipeline_block":
            continue
        protect = set(op.attr("boundary_names")) | {op.attr("loss_name")}
        for bi in op.attr("stage_blocks"):
            did |= _apply_recompute_block(
                program, program.blocks[bi], checkpoint_names,
                protected_reads=protect,
            )
    if did:
        program._bump()
    return program


def _apply_recompute_block(program, block, checkpoint_names,
                           protected_reads=()):
    ops = list(block.ops)
    # index just past the producer of each checkpoint
    bounds = []
    for cname in checkpoint_names:
        pos = None
        for i, op in enumerate(ops):
            if cname in op.output_names():
                pos = i + 1
        if pos is not None:
            bounds.append(pos)
    bounds = sorted(set(bounds))
    if not bounds:
        return False

    # reads occurring after a position (for segment output computation)
    segments = []
    start = 0
    for b in bounds:
        if b - start >= 2:  # folding a single op gains nothing
            segments.append((start, b))
        start = b

    new_ops = []
    cursor = 0
    for start, end in segments:
        new_ops.extend(ops[cursor:start])
        span = ops[start:end]
        later_reads = set(protected_reads)
        for op in ops[end:]:
            later_reads.update(op.input_names())
        in_names, out_names = _segment_io(span, block, later_reads)
        sub = [
            (o.type, {k: list(v) for k, v in o.inputs.items()},
             {k: list(v) for k, v in o.outputs.items()}, dict(o.attrs))
            for o in span
        ]
        from ..framework.program import Operator

        seg_op = Operator(
            block,
            "recompute_segment",
            {"X": in_names},
            {"Out": out_names},
            {
                "sub_ops": sub,
                "in_names": list(in_names),
                "out_names": list(out_names),
            },
        )
        new_ops.append(seg_op)
        cursor = end
    new_ops.extend(ops[cursor:])
    block.ops = new_ops
    return True


class RecomputeOptimizer:
    """Wrap any optimizer; checkpoints set via _set_checkpoints (reference
    API shape, optimizer.py:3858)."""

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = []

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = [
            c.name if hasattr(c, "name") else str(c) for c in (checkpoints or [])
        ]

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        main = loss.block.program
        if self._checkpoints:
            apply_recompute(main, self._checkpoints)
        return self._inner.backward(
            loss, startup_program, parameter_list, no_grad_set
        )

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        main = loss.block.program
        with program_guard(main, startup_program or default_startup_program()):
            params_grads = self.backward(
                loss, startup_program, parameter_list, no_grad_set
            )
            ops = self.apply_gradients(params_grads)
        return ops, params_grads
