"""incubate: meta-optimizers and experimental features (reference
incubate/: fleet lives at paddle_tpu.fleet; recompute here)."""

from .recompute import RecomputeOptimizer, apply_recompute  # noqa: F401
from . import data_generator  # noqa: F401
