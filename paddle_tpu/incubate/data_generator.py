"""User-facing MultiSlot sample writers (reference
python/paddle/fluid/incubate/data_generator/__init__.py): users subclass
DataGenerator, implement generate_sample (one input line -> one or more
[(slot_name, [feasign, ...]), ...] samples), and the generator emits the
MultiSlot text lines that the native parser consumes
(native/multislot.cpp via dataset/factory.py):

    <ids_num> <id1> <id2> ...  per slot, space-joined across slots

The reference writes to stdout for its Hadoop-pipe trainers
(run_from_stdin); here write_to_file is the primary path (local file ->
InMemoryDataset/QueueDataset -> Executor.train_from_dataset), with the
stdin/stdout protocol kept for pipe-command parity.
"""

from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base: subclass and override generate_sample (and optionally
    generate_batch + set_batch)."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        if not isinstance(batch_size, int) or batch_size < 1:
            raise ValueError(f"batch_size must be a positive int, got "
                             f"{batch_size!r}")
        self.batch_size_ = batch_size

    # -- user hooks ----------------------------------------------------
    def generate_sample(self, line):
        """Return a callable yielding [(name, [feasign, ...]), ...] for
        one raw input line (None when generating from memory)."""
        raise NotImplementedError(
            "generate_sample must be implemented by the subclass"
        )

    def generate_batch(self, samples):
        """Optional batch-level hook (identity by default)."""

        def local_iter():
            for s in samples:
                yield s

        return local_iter

    # -- drivers -------------------------------------------------------
    def _iter_outputs(self, lines):
        batch = []
        for line in lines:
            it = self.generate_sample(line)
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    for out in self.generate_batch(batch)():
                        yield self._gen_str(out)
                    batch = []
        if batch:
            for out in self.generate_batch(batch)():
                yield self._gen_str(out)

    def run_from_memory(self, out=None):
        """Emit samples produced by generate_sample(None) (debug path)."""
        out = out or sys.stdout
        for s in self._iter_outputs([None]):
            out.write(s)

    def run_from_stdin(self, stdin=None, out=None):
        """Reference pipe protocol: one raw line in, MultiSlot lines out."""
        stdin = stdin or sys.stdin
        out = out or sys.stdout
        for s in self._iter_outputs(stdin):
            out.write(s)

    def write_to_file(self, lines, path):
        """Process `lines` and write MultiSlot text to `path`; returns the
        number of samples written. The file feeds
        dataset.DatasetFactory().create_dataset(...).set_filelist."""
        n = 0
        with open(path, "w") as f:
            for s in self._iter_outputs(lines):
                f.write(s)
                n += 1
        return n

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator"
        )

    # shared validation for both writers
    def _check_sample(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "generate_sample output must be a list/tuple of "
                "(name, [feasign, ...]) pairs, got " + repr(type(line))
            )
        for item in line:
            name, elements = item
            if not isinstance(name, str):
                raise ValueError(f"slot name must be str, got {type(name)}")
            if not isinstance(elements, (list, tuple)) or not elements:
                raise ValueError(
                    f"slot {name!r}: elements must be a non-empty list "
                    "(pad in generate_sample, the feed is dense)"
                )


class MultiSlotDataGenerator(DataGenerator):
    """Feasigns are ints (slot type uint64) or floats (slot type float);
    a float anywhere in a slot promotes that slot to float — the
    reference's proto_info rule. Slot order and membership must be
    identical across samples."""

    def _gen_str(self, line):
        self._check_sample(line)
        if self._proto_info is None:
            self._proto_info = [(name, "uint64") for name, _ in line]
        elif len(line) != len(self._proto_info):
            raise ValueError(
                "the complete field set of two given lines are inconsistent"
            )
        parts = []
        for idx, (name, elements) in enumerate(line):
            if self._proto_info[idx][0] != name:
                raise ValueError(
                    f"slot order changed: expected "
                    f"{self._proto_info[idx][0]!r}, got {name!r}"
                )
            parts.append(str(len(elements)))
            for e in elements:
                if isinstance(e, float):
                    self._proto_info[idx] = (name, "float")
                elif isinstance(e, bool) or not isinstance(e, int):
                    # bool IS an int subclass but str(True) would write a
                    # non-numeric token the parser rejects much later
                    raise ValueError(
                        f"slot {name!r}: feasign type {type(e)} not int/float"
                    )
                parts.append(str(e))
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """Feasigns are pre-formatted strings (fast path, no type tracking)."""

    def _gen_str(self, line):
        self._check_sample(line)
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"
