"""Fleet parameter-server mode (reference incubate/fleet/parameter_server/:
DistributedTranspiler(Fleet) :41, TranspilerOptimizer :353, the pslib
DownpourOptimizer :867 and the Sync/Async/HalfAsync/Geo strategies).

TPU-native position: the reference's PS stack exists to hold tables bigger
than one accelerator and to ship grads over gRPC to per-shard optimize
blocks (listen_and_serv_op.cc, communicator.h:237). On a TPU mesh the same
capability is the row-sharded in-HBM table + ICI lookup of ops/sparse.py —
no RPC runtime, no async staleness, and the per-shard optimizer locality
comes from sharding the accumulators (parallel/sparse.py). The async/geo
modes trade consistency for bandwidth the ICI fabric does not need; they
are intentionally absent, and `DistributedStrategy(mode=...)` documents
that degrade. This module provides the fleet-PS API surface over that
design: init / distributed_optimizer / minimize / init_server / init_worker
/ save_persistables keep their reference signatures.
"""

from __future__ import annotations

from ..framework.program import default_main_program
from ..parallel.mesh import make_mesh
from ..parallel.sparse import shard_sparse_tables, sparse_table_names
from ..parallel.spmd import shard_program


class DistributedStrategy:
    """reference parameter_server/distributed_strategy.py factory modes.

    sync        — tables updated in-graph every step (sharded over ICI).
    async       — in-graph table updates removed; a host-side
                  AsyncCommunicator applies merged grads with bounded
                  staleness (fleet/communicator.py).
    half_async  — async engine + a per-round barrier: every push is
                  applied before the next step (HalfAsyncCommunicator
                  protocol, communicator.h:299).
    geo         — local training + periodic delta allreduce
                  (GeoCommunicator, update_frequency steps apart).
    """

    def __init__(self, mode="sync", update_frequency=100,
                 send_queue_size=16, merge_size=4):
        if mode not in ("sync", "async", "half_async", "geo"):
            raise ValueError(f"unknown PS mode {mode!r}")
        self.mode = mode
        self.update_frequency = update_frequency
        self.send_queue_size = 1 if mode == "half_async" else send_queue_size
        self.merge_size = merge_size


class StrategyFactory:
    @staticmethod
    def create_sync_strategy():
        return DistributedStrategy("sync")

    @staticmethod
    def create_async_strategy():
        return DistributedStrategy("async")

    @staticmethod
    def create_half_async_strategy():
        return DistributedStrategy("half_async")

    @staticmethod
    def create_geo_strategy(update_frequency=100):
        return DistributedStrategy("geo", update_frequency=update_frequency)


class ParameterServerOptimizer:
    """TranspilerOptimizer parity: wraps the inner optimizer; after minimize
    it row-shards every sparse table (+grad+accumulators) over the "ps"
    axis and attaches the mesh."""

    def __init__(self, optimizer, strategy=None, fleet=None):
        self._inner = optimizer
        self._strategy = strategy or DistributedStrategy("sync")
        self._fleet = fleet

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        program = loss.block.program
        mesh = getattr(self._fleet, "_mesh", None) if self._fleet else None
        if mesh is None:  # fleet.init() not called (or no fleet): default mesh
            mesh = make_mesh({"ps": -1})
        program._mesh = mesh  # so shard_sparse_tables can validate rows%n
        tables = shard_sparse_tables(program, axis="ps")
        if not tables:
            raise ValueError(
                "PS mode but the program has no sparse tables; use "
                "layers.sparse_embedding (or fleet collective mode for "
                "dense-only models)"
            )
        shard_program(program, mesh)
        if self._strategy.mode in ("async", "half_async"):
            if self._fleet is None:
                # stripping the in-graph table updates without a fleet to
                # host the communicator would silently freeze the tables
                raise ValueError(
                    "async PS mode needs a fleet: use "
                    "ParameterServerFleet().init().distributed_optimizer(...)"
                    " so init_worker() can start the AsyncCommunicator"
                )
            from .communicator import async_ps_transpile

            grad_of = async_ps_transpile(program, tables)
            # stash the INNER optimizer's lr/type so init_worker's host
            # applier steps the tables at the same rate as the dense
            # params (advisor: a silent default lr mismatch converges
            # wrong with no error)
            lr = getattr(self._inner, "_learning_rate", 0.01)
            opt_name = type(self._inner).__name__.lower()
            self._fleet._async_info = (grad_of, self._strategy, lr, opt_name)
        elif self._strategy.mode == "geo" and self._fleet is not None:
            self._fleet._geo_info = (tables, self._strategy)
        return ops, params_grads


class ParameterServerFleet:
    """Fleet PS facade (reference DistributedTranspiler(Fleet))."""

    def __init__(self):
        self._role = None
        self._mesh = None
        self._async_info = None
        self._geo_info = None
        self.communicator = None

    def init(self, role_maker=None, mesh=None):
        self._role = role_maker
        self._mesh = mesh if mesh is not None else make_mesh({"ps": -1})
        return self

    def distributed_optimizer(self, optimizer, strategy=None):
        return ParameterServerOptimizer(optimizer, strategy, fleet=self)

    # every process is both trainer and table shard owner on a TPU mesh:
    # the reference's server/worker split collapses
    def init_server(self, *args, **kwargs):
        pass

    def init_worker(self, scope=None, exe=None, lr=None, optimizer=None):
        """Start the communicator for async/geo strategies (reference
        fleet.init_worker starts the Communicator singleton). lr/optimizer
        default to the INNER optimizer handed to distributed_optimizer —
        override only to intentionally train tables at a different rate."""
        from ..framework.scope import global_scope

        if self._async_info is not None:
            from .communicator import AsyncCommunicator

            grad_of, strategy, inner_lr, inner_opt = self._async_info
            eff_lr = lr if lr is not None else (
                inner_lr if isinstance(inner_lr, (int, float)) else 0.01
            )
            eff_opt = optimizer or (
                "adam" if "adam" in inner_opt else "sgd"
            )
            self.communicator = AsyncCommunicator(
                scope or global_scope(), grad_of, lr=float(eff_lr),
                optimizer=eff_opt,
                send_queue_size=strategy.send_queue_size,
                merge_size=strategy.merge_size,
                step_barrier=strategy.mode == "half_async",
            ).start()
        elif self._geo_info is not None:
            from .communicator import GeoCommunicator

            if exe is None:
                raise ValueError(
                    "geo PS mode: pass the Executor to init_worker(exe=...) "
                    "— the periodic delta sync runs a compiled program"
                )
            tables, strategy = self._geo_info
            self.communicator = GeoCommunicator(
                tables, scope or global_scope(), exe,
                update_frequency=strategy.update_frequency,
                mesh=self._mesh,
            )
        return self.communicator

    def run_server(self):
        pass

    def stop_worker(self):
        if self.communicator is not None and hasattr(self.communicator, "stop"):
            self.communicator.flush()
            self.communicator.stop()
        self.communicator = None

    def is_server(self):
        return True

    def is_worker(self):
        return True

    def worker_num(self):
        return len(self._mesh.devices.flat) if self._mesh is not None else 1

    def server_num(self):
        return self.worker_num()

    def sparse_table_names(self, program=None):
        return sparse_table_names(program or default_main_program())

    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io

        io.save_persistables(executor, dirname, main_program)


fleet = ParameterServerFleet()
