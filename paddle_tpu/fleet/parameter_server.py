"""Fleet parameter-server mode (reference incubate/fleet/parameter_server/:
DistributedTranspiler(Fleet) :41, TranspilerOptimizer :353, the pslib
DownpourOptimizer :867 and the Sync/Async/HalfAsync/Geo strategies).

TPU-native position: the reference's PS stack exists to hold tables bigger
than one accelerator and to ship grads over gRPC to per-shard optimize
blocks (listen_and_serv_op.cc, communicator.h:237). On a TPU mesh the same
capability is the row-sharded in-HBM table + ICI lookup of ops/sparse.py —
no RPC runtime, no async staleness, and the per-shard optimizer locality
comes from sharding the accumulators (parallel/sparse.py). The async/geo
modes trade consistency for bandwidth the ICI fabric does not need; they
are intentionally absent, and `DistributedStrategy(mode=...)` documents
that degrade. This module provides the fleet-PS API surface over that
design: init / distributed_optimizer / minimize / init_server / init_worker
/ save_persistables keep their reference signatures.
"""

from __future__ import annotations

from ..framework.program import default_main_program
from ..parallel.mesh import make_mesh
from ..parallel.sparse import shard_sparse_tables, sparse_table_names
from ..parallel.spmd import shard_program


class DistributedStrategy:
    """reference parameter_server/distributed_strategy.py factory modes."""

    def __init__(self, mode="sync"):
        if mode not in ("sync", "async", "half_async", "geo"):
            raise ValueError(f"unknown PS mode {mode!r}")
        # async/half_async/geo traded staleness for gRPC bandwidth; on ICI
        # the sync path is strictly faster, so every mode runs sync.
        self.mode = mode


class StrategyFactory:
    @staticmethod
    def create_sync_strategy():
        return DistributedStrategy("sync")

    @staticmethod
    def create_async_strategy():
        return DistributedStrategy("async")

    @staticmethod
    def create_half_async_strategy():
        return DistributedStrategy("half_async")

    @staticmethod
    def create_geo_strategy(update_frequency=100):
        return DistributedStrategy("geo")


class ParameterServerOptimizer:
    """TranspilerOptimizer parity: wraps the inner optimizer; after minimize
    it row-shards every sparse table (+grad+accumulators) over the "ps"
    axis and attaches the mesh."""

    def __init__(self, optimizer, strategy=None, fleet=None):
        self._inner = optimizer
        self._strategy = strategy or DistributedStrategy("sync")
        self._fleet = fleet

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        program = loss.block.program
        mesh = getattr(self._fleet, "_mesh", None) if self._fleet else None
        if mesh is None:  # fleet.init() not called (or no fleet): default mesh
            mesh = make_mesh({"ps": -1})
        program._mesh = mesh  # so shard_sparse_tables can validate rows%n
        tables = shard_sparse_tables(program, axis="ps")
        if not tables:
            raise ValueError(
                "PS mode but the program has no sparse tables; use "
                "layers.sparse_embedding (or fleet collective mode for "
                "dense-only models)"
            )
        shard_program(program, mesh)
        return ops, params_grads


class ParameterServerFleet:
    """Fleet PS facade (reference DistributedTranspiler(Fleet))."""

    def __init__(self):
        self._role = None
        self._mesh = None

    def init(self, role_maker=None, mesh=None):
        self._role = role_maker
        self._mesh = mesh if mesh is not None else make_mesh({"ps": -1})
        return self

    def distributed_optimizer(self, optimizer, strategy=None):
        return ParameterServerOptimizer(optimizer, strategy, fleet=self)

    # every process is both trainer and table shard owner on a TPU mesh:
    # the reference's server/worker split collapses
    def init_server(self, *args, **kwargs):
        pass

    def init_worker(self):
        pass

    def run_server(self):
        pass

    def stop_worker(self):
        pass

    def is_server(self):
        return True

    def is_worker(self):
        return True

    def worker_num(self):
        return len(self._mesh.devices.flat) if self._mesh is not None else 1

    def server_num(self):
        return self.worker_num()

    def sparse_table_names(self, program=None):
        return sparse_table_names(program or default_main_program())

    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io

        io.save_persistables(executor, dirname, main_program)


fleet = ParameterServerFleet()
