"""Role makers: who am I in the distributed job?

Reference parity: incubate/fleet/base/role_maker.py (RoleMakerBase :68,
PaddleCloudRoleMaker env-based, UserDefinedRoleMaker). TPU-native changes:
  * the process unit is a HOST (each host drives its local chips via one
    JAX process), not a GPU — so worker_num == number of host processes;
  * rendezvous is jax.distributed's coordination service (the analog of the
    reference's gen_nccl_id RPC server, c_gen_nccl_id_op.cc:87-108, and the
    http_server.py KV store): coordinator address + process id from env.
"""

from __future__ import annotations

import os

import jax


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._generated = False
        self._trainer_id = 0
        self._trainers_num = 1
        self._role = Role.WORKER
        self._worker_endpoints = []
        self._server_endpoints = []

    def generate_role(self):
        self._generated = True

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._trainer_id == 0

    def worker_index(self):
        return self._trainer_id

    def worker_num(self):
        return self._trainers_num

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var role maker (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
    PADDLE_TRAINER_ENDPOINTS), the variables set by our launch module and by
    the reference's launcher (launch.py:193-227)."""

    def __init__(self, is_collective=True):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._generated:
            return
        env = os.environ
        self._trainer_id = int(env.get("PADDLE_TRAINER_ID", 0))
        eps = env.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        self._trainers_num = int(
            env.get("PADDLE_TRAINERS_NUM", max(1, len(self._worker_endpoints)))
        )
        self._server_endpoints = [
            e for e in env.get("PADDLE_PSERVER_ENDPOINTS", "").split(",") if e
        ]
        if env.get("TRAINING_ROLE", "TRAINER") == "PSERVER":
            self._role = Role.SERVER
        # multi-host: bring up the JAX coordination service so every host's
        # chips join one global device set (replaces ncclUniqueId exchange)
        if self._trainers_num > 1 and self._worker_endpoints:
            coordinator = env.get(
                "PADDLE_COORDINATOR", self._worker_endpoints[0]
            )
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=self._trainers_num,
                    process_id=self._trainer_id,
                )
            except RuntimeError as e:
                # tolerate only re-init; a failed rendezvous must NOT silently
                # degrade a multi-host job to independent single-host training
                if "already initialized" not in str(e).lower():
                    raise
        self._generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._trainer_id = current_id
        self._role = role
        self._trainers_num = worker_num
        self._server_endpoints = server_endpoints or []
