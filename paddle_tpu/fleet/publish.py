"""Live model publish plane: versioned delta bundles, trainer → servers.

The pslib/FleetWrapper online-learning loop (reference
``framework/fleet/fleet_wrapper.h``) keeps a model serving WHILE it
trains: the trainer streams minutes-fresh weight updates to the serving
fleet instead of redeploying checkpoints. This module is that loop's
transport, built from contracts the repo already trusts:

* :class:`ModelPublisher` (trainer side) emits **versioned update
  bundles** under a publish directory — ``v000001/``, ``v000002/``, … —
  each holding one ``model_update.npz`` payload + CRC manifest written
  with the io.py durability discipline (temp + fsync + ``os.replace``)
  and a ``commit.json`` written LAST: a version without its commit
  record does not exist to any reader, so a publisher crash mid-bundle
  is invisible rather than torn. Bundles form the PR-12 delta-chain
  shape: a FULL bundle carries every persistable; a DELTA bundle carries
  only CRC-changed dense arrays plus row-level embedding payloads
  (``<name>@@rows``/``@@ridx`` pairs from
  ``EmbeddingEngine.delta_row_oracles(consumer="publish")`` — the
  per-consumer cursor, so a checkpoint landing between publishes cannot
  eat the publisher's dirty rows) and names its ``base`` version;
  :func:`resolve_chain`/:func:`load_version` fold any committed version
  back to full arrays, bitwise equal to the trainer's snapshot.
* :class:`ModelSubscriber` (serving side) follows the directory and
  applies updates **all-or-nothing between batches**: the target
  arrays are folded and verified first, the pre-apply scope values are
  snapshotted, and any failure during the scope mutation (the
  ``publish.apply`` chaos seam fires inside it) restores the snapshot —
  the subscriber's ``version`` only ever names a fully-applied bundle,
  which is the epoch fence no mixed-version batch can cross.
* Rollback is data, not control flow: :func:`block_version` records a
  bad version in ``blocked.json`` (atomic), every subscriber's
  :meth:`~ModelSubscriber.poll` targets the newest *eligible* version,
  and a downgrade re-folds the full chain of the rollback target — the
  exact path a fresh worker takes, so rolled-back and cold-started
  replicas are bitwise identical.

Staleness is first-class: ``serving.model_version`` and
``serving.model_staleness_seconds`` gauges (journal-replayable, so
``tools/fleet_report.py`` renders cross-process publish-version skew),
the version stamped into heartbeats, and the commit record carrying the
publisher's :class:`~paddle_tpu.observability.trace.TraceContext` — the
click→gradient→published-row→served freshness trace rides it into every
subscriber's apply span.

Chaos seams: ``publish.commit`` fires after the payload lands but
before the commit record (a raising kind = a crash that must stay
invisible; ``hang`` = a wedged publisher mid-commit), ``publish.apply``
fires inside the subscriber's scope mutation (a raising kind must leave
the old version bitwise intact; ``hang`` = the SIGKILL-mid-apply window
the CI chaos stage shoots into).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time

import numpy as np

from ..errors import CheckpointCorruptionError, InvalidArgumentError
from ..resilience.faults import fault_point

__all__ = [
    "BLOCKED_NAME",
    "COMMIT_NAME",
    "PAYLOAD_NAME",
    "ModelPublisher",
    "ModelSubscriber",
    "block_version",
    "committed_versions",
    "latest_version",
    "load_version",
    "read_blocked",
    "read_commit",
    "resolve_chain",
    "version_dir",
]

COMMIT_NAME = "commit.json"
PAYLOAD_NAME = "model_update.npz"
BLOCKED_NAME = "blocked.json"


# -- directory layout --------------------------------------------------------
def version_dir(publish_dir, version):
    """``{publish_dir}/v{version:06d}`` — the bundle dir naming contract."""
    return os.path.join(publish_dir, f"v{int(version):06d}")


def _commit_path(publish_dir, version):
    return os.path.join(version_dir(publish_dir, version), COMMIT_NAME)


def committed_versions(publish_dir):
    """Sorted committed version numbers (a ``v*`` dir WITHOUT its commit
    record is an unfinished publish and does not exist to readers)."""
    out = []
    try:
        entries = os.listdir(publish_dir)
    except OSError:
        return out
    for name in entries:
        if not (name.startswith("v") and name[1:].isdigit()):
            continue
        v = int(name[1:])
        if os.path.exists(_commit_path(publish_dir, v)):
            out.append(v)
    return sorted(out)


def read_commit(publish_dir, version):
    """The commit record of `version` (raises typed when absent/torn)."""
    path = _commit_path(publish_dir, version)
    try:
        with open(path) as f:
            commit = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptionError(
            f"unreadable publish commit record {path!r}: {e}"
        ) from e
    if not isinstance(commit, dict) or int(commit.get("version", -1)) != int(
        version
    ):
        raise CheckpointCorruptionError(
            f"publish commit record {path!r} does not describe version "
            f"{version}"
        )
    return commit


def read_blocked(publish_dir):
    """Versions rolled back as bad — every subscriber skips them."""
    try:
        with open(os.path.join(publish_dir, BLOCKED_NAME)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return set()
    return {int(v) for v in data.get("blocked", ())}


def block_version(publish_dir, version):
    """Record `version` as bad (atomic read-modify-write; the rollout
    controller is the single writer). Returns the new blocked set."""
    from .. import io as _io

    blocked = read_blocked(publish_dir)
    blocked.add(int(version))
    payload = json.dumps({"blocked": sorted(blocked)}).encode()
    _io._atomic_write(
        os.path.join(publish_dir, BLOCKED_NAME), lambda f: f.write(payload)
    )
    from .. import observability as _obs

    _obs.add("publish.versions_blocked")
    return blocked


def latest_version(publish_dir, blocked=None):
    """Newest committed version, skipping blocked ones; None when empty."""
    if blocked is None:
        blocked = read_blocked(publish_dir)
    for v in reversed(committed_versions(publish_dir)):
        if v not in blocked:
            return v
    return None


def resolve_chain(publish_dir, version):
    """The bundle chain for `version`, oldest→newest: walk ``base``
    pointers back to the FULL bundle the deltas build on. Raises typed
    when a link is missing (rotated away) or the chain is cyclic."""
    chain = []
    seen = set()
    v = int(version)
    while True:
        if v in seen:
            raise CheckpointCorruptionError(
                f"publish chain for v{version} is cyclic at v{v}"
            )
        seen.add(v)
        commit = read_commit(publish_dir, v)
        chain.append(v)
        if commit.get("kind") == "full":
            chain.reverse()
            return chain
        base = commit.get("base")
        if base is None:
            raise CheckpointCorruptionError(
                f"publish delta bundle v{v} names no base version"
            )
        v = int(base)


def load_version(publish_dir, version):
    """Fold `version`'s chain into full host arrays (the cold-load path
    — and the bitwise reference every delta-applied subscriber must
    match). Every link is CRC-verified before any folding."""
    from .. import io as _io

    acc = {}
    for v in resolve_chain(publish_dir, version):
        arrays = _io.read_persistables(
            version_dir(publish_dir, v), filename=PAYLOAD_NAME
        )
        _io.merge_checkpoint_arrays(acc, arrays, f"publish v{v}")
    return acc


# -- trainer side ------------------------------------------------------------
class ModelPublisher:
    """Emit versioned update bundles from the live training scope.

    ``publish()`` snapshots the program's persistables (plus, with an
    ``engine=``, the embedding host stores after a flush), decides full
    vs delta (first bundle full, then a full every ``full_every``
    bundles — the chain-length bound), row/CRC-filters the delta, and
    commits the bundle with the commit record LAST. Fingerprints and
    row cursors advance only after a successful commit, so a failed
    publish changes nothing and the next one re-carries its rows.

    The publisher is a freeze target for the storage pressure ladder
    (the HARD rung, same contract as ``RolloutController.freeze``):
    while frozen, ``publish()`` is a cheap skip returning None
    (``publish.skipped_frozen``) — cursors don't advance, so the first
    post-thaw bundle carries everything the frozen window trained.
    Construction also sweeps ``*.tmp.*`` residue out of the publish
    root: the publisher is the dir's single writer, so any temp file
    found at startup is a dead predecessor's and safe to unlink.
    """

    def __init__(self, publish_dir, main_program=None, scope=None,
                 engine=None, full_every=8, max_versions=8, exclude=None):
        if int(full_every) < 1:
            raise InvalidArgumentError(
                f"ModelPublisher: full_every must be >= 1, got {full_every}"
            )
        if int(max_versions) < 2:
            raise InvalidArgumentError(
                f"ModelPublisher: max_versions must be >= 2, got "
                f"{max_versions}"
            )
        self.publish_dir = os.fspath(publish_dir)
        self._main = main_program
        self._scope = scope
        self._engine = engine
        self._full_every = int(full_every)
        self._max_versions = int(max_versions)
        self._exclude = tuple(exclude or ())
        self._snap_cache = {}
        self._fp = {}              # name -> manifest entry at last commit
        self._row_marks = {}       # oracle key -> mark at last commit
        self._since_full = None    # deltas since the last committed full
        self._frozen = False
        self._lock = threading.Lock()
        os.makedirs(self.publish_dir, exist_ok=True)
        from .. import io as _io

        _io.sweep_stale_tmp(self.publish_dir, recursive=True)
        committed = committed_versions(self.publish_dir)
        self._next = (committed[-1] + 1) if committed else 1

    # -- the freeze rung ---------------------------------------------------
    @property
    def frozen(self):
        return self._frozen

    def freeze(self, reason=None):
        """Stop emitting bundles (storage HARD rung / operator hold).
        Idempotent; already-committed versions stay readable."""
        from .. import observability as _obs

        if not self._frozen:
            self._frozen = True
            _obs.add("publish.freezes")
            if reason:
                _obs.add(f"publish.freezes.{reason}")
        _obs.set_gauge("publish.frozen", 1.0)

    def unfreeze(self):
        from .. import observability as _obs

        self._frozen = False
        _obs.set_gauge("publish.frozen", 0.0)

    # -- payload assembly --------------------------------------------------
    def _collect(self):
        from .. import io as _io

        scope = self._scope
        arrays = _io.snapshot_persistables(
            self._main, scope=scope, exclude=self._exclude,
            reuse_cache=self._snap_cache,
        )
        if self._engine is not None:
            # flush first: resident rows write back to the host stores
            # (bumping their dirty ticks), so trained state always lands
            # in the bundle
            self._engine.flush(scope)
            for g in self._engine.groups:
                for vname, store in g.host.items():
                    arrays[f"{g.name}::host::{vname}"] = store.copy()
        return arrays

    def _row_filter(self, arrays, is_full):
        """Replace oracle-covered host stores with (rows, indices) pairs
        on a delta bundle. Returns the proposed mark updates (applied
        only on commit)."""
        from .. import io as _io

        if self._engine is None:
            return {}
        oracles = self._engine.delta_row_oracles(consumer="publish")
        marks = {}
        for name, oracle in oracles.items():
            last = self._row_marks.get(name)
            rows, mark = oracle(last)
            marks[name] = mark
            # rows is None = no base for THIS consumer (neither an
            # in-process mark nor a committed group cursor): ship full.
            # A restarted publisher (last None, cursor present) still
            # gets row deltas — the cursor is exactly what survives it.
            if is_full or rows is None:
                continue
            if name in arrays:
                rows = np.asarray(rows, dtype=np.int64)
                full = arrays.pop(name)
                arrays[name + _io.ROW_VAL_MARK] = np.ascontiguousarray(
                    full[rows]
                )
                arrays[name + _io.ROW_IDX_MARK] = rows
        return marks

    def _crc_filter(self, arrays):
        """Drop dense arrays whose CRC matches the chain's last committed
        value; row pairs always pass (already minimal)."""
        from .. import io as _io
        from .. import observability as _obs

        out, fp = {}, {}
        dropped = 0
        for name, arr in arrays.items():
            if name.endswith(_io.ROW_VAL_MARK) or name.endswith(
                _io.ROW_IDX_MARK
            ):
                out[name] = arr
                continue
            entry = _io._array_entry(arr)
            if self._fp.get(name) == entry:
                dropped += int(arr.nbytes)
                continue
            out[name] = arr
            fp[name] = entry
        if dropped:
            _obs.add("publish.delta_bytes_dropped", dropped)
        return out, fp

    # -- publish -----------------------------------------------------------
    def publish(self, step=None):
        """Emit one bundle; returns its version number. Raises typed on
        failure, leaving no committed trace and no advanced cursors."""
        from .. import io as _io
        from .. import observability as _obs
        from ..observability import trace as _trace
        from ..resilience import storage as _storage

        if self._frozen:
            _obs.add("publish.skipped_frozen")
            return None
        _storage.require_writable("publish")
        with self._lock:
            t0 = time.perf_counter()
            is_full = self._since_full is None or (
                self._since_full >= self._full_every - 1
            )
            base = None if is_full else self._next - 1
            arrays = self._collect()
            marks = self._row_filter(arrays, is_full)
            if is_full:
                fp = {
                    name: _io._array_entry(arr)
                    for name, arr in arrays.items()
                }
            else:
                arrays, fp = self._crc_filter(arrays)
            version = self._next
            vdir = version_dir(self.publish_dir, version)
            if os.path.isdir(vdir):
                # an uncommitted carcass from a crashed publish: it never
                # existed to readers, reclaim the number
                shutil.rmtree(vdir, ignore_errors=True)
            try:
                _io.save_arrays(vdir, arrays, filename=PAYLOAD_NAME)
                # the commit seam: a raising kind here is the crash that
                # durability must make invisible — payload written,
                # commit record absent, version nonexistent to readers
                fault_point("publish.commit")
                ctx = _trace.current()
                commit = {
                    "version": int(version),
                    "kind": "full" if is_full else "delta",
                    "base": base,
                    "created_at": time.time(),
                    "step": None if step is None else int(step),
                    "arrays": len(arrays),
                }
                if ctx is not None:
                    commit.update(ctx.to_dict())
                payload = json.dumps(commit, indent=1).encode()
                _io._atomic_write(
                    _commit_path(self.publish_dir, version),
                    lambda f: f.write(payload),
                )
            except BaseException:
                shutil.rmtree(vdir, ignore_errors=True)
                raise
            # commit succeeded: NOW advance every cursor
            self._next = version + 1
            self._since_full = 0 if is_full else self._since_full + 1
            if is_full:
                self._fp = fp
            else:
                self._fp.update(fp)
            self._row_marks.update(marks)
            if self._engine is not None:
                self._engine.commit_row_marks("publish", marks)
            self._retire()
            _obs.add("publish.versions")
            _obs.set_gauge("publish.version", float(version))
            _obs.add(
                "publish.bytes",
                int(sum(np.asarray(a).nbytes for a in arrays.values())),
            )
            _obs.observe(
                "publish.commit_latency", time.perf_counter() - t0
            )
            return version

    def _retire(self):
        """Keep the last ``max_versions`` bundles plus every bundle a
        kept delta still chains through — a base full is never rotated
        out from under its dependents."""
        committed = committed_versions(self.publish_dir)
        if len(committed) <= self._max_versions:
            return
        keep = set(committed[-self._max_versions:])
        for v in list(keep):
            try:
                keep.update(resolve_chain(self.publish_dir, v))
            except CheckpointCorruptionError:
                pass
        for v in committed:
            if v in keep:
                continue
            vdir = version_dir(self.publish_dir, v)
            # commit record first: the dir stops existing to readers
            # before its payload disappears
            try:
                os.unlink(_commit_path(self.publish_dir, v))
            except OSError:
                pass
            shutil.rmtree(vdir, ignore_errors=True)


# -- serving side ------------------------------------------------------------
class ModelSubscriber:
    """Follow a publish dir; apply updates all-or-nothing into a scope.

    The subscriber mutates nothing until the full target payload is
    folded and CRC-verified; the scope writes happen inside a
    pre-mutation snapshot that any failure (including the
    ``publish.apply`` seam) restores — so :attr:`version` only ever
    names a fully-applied bundle. The caller provides the fence: apply
    between batches (the process worker's serve loop) or under the
    runner's dispatch lock (:class:`serving.rollout.SubscribedRunner`).
    """

    def __init__(self, publish_dir, main_program=None, scope=None,
                 heartbeat=None, name="subscriber"):
        self.publish_dir = os.fspath(publish_dir)
        self._main = main_program
        self._scope = scope
        self._heartbeat = heartbeat
        self.name = name
        self.version = None
        self.commit_time = None    # created_at of the applied bundle
        self.shapes_changed = False  # did the LAST apply change any shape
        self._applied_chain = ()   # chain of the applied version

    # -- staleness ---------------------------------------------------------
    def staleness_s(self, now=None):
        """Seconds since the applied bundle was published (grows between
        publishes, snaps down on apply — the monotonic-between-applies
        contract the staleness test holds)."""
        if self.commit_time is None:
            return None
        return max(0.0, (time.time() if now is None else now)
                   - float(self.commit_time))

    def _publish_gauges(self):
        from .. import observability as _obs

        if self.version is not None:
            _obs.set_gauge("serving.model_version", float(self.version))
        stale = self.staleness_s()
        if stale is not None:
            _obs.set_gauge("serving.model_staleness_seconds", stale)

    # -- apply -------------------------------------------------------------
    def target_version(self):
        """Newest eligible (committed, not blocked) version, or None."""
        return latest_version(self.publish_dir)

    def poll(self):
        """Apply the newest eligible version when it differs from the
        applied one (a LOWER target = a rollback, taken via a full chain
        re-fold). Returns the newly applied version, or None. Always
        refreshes the staleness gauge."""
        target = self.target_version()
        applied = None
        if target is not None and target != self.version:
            applied = self.apply_version(target)
        self._publish_gauges()
        return applied

    def apply_version(self, version):
        """Fold `version` and swap it into the scope atomically (from
        any reader's point of view: old version before, new version
        after, nothing in between survives an error)."""
        from .. import io as _io
        from .. import observability as _obs
        from ..observability import trace as _trace

        version = int(version)
        t0 = time.perf_counter()
        commit = read_commit(self.publish_dir, version)
        chain = resolve_chain(self.publish_dir, version)
        # incremental when the applied version is an ancestor on this
        # exact chain: only the new links fold, with the live scope as
        # the row-delta base. Anything else — first apply, rollback,
        # divergent chain — re-folds from the full bundle.
        incremental = (
            self.version is not None
            and self.version in chain
            and tuple(chain[: chain.index(self.version) + 1])
            == tuple(self._applied_chain)
        )
        links = chain[chain.index(self.version) + 1:] if incremental \
            else chain
        acc = {}
        payloads = [
            _io.read_persistables(
                version_dir(self.publish_dir, v), filename=PAYLOAD_NAME
            )
            for v in links
        ]
        if incremental:
            # pre-seed row-delta bases from the live scope (the applied
            # chain made it bitwise equal to the folded base)
            for arrays in payloads:
                for pname in arrays:
                    if pname.endswith(_io.ROW_VAL_MARK):
                        base = pname[: -len(_io.ROW_VAL_MARK)]
                        val = self._find(base)
                        if val is not None and base not in acc:
                            acc[base] = np.array(val, copy=True)
        for v, arrays in zip(links, payloads):
            _io.merge_checkpoint_arrays(acc, arrays, f"publish v{v}")
        # only names this scope actually serves apply; the rest (e.g.
        # trainer-only optimizer state, host stores of an engine this
        # replica does not run) are counted, not errors
        apply, skipped = {}, 0
        for pname, arr in acc.items():
            if self._find(pname) is not None:
                apply[pname] = arr
            else:
                skipped += 1
        if skipped:
            _obs.add("publish.arrays_skipped", skipped)
        before = {pname: self._find(pname) for pname in apply}
        ctx = None
        if "trace_id" in commit:
            ctx = _trace.TraceContext(
                commit["trace_id"], commit.get("span_id")
            )
        try:
            with _trace.activate(ctx):
                # the apply seam, INSIDE the fence: a raising kind must
                # leave the old version bitwise intact; a hang is the
                # SIGKILL-mid-apply window
                fault_point("publish.apply")
                self._write(apply)
        except BaseException:
            self._write(before, restore=True)
            _obs.add("publish.apply_failures")
            raise
        self.shapes_changed = any(
            tuple(np.shape(before[pname])) != tuple(np.shape(arr))
            for pname, arr in apply.items()
        )
        self.version = version
        self.commit_time = commit.get("created_at")
        self._applied_chain = tuple(chain)
        _obs.add("publish.applies")
        _obs.observe("publish.apply_latency", time.perf_counter() - t0)
        self._publish_gauges()
        if self._heartbeat is not None:
            try:
                self._heartbeat.set_stamp("model_version", version)
            except Exception:
                pass  # a broken stamp must not fail an apply
        return version

    # -- scope plumbing ----------------------------------------------------
    def _find(self, name):
        from ..framework.scope import global_scope

        scope = self._scope if self._scope is not None else global_scope()
        return scope.find_var(name)

    def _write(self, arrays, restore=False):
        import jax.numpy as jnp

        from ..framework.scope import global_scope

        scope = self._scope if self._scope is not None else global_scope()
        for name, arr in arrays.items():
            scope.set_var(
                name, arr if restore else jnp.asarray(arr)
            )
