"""Async / Geo parameter-server communicators (reference
operators/distributed/communicator.h: AsyncCommunicator :237,
HalfAsyncCommunicator :299, GeoCommunicator :365, and
transpiler/geo_sgd_transpiler.py).

TPU-native re-design, same capability:

* AsyncCommunicator — the trainer's compiled step STOPS updating the
  sparse tables in-graph (async_ps_transpile removes those optimizer ops);
  table grads come back as fetches and are pushed into a bounded queue. A
  host-side worker thread merges up to `merge_size` pending grads per
  table and applies the update to the scope-resident table buffers. The
  trainer reads tables from the scope at each step, so updates land with
  bounded staleness: `send_queue_size` caps the number of un-applied
  batches (push blocks when full — set it to 0/1 for the reference's
  HalfAsyncCommunicator barrier semantics).

* GeoCommunicator — every worker trains on its LOCAL table copy (the
  in-graph optimizer keeps running); every `update_frequency` steps the
  workers exchange table DELTAS (cur - base) through a compiled
  c_allreduce_sum program over the process mesh and rebase:
  new = base + sum_w(delta_w). This is Geo-SGD's consistency model —
  divergence bounded by the sync period — with XLA collectives instead of
  the reference's gRPC delta-send.

The reference's server side (listen_and_serv + per-pserver optimize
blocks) has no analog here because tables are scope-resident on the
trainer side (sharded over the mesh in multi-chip runs); the communicator
IS the server loop.
"""

from __future__ import annotations

import queue
import threading

import numpy as np


def async_ps_transpile(program, table_names):
    """Remove in-graph optimizer ops that update the given tables; returns
    {table_name: grad_var_name} for the communicator to fetch. Mirrors the
    reference's delete_optimizer_pass (ps_program_builder)."""
    blk = program.global_block
    grad_of = {}
    kept = []
    for op in blk.ops:
        params = op.inputs.get("Param", [])
        if params and params[0] in table_names:
            grads = op.inputs.get("Grad", [])
            if grads:
                grad_of[params[0]] = grads[0]
            continue  # drop the table's in-graph update
        kept.append(op)
    blk.ops[:] = kept
    missing = [t for t in table_names if t not in grad_of]
    if missing:
        raise ValueError(
            f"async_ps_transpile: no optimizer op found for tables "
            f"{missing}; run optimizer.minimize first"
        )
    return grad_of


class _HostSGD:
    def __init__(self, lr):
        self.lr = lr

    def apply(self, table, grad):
        table -= self.lr * grad
        return table


class _HostAdam:
    def __init__(self, lr, beta1=0.9, beta2=0.999, eps=1e-8):
        self.lr, self.b1, self.b2, self.eps = lr, beta1, beta2, eps
        self._m = {}
        self._v = {}
        self._t = {}

    def apply(self, table, grad, key="t"):
        m = self._m.setdefault(key, np.zeros_like(table))
        v = self._v.setdefault(key, np.zeros_like(table))
        t = self._t.get(key, 0) + 1
        self._t[key] = t
        m += (1 - self.b1) * (grad - m)
        v += (1 - self.b2) * (grad * grad - v)
        mhat = m / (1 - self.b1 ** t)
        vhat = v / (1 - self.b2 ** t)
        table -= self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return table


class AsyncCommunicator:
    """Host-side async update engine over scope-resident tables."""

    def __init__(self, scope, grad_of, lr=0.01, optimizer="sgd",
                 send_queue_size=16, merge_size=4, step_barrier=False):
        self._scope = scope
        self._grad_of = dict(grad_of)
        self._opts = {
            t: (_HostAdam(lr) if optimizer == "adam" else _HostSGD(lr))
            for t in grad_of
        }
        # staleness bound: at most send_queue_size un-applied pushes;
        # 1 ~= half-async (trainer blocks until the previous batch lands)
        self._q = queue.Queue(maxsize=max(1, send_queue_size))
        # half-async (communicator.h:299 HalfAsyncCommunicator): a barrier
        # per round — every pushed gradient is applied before the next
        # step starts. Stronger than send_queue_size=1 (which still
        # permits one in-flight batch of staleness); this is the
        # reference's BarrierTriggerDecrement round protocol expressed as
        # a flush after each push.
        self._step_barrier = bool(step_barrier)
        self._merge_size = max(1, merge_size)
        self._stop = threading.Event()
        self._thread = None
        self._applied = 0
        self._error = None

    # -- trainer side -------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def _check_error(self):
        if self._error is not None:
            raise RuntimeError(
                "AsyncCommunicator apply thread died"
            ) from self._error

    def push(self, grads):
        """grads: {table_name: np.ndarray}; blocks when the staleness
        bound is reached (communicator.h send_queue_size semantics)."""
        self._check_error()
        self._q.put({k: np.asarray(v) for k, v in grads.items()})

    def train_step(self, exe, program, feed, fetch_list=None, **kw):
        """Run one step, fetching table grads alongside the user fetches
        and pushing them to the apply thread."""
        fetch_list = list(fetch_list or [])
        n_user = len(fetch_list)
        tables = list(self._grad_of)
        outs = exe.run(
            program, feed=feed,
            fetch_list=fetch_list + [self._grad_of[t] for t in tables],
            **kw,
        )
        self.push({t: np.asarray(g) for t, g in zip(tables, outs[n_user:])})
        if self._step_barrier:
            self.flush()
        return outs[:n_user]

    def flush(self):
        """Block until every pushed batch has been applied (raises if the
        apply thread died — every queued item is drained on error, so this
        cannot hang)."""
        self._q.join()
        self._check_error()

    def stop(self):
        """Stops the apply thread. Pending (un-applied) batches are
        DRAINED AND DROPPED — call flush() first to guarantee every pushed
        gradient landed (fleet.stop_worker does)."""
        self._stop.set()
        if self._thread is not None:
            self._q.put(None)  # wake
            self._thread.join(timeout=10.0)
            self._thread = None
        # drain anything the worker never consumed so a later flush()'s
        # Queue.join() cannot hang on un-task_done'd items
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
            self._q.task_done()

    # -- server side --------------------------------------------------------
    def _run(self):
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                self._q.task_done()
                break
            batch = [item]
            # merge up to merge_size pending batches into one apply
            # (communicator.h merge_add)
            for _ in range(self._merge_size - 1):
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._q.task_done()
                    self._stop.set()
                    break
                batch.append(nxt)
            try:
                if self._error is None:
                    merged = {}
                    for grads in batch:
                        for t, g in grads.items():
                            merged[t] = merged.get(t, 0) + g
                    for t, g in merged.items():
                        var = self._scope.find_var(t)
                        if var is None:
                            raise KeyError(
                                f"table {t!r} not found in the scope"
                            )
                        new = self._opts[t].apply(np.asarray(var).copy(), g)
                        self._scope.set_var(t, new)
                    self._applied += len(batch)
            except Exception as e:  # record + keep draining: no deadlock
                self._error = e
            finally:
                for _ in batch:
                    self._q.task_done()


class GeoCommunicator:
    """Geo-SGD periodic delta sync over the process mesh."""

    def __init__(self, table_names, scope, exe, update_frequency=10,
                 mesh=None):
        self._tables = list(table_names)
        self._scope = scope
        self._exe = exe
        self._k = int(update_frequency)
        self._mesh = mesh
        self._step = 0
        self._base = {
            t: np.asarray(scope.find_var(t)).copy() for t in self._tables
        }
        self._sync_prog = None

    def _build_sync_program(self):
        import jax

        import paddle_tpu as fluid
        from paddle_tpu import layers

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            outs = []
            for t in self._tables:
                shape = list(np.asarray(self._base[t]).shape)
                cur = fluid.data(f"cur__{t}", shape)
                base = fluid.data(f"base__{t}", shape)
                delta = cur - base
                # under a mesh, feeds replicate over each process's local
                # devices, so the mesh-wide psum would count every
                # process's delta local_device_count times. Pre-scaling by
                # 1/THIS process's local count makes each process
                # contribute exactly once REGARDLESS of how many devices
                # other processes hold (the r3 post-divide assumed
                # identical local counts on every process — VERDICT r3
                # weak item 8). With no mesh the allreduce is an identity
                # and the scale is 1.
                denom = jax.local_device_count() if self._mesh is not None \
                    else 1.0
                delta = layers.scale(delta, scale=1.0 / denom)
                blk = prog.global_block
                summed = blk.create_var(
                    name=f"sum_delta__{t}", shape=shape, dtype="float32"
                )
                blk.append_op(
                    "c_allreduce_sum",
                    {"X": [delta.name]},
                    {"Out": [summed.name]},
                    {"ring_id": 0, "use_calc_stream": True},
                )
                outs.append(base + blk.var(summed.name))
        if self._mesh is not None:
            from ..parallel.spmd import shard_program

            shard_program(prog, self._mesh)
        return prog, outs

    def maybe_sync(self):
        """Call once per train step; performs the delta exchange every
        update_frequency steps. Returns True when a sync ran."""
        self._step += 1
        if self._step % self._k:
            return False
        if self._sync_prog is None:
            self._sync_prog = self._build_sync_program()
        prog, outs = self._sync_prog
        feed = {}
        for t in self._tables:
            feed[f"cur__{t}"] = np.asarray(self._scope.find_var(t))
            feed[f"base__{t}"] = self._base[t]
        news = self._exe.run(prog, feed=feed, fetch_list=list(outs))
        for t, new in zip(self._tables, news):
            arr = np.asarray(new)
            self._scope.set_var(t, arr)
            self._base[t] = arr.copy()
        return True
