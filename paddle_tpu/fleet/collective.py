"""Fleet collective mode: data-parallel (and hybrid) training over a mesh.

Reference parity: incubate/fleet/collective/__init__.py — Collective(Fleet)
:64, CollectiveOptimizer :384, DistributedStrategy :334. TPU-native design:
`minimize` builds fwd+bwd+update as usual, then

  1. inserts per-grad `c_allreduce_sum` ops (parallel/transpiler.py), the
     analog of the reference's GradAllReduce transpile;
  2. attaches the device Mesh to the Program and shards every feed variable's
     batch dim over the "dp" axis — replacing ParallelExecutor's feed-split
     (FeedAndSplitTensorIntoLocalScopes) and param broadcast
     (BCastParamsToDevices, parallel_executor.cc:570);
  3. the Executor then runs the block under shard_map: gradients allreduce
     over ICI, parameters stay replicated.

Strategies the reference toggles by hand (nccl_comm_num, hierarchical
allreduce, fuse_all_reduce) are XLA scheduler concerns here and intentionally
accepted-and-ignored for API compatibility.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from ..framework.program import default_main_program, default_startup_program
from ..parallel.mesh import DATA_AXIS, make_mesh
from ..parallel.transpiler import GradAllReduce


class DistributedStrategy:
    """Knob bag (reference :334). XLA makes most of these no-ops; kept for
    source compatibility and for the ones that DO change the program."""

    def __init__(self):
        self.nccl_comm_num = 1  # ignored: XLA owns collective scheduling
        self.use_hierarchical_allreduce = False  # ignored: mesh expresses it
        self.hierarchical_allreduce_inter_nranks = 8
        self.fuse_all_reduce_ops = True  # ignored: XLA collective combiner
        self.local_sgd = False
        self.local_sgd_steps = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2.0**15
        self.mesh_axes = None  # {axis: size}; default all-dp
        self.sharding = {}  # extra var->spec annotations (TP etc.)
        # ZeRO-style cross-replica weight-update sharding
        # (arXiv:2004.13336): per-grad allreduce becomes reduce-scatter +
        # shard-local optimizer update + param all-gather; optimizer state
        # is 1/dp per rank (parallel/transpiler.py ShardedWeightUpdate)
        self.shard_weight_update = False
        # opt-in block-quantized collectives for the sharded update
        # (arXiv:2506.17615 EQuARX): None/"none" = full precision wire,
        # "int8" = int8 blocks with per-block fp32 scales, fp32 accumulate
        self.collective_quant = None
        self.collective_quant_block = 256
        # communication/compute overlap (ROADMAP item 4): gradient
        # collectives group into size-targeted buckets in reverse-
        # topological order and fire as soon as each bucket's last member
        # gradient is produced — one collective per bucket instead of one
        # per grad, its wire time hidden behind the remaining backward.
        # Applies to BOTH dp paths (bucketed c_allreduce and bucketed
        # zero_reduce_scatter); fp32 results are bitwise-identical to the
        # per-grad schedule. 0/None = per-grad (the serialized schedule).
        self.collective_bucket_mb = 25.0
        # prefetched all-gathers (sharded update only): hoist each param's
        # shard update + zero_all_gather to fire as soon as its grad shard
        # is ready, so the next layer's params are in flight while the
        # current layer computes
        self.collective_prefetch = True


_CHECKPOINT_PREFIX = "__paddle_checkpoint__"
_TRAIN_STATUS_FILE = "train_status.json"
_COMMIT_FILE = "commit.json"
_RANK_PREFIX = "rank_"
#: marker of a tiered (delta) checkpoint dir: {"base_checkpoint_no": M,
#: "chain_len": K} — the payload holds only arrays/rows changed since M;
#: load walks the base chain back to the nearest full save. Absent = full.
_DELTA_FILE = "delta.json"
#: auxiliary (non-scope) checkpoint payload — e.g. the embedding engine's
#: flushed host cold stores — published atomically alongside the
#: replicated payload with its own CRC manifest; load_check_point(...,
#: load_aux=True) returns it on ``status.aux``.
_AUX_FILE = "__aux__.npz"
_AUX_MANIFEST = "aux_manifest.json"

#: bytes/second buckets for the checkpoint.save_bandwidth histogram
#: (1 MB/s .. 10 GB/s)
_BANDWIDTH_BUCKETS = (
    1e6, 2.5e6, 5e6, 1e7, 2.5e7, 5e7, 1e8, 2.5e8, 5e8, 1e9, 2.5e9, 5e9,
    1e10,
)


def _as_touch(heartbeat):
    """Normalize a liveness argument — a health.Heartbeat, any zero-arg
    callable, or None — into a touch callback for LivenessPulse."""
    if heartbeat is None:
        return None
    if callable(heartbeat) and not hasattr(heartbeat, "touch"):
        return heartbeat
    return heartbeat.touch


def _dir_bytes(path):
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


class CheckpointSnapshot:
    """Immutable host staging of one checkpoint — the output of the async
    snapshot stage and the input of the publish stage. `arrays` is the
    replicated persistable payload, `local_arrays` this rank's
    ``local_vars`` shard payload (dim-0 slice keys included for
    cross-process-sharded values), `aux` the optional auxiliary payload,
    `status` a frozen TrainStatus copy."""

    __slots__ = ("arrays", "local_arrays", "aux", "status")

    def __init__(self, arrays, local_arrays=None, aux=None, status=None):
        self.arrays = dict(arrays)
        self.local_arrays = dict(local_arrays or {})
        self.aux = dict(aux) if aux else None
        self.status = status

    def nbytes(self):
        import numpy as np

        return int(sum(
            np.asarray(a).nbytes
            for payload in (self.arrays, self.local_arrays, self.aux or {})
            for a in payload.values()
        ))

    def _replace_payloads(self, arrays, aux):
        """A view of this snapshot with delta-filtered replicated/aux
        payloads (the publish stage's working copy)."""
        return CheckpointSnapshot(arrays, self.local_arrays, aux,
                                  self.status)

#: Schema version written into train_status.json / commit.json. v1 was the
#: bare ``{"epoch_no": N}`` payload; v2 adds global step, per-program RNG
#: state, AMP loss-scale state, TrainGuard counters, the data-pipeline
#: cursor, and the per-rank shard + commit-record layout. v1 files still
#: load (missing fields keep their defaults).
TRAIN_STATUS_VERSION = 2


def _rank_dir_name(rank):
    return f"{_RANK_PREFIX}{int(rank)}"


#: npz-key marker for a process-local dim-0 slice of a cross-process-
#: sharded persistable: "<var name>@@off<global dim0 start>"
_SLICE_MARK = "@@off"


def _local_dim0_slices(name, value):
    """{key: np.ndarray} for every dim-0 slice of `value` addressable from
    this process (deduped: replicated-over-submesh shards repeat). Only
    dim-0 sharding is expressible in the ``@@off<start>`` key layout —
    anything else (e.g. a TP column-parallel persistable) would collapse
    distinct shards onto one key and silently drop data, so it refuses."""
    import numpy as np

    out = {}
    for sh in value.addressable_shards:
        idx = tuple(sh.index)
        for d, s in enumerate(idx[1:], start=1):
            if isinstance(s, slice) and not (
                s.start in (None, 0)
                and s.stop in (None, int(value.shape[d]))
            ):
                raise ValueError(
                    f"local_vars persistable {name!r} is sharded over "
                    f"dim {d}; per-rank checkpoint slices support dim-0 "
                    "sharding only (the ZeRO flat-state layout)"
                )
        start = 0
        if idx and isinstance(idx[0], slice):
            start = int(idx[0].start or 0)
        out[f"{name}{_SLICE_MARK}{start}"] = np.asarray(sh.data)
    return out


def _overlay_slice(scope, key, arr):
    """Write a persisted dim-0 slice back over the startup-initialized
    full-shape value (the inverse of :func:`_local_dim0_slices`); the
    SPMD staging then slices each rank's part out again, so the untouched
    remainder is never read. Returns False when the base value is absent
    or itself not materializable host-side."""
    import jax.numpy as jnp
    import numpy as np

    name, off = key.rsplit(_SLICE_MARK, 1)
    base = scope.find_var(name)
    if base is None or not getattr(base, "is_fully_addressable", True):
        return False
    full = np.asarray(base).copy()
    start = int(off)
    full[start:start + arr.shape[0]] = arr
    scope.set_var(name, jnp.asarray(full))
    return True


def _dir_numbers(dirs):
    nos = []
    for d in dirs:
        if d.startswith(_CHECKPOINT_PREFIX):
            try:
                nos.append(int(d[len(_CHECKPOINT_PREFIX):]))
            except ValueError:
                continue  # e.g. a stale "<prefix>N.tmp" from a crashed save
    return sorted(nos)


def _checkpoint_numbers(fs, path):
    return _dir_numbers(fs.list_dirs(path))


class Fleet:
    """Singleton facade (reference fleet_base.py)."""

    def __init__(self):
        self._role_maker = None
        self._inited = False
        self._mesh = None
        self._mesh_key = None
        self._strategy = None

    # -- lifecycle ---------------------------------------------------------
    def init(self, role_maker=None):
        from .role_maker import PaddleCloudRoleMaker

        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._inited = True
        return self

    def _require_init(self):
        if not self._inited:
            raise RuntimeError("call fleet.init(role_maker) first")

    # -- identity ----------------------------------------------------------
    def is_first_worker(self):
        self._require_init()
        return self._role_maker.is_first_worker()

    def worker_index(self):
        self._require_init()
        return self._role_maker.worker_index()

    def worker_num(self):
        self._require_init()
        return self._role_maker.worker_num()

    def is_worker(self):
        self._require_init()
        return self._role_maker.is_worker()

    def is_server(self):
        self._require_init()
        return self._role_maker.is_server()

    def barrier_worker(self):
        """Host-level barrier (reference: gloo barrier). Multi-host JAX gives
        this via a trivial collective; single host it is a no-op."""
        pass

    # -- training ----------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._require_init()
        self._strategy = strategy or DistributedStrategy()
        return CollectiveOptimizer(self, optimizer, self._strategy)

    def mesh(self, strategy=None):
        axes = (strategy or self._strategy or DistributedStrategy()).mesh_axes
        key = tuple(sorted(axes.items())) if axes else None
        if self._mesh is None or key != self._mesh_key:
            self._mesh = make_mesh(axes)
            self._mesh_key = key
        return self._mesh

    # -- io (delegates; first-worker gated like the reference) -------------
    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io

        if self.is_first_worker():
            io.save_persistables(executor, dirname, main_program)

    def save_inference_model(
        self, executor, dirname, feeded_var_names, target_vars,
        main_program=None,
    ):
        from .. import io

        if self.is_first_worker():
            io.save_inference_model(
                dirname, feeded_var_names, target_vars, executor, main_program
            )


    # -- fault-tolerant checkpointing (reference incubate/fleet/collective/
    # __init__.py:155-240: _save_train_status :155,
    # clean_redundant_check_points :205, save/load_check_point :236+) ------
    # per-rank shard + commit-record helpers -------------------------------
    def _commit_record(self, train_status, no, per_rank):
        return {
            "version": TRAIN_STATUS_VERSION,
            "checkpoint_no": int(no),
            "epoch_no": train_status._epoch_no,
            "global_step": train_status.global_step,
            # the commit only PROMISES shards that will actually be
            # published: per_rank off -> just the first worker's own
            # shard, so the load-side completeness check stays satisfied
            # for callers using the classic first-worker-only pattern
            "nranks": self.worker_num() if (per_rank and self._inited) else 1,
        }

    @staticmethod
    def _read_commit(fs, ckpt):
        """The checkpoint's commit record, or None for a pre-v2 checkpoint
        (no commit.json) or an FS backend without read_file support."""
        from ..errors import CheckpointCorruptionError

        try:
            blob = fs.read_file(os.path.join(ckpt, _COMMIT_FILE))
        except NotImplementedError:
            return None
        if blob is None:
            return None
        try:
            return json.loads(blob.decode())
        except (UnicodeDecodeError, ValueError) as e:
            raise CheckpointCorruptionError(
                f"undecodable commit record in {ckpt!r}: {e}"
            ) from e

    @staticmethod
    def _read_delta(fs, ckpt):
        """The checkpoint's delta marker ({"base_checkpoint_no": M,
        "chain_len": K}), or None for a full checkpoint (no delta.json)
        or an FS backend without read_file support."""
        from ..errors import CheckpointCorruptionError

        try:
            blob = fs.read_file(os.path.join(ckpt, _DELTA_FILE))
        except NotImplementedError:
            return None
        if blob is None:
            return None
        try:
            meta = json.loads(blob.decode())
        except (UnicodeDecodeError, ValueError) as e:
            raise CheckpointCorruptionError(
                f"undecodable delta marker in {ckpt!r}: {e}"
            ) from e
        if not isinstance(meta, dict) or "base_checkpoint_no" not in meta:
            raise CheckpointCorruptionError(
                f"malformed delta marker in {ckpt!r}: {meta!r}"
            )
        return meta

    #: hard upper bound on delta-chain length at load time. Termination
    #: is already guaranteed by the strictly-decreasing base check; this
    #: only bounds pathological hand-built chains. AsyncCheckpointer
    #: refuses full_every anywhere near it, so a legitimately published
    #: chain can never be rejected here.
    CHAIN_LIMIT = 1024

    def _resolve_chain(self, fs, path, no, limit=CHAIN_LIMIT):
        """The delta chain of checkpoint `no`, oldest-first: ``[full,
        d1, ..., no]`` (just ``[no]`` for a full checkpoint). Raises
        ResumeMismatchError when a link is missing (base rotated away)
        and CheckpointCorruptionError on a cycle/overlong chain or a
        garbled marker — either way the candidate is unloadable."""
        from ..errors import CheckpointCorruptionError, ResumeMismatchError

        chain = [int(no)]
        cur = int(no)
        while True:
            d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{cur}")
            if not fs.is_exist(d):
                raise ResumeMismatchError(
                    f"checkpoint {cur} in the delta chain of {no} under "
                    f"{path!r} is missing (base rotated away?)"
                )
            meta = self._read_delta(fs, d)
            if meta is None:
                return list(reversed(chain))
            base = int(meta["base_checkpoint_no"])
            if base >= cur or len(chain) >= limit:
                raise CheckpointCorruptionError(
                    f"delta chain of checkpoint {no} under {path!r} does "
                    f"not terminate (link {cur} -> base {base}, length "
                    f"{len(chain)})"
                )
            chain.append(base)
            cur = base

    @staticmethod
    def _collect_local_arrays(local_vars, scope=None):
        """Host payload of this rank's `local_vars`: fully-addressable
        values as plain arrays, cross-process-sharded values (ZeRO
        optimizer shards) as the dim-0 slices THIS process holds, keyed by
        their global offset (load overlays them back onto the
        startup-initialized full value)."""
        import numpy as np

        from ..framework.scope import global_scope

        scope = scope if scope is not None else global_scope()
        arrays = {}
        for v in local_vars or ():
            name = v if isinstance(v, str) else v.name
            value = scope.find_var(name)
            if value is None:
                continue
            if getattr(value, "is_fully_addressable", True):
                from .. import io as _io

                arrays[name] = _io._private_host_copy(value)
            else:
                arrays.update(_local_dim0_slices(name, value))
        return arrays

    def _write_rank_shard(self, local_dir, rank, commit, train_status,
                          local_vars, scope=None, arrays=None,
                          compress=False):
        """Materialize one ``rank_<i>/`` shard into `local_dir`: this
        rank's full TrainStatus, a commit record echoing the checkpoint it
        belongs to (the rank-coherence check compares the two on load),
        and — when `local_vars` names non-replicated persistables (sharded
        optimizer state, per-rank tables) — a CRC-manifested payload of
        their scope values. `arrays` overrides the scope read with a
        pre-collected (possibly delta-filtered) payload — the async
        publisher path, which must never touch the live scope."""
        from .. import io as _io

        shard = os.path.join(local_dir, _rank_dir_name(rank))
        os.makedirs(shard, exist_ok=True)
        with open(os.path.join(shard, _TRAIN_STATUS_FILE), "w") as f:
            json.dump(train_status.to_dict(), f)
        with open(os.path.join(shard, _COMMIT_FILE), "w") as f:
            json.dump(dict(commit, rank=int(rank)), f)
        if arrays is None and local_vars:
            arrays = self._collect_local_arrays(local_vars, scope)
        if arrays is not None:
            _io.save_arrays(shard, arrays, compress=compress)
        return shard

    def _publish_rank_shard(self, fs, path, train_status, local_vars,
                            wait_timeout, shard_arrays_fn=None,
                            compress=False):
        """Non-first-worker half of save_check_point: wait for the first
        worker to publish the replicated checkpoint whose commit record
        matches this save (same epoch/global step), then publish this
        rank's shard into it with the same tmp+mv discipline. Returns the
        checkpoint number.

        `shard_arrays_fn(dir_is_delta)` (async path) supplies the
        pre-collected shard payload — full when the matched dir is a full
        checkpoint, delta-filtered when it is a link of a delta chain, so
        the shard tier always follows the dir's own chain shape."""
        import shutil
        import tempfile
        import time as _time

        from ..errors import ExecutionTimeoutError
        from ..resilience import retry

        from ..errors import CheckpointCorruptionError

        rank = self.worker_index()
        deadline = _time.monotonic() + wait_timeout
        ckpt = no = None
        inspected = set()  # a published commit is immutable: read it once
        delay = 0.05
        while True:
            try:
                cands = list(reversed(_checkpoint_numbers(fs, path)))
            except Exception:
                cands = []  # transient listing failure: this IS a poll loop
            for cand in cands:
                if cand in inspected:
                    continue
                d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{cand}")
                try:
                    commit = self._read_commit(fs, d)
                except CheckpointCorruptionError:
                    # one rotted old commit must not wedge every future
                    # save of this rank; the scan just skips it
                    inspected.add(cand)
                    continue
                if commit is None:
                    # pre-v2 dir (immutable) — unless read_file is simply
                    # unsupported, in which case nothing can ever match
                    inspected.add(cand)
                    continue
                inspected.add(cand)
                if (commit.get("global_step") == train_status.global_step
                        and commit.get("epoch_no") == train_status._epoch_no):
                    ckpt, no = d, cand
                    break
            if ckpt is not None:
                break
            if _time.monotonic() >= deadline:
                raise ExecutionTimeoutError(
                    f"rank {rank}: no checkpoint with epoch="
                    f"{train_status._epoch_no} step="
                    f"{train_status.global_step} was published under "
                    f"{path!r} within {wait_timeout}s (is the first worker "
                    "saving with the same TrainStatus?)"
                )
            _time.sleep(delay)
            delay = min(1.0, delay * 1.5)  # don't hammer a remote namenode
        local = tempfile.mkdtemp(prefix="paddle_tpu_shard_")
        shard_tmp = os.path.join(ckpt, _rank_dir_name(rank) + ".tmp")
        shard_dst = os.path.join(ckpt, _rank_dir_name(rank))
        arrays = None
        if shard_arrays_fn is not None:
            try:
                dir_is_delta = self._read_delta(fs, ckpt) is not None
            except Exception:
                dir_is_delta = False  # unreadable marker: err toward full
            arrays = shard_arrays_fn(dir_is_delta)

        def _publish():
            from ..resilience.faults import fault_point

            fault_point("checkpoint.publish")
            if fs.is_exist(shard_dst):  # prior attempt's mv already landed
                fs.delete(shard_tmp)
                return
            src = self._write_rank_shard(
                local, rank, self._commit_record(train_status, no, True),
                train_status, local_vars, arrays=arrays, compress=compress,
            )
            fs.delete(shard_tmp)
            fs.upload(src, shard_tmp)
            fs.mv(shard_tmp, shard_dst)

        try:
            retry(
                max_attempts=4, base_delay=0.05, max_delay=2.0,
                name="checkpoint.shard",
            ).call(_publish)
        finally:
            shutil.rmtree(local, ignore_errors=True)
        return no

    def save_check_point(
        self, executor, path, train_status, main_program=None, fs=None,
        remain_all_checkpoint=False, max_checkpoint_num=3, local_vars=None,
        per_rank=None, shard_wait_timeout=120.0, snapshot=None,
        heartbeat=None, compress=False, delta_meta=None,
        shard_arrays_fn=None, max_checkpoint_bytes=None,
    ):
        """Save persistables + the full TrainStatus into a new numbered
        checkpoint dir and rotate old ones. The payload is written locally
        and published through the FS backend (upload + atomic mv), so
        remote backends only implement the FS contract; write + publish
        are retried with backoff (transient FS faults heal, the final
        state is idempotent).

        Per-rank decomposition (``per_rank=True``, or implied by passing
        ``local_vars``; every rank then calls this): replicated state —
        the persistables plus the first worker's TrainStatus — is written
        ONCE by the first worker, together with a ``commit.json`` record
        naming the checkpoint number, global step, and world size; every
        rank (first worker included) then publishes a ``rank_<i>/`` shard
        carrying its own TrainStatus (data cursor, RNG position) and any
        `local_vars` — non-replicated per-rank persistables (sharded
        optimizer state, PS tables). Non-first workers wait (up to
        `shard_wait_timeout`) for the matching replicated publish before
        attaching their shard. With ``per_rank`` off (the default), the
        classic contract holds: non-first workers return None immediately
        and the commit promises only the first worker's shard — existing
        first-worker-only call sites (TrainGuard's preemption drain,
        epoch-boundary saves) keep their exact old behavior.

        The just-published checkpoint is spot-verified (manifest/CRC
        readback) BEFORE predecessors rotate away, so a bad publish can
        never leave zero loadable checkpoints. Returns the checkpoint
        number.

        Async/tiered extensions (all optional; the classic synchronous
        contract is the default): `snapshot` is a pre-collected
        :class:`CheckpointSnapshot` — the publish then never touches the
        live scope, which is how the async publisher runs this whole
        method off the step loop; `heartbeat` (a health.Heartbeat or any
        zero-arg callable) is pulsed for the duration of the save so a
        slow publish never reads as a hung step; `compress` writes
        zlib-compressed payloads; `delta_meta` marks the dir as a delta
        link ({"base_checkpoint_no": M, "chain_len": K}) whose payload
        holds only changed arrays/rows — rotation then spares every chain
        ancestor a surviving delta still needs.

        Storage fault domain: the save first consults
        ``resilience.storage.require_writable("checkpoint")`` — at
        CRITICAL pressure it refuses with a typed StorageExhaustedError
        before touching the FS. `max_checkpoint_bytes` adds a BYTES
        budget to rotation (local backends; remote dirs measure 0 and
        opt out): oldest checkpoints beyond the budget rotate even
        inside `max_checkpoint_num`, with the chain-ancestor and
        completeness sparing below still overriding — durability
        invariants outrank the budget."""
        import tempfile
        import time as _time

        from .fs_wrapper import LocalFS
        from .. import io as _io
        from .. import observability as _obs
        from ..errors import CheckpointCorruptionError
        from ..resilience import retry
        from ..resilience.faults import fault_point
        from ..resilience.health import LivenessPulse

        from ..resilience import storage as _storage_domain

        # the CRITICAL-rung gate: refuse before any FS work, on every
        # rank (a shard publish is a durable write too)
        _storage_domain.require_writable("checkpoint")
        fs = fs or LocalFS()
        if per_rank is None:
            per_rank = local_vars is not None
        if snapshot is not None and train_status is None:
            train_status = snapshot.status
        if not self.is_first_worker():
            if not per_rank:
                return None
            if snapshot is not None and shard_arrays_fn is None:
                # even an EMPTY dict must flow through (never None): a
                # None payload makes _write_rank_shard re-collect from
                # the LIVE scope on whatever thread runs the publish
                shard_arrays_fn = lambda _delta: (  # noqa: E731
                    dict(snapshot.local_arrays) if local_vars else None
                )
            with LivenessPulse(_as_touch(heartbeat)):
                return self._publish_rank_shard(
                    fs, path, train_status, local_vars, shard_wait_timeout,
                    shard_arrays_fn=shard_arrays_fn, compress=compress,
                )
        import shutil

        def _prepare():
            # the scan prelude hits the FS too (fs.mkdir / fs.list_dirs
            # seams): a flaky remote listing must heal, not fail the save
            fs.mkdir(path)
            dirs = fs.list_dirs(path)
            # a *.tmp dir is a crashed prior save's half-published payload:
            # sweep it here, the only writer (list once, reuse for numbering)
            for d in dirs:
                if d.startswith(_CHECKPOINT_PREFIX) and d.endswith(".tmp"):
                    fs.delete(os.path.join(path, d))
            return _dir_numbers(dirs)

        pulse = LivenessPulse(_as_touch(heartbeat))
        pulse.__enter__()
        t_publish = _time.perf_counter()
        published_bytes = [0]
        try:
            nos = retry(
                max_attempts=4, base_delay=0.05, max_delay=2.0,
                name="checkpoint.prepare",
            ).call(_prepare)
            no = (nos[-1] + 1) if nos else 0
            ckpt = os.path.join(path, f"{_CHECKPOINT_PREFIX}{no}")
            tmp = ckpt + ".tmp"
            local = tempfile.mkdtemp(prefix="paddle_tpu_ckpt_")

            def _write_and_publish():
                fault_point("checkpoint.publish")
                # a prior attempt's mv may have landed even though it
                # REPORTED failure (remote rename applied, response lost);
                # mv onto an existing dir would nest tmp inside the live
                # checkpoint, so treat an existing ckpt as "published"
                if fs.is_exist(ckpt):
                    fs.delete(tmp)
                    return
                # local_vars travel in the per-rank shards, not the
                # replicated payload (on a cross-process mesh this process
                # could not materialize them anyway)
                if snapshot is not None:
                    _io.save_arrays(local, snapshot.arrays,
                                    compress=compress)
                    if snapshot.aux is not None:
                        _io.save_arrays(
                            local, snapshot.aux, filename=_AUX_FILE,
                            compress=compress, manifest_name=_AUX_MANIFEST,
                        )
                else:
                    _io.save_persistables(
                        executor, local, main_program,
                        exclude=[
                            v if isinstance(v, str) else v.name
                            for v in (local_vars or ())
                        ],
                        progress=_as_touch(heartbeat), compress=compress,
                    )
                with open(os.path.join(local, _TRAIN_STATUS_FILE), "w") as f:
                    json.dump(train_status.to_dict(), f)
                commit = self._commit_record(train_status, no, per_rank)
                with open(os.path.join(local, _COMMIT_FILE), "w") as f:
                    json.dump(commit, f)
                if delta_meta is not None:
                    with open(os.path.join(local, _DELTA_FILE), "w") as f:
                        json.dump(delta_meta, f)
                # the first worker's own shard rides inside the publish
                shard_arrays = None
                if shard_arrays_fn is not None:
                    shard_arrays = shard_arrays_fn(delta_meta is not None)
                elif snapshot is not None and local_vars:
                    # possibly-empty dict, never None: the publish must
                    # not re-read the live scope (see CheckpointSnapshot)
                    shard_arrays = dict(snapshot.local_arrays)
                self._write_rank_shard(
                    local, 0, commit, train_status, local_vars,
                    arrays=shard_arrays, compress=compress,
                )
                published_bytes[0] = _dir_bytes(local)
                fs.delete(tmp)
                fs.upload(local, tmp)
                # atomic publish: a crash mid-save leaves only a .tmp dir
                # behind, never a half-written numbered checkpoint
                fs.mv(tmp, ckpt)

            try:
                retry(
                    max_attempts=4, base_delay=0.05, max_delay=2.0,
                    name="checkpoint.save",
                ).call(_write_and_publish)
            finally:
                shutil.rmtree(local, ignore_errors=True)
            elapsed = _time.perf_counter() - t_publish
            _obs.observe("checkpoint.publish_latency", elapsed)
            if published_bytes[0]:
                _obs.add("checkpoint.bytes_written", published_bytes[0])
                _obs.set_gauge("checkpoint.last_payload_bytes",
                               published_bytes[0])
                if elapsed > 0:
                    _obs.observe(
                        "checkpoint.save_bandwidth",
                        published_bytes[0] / elapsed, _BANDWIDTH_BUCKETS,
                    )
            _obs.add(
                "checkpoint.delta_saves" if delta_meta is not None
                else "checkpoint.full_saves"
            )
            if not remain_all_checkpoint:
                # spot-verify the JUST-PUBLISHED checkpoint (manifest/CRC
                # readback through the backend) before deleting
                # predecessors: rotating first and verifying never could
                # leave a run with zero loadable checkpoints after one
                # bad publish
                self._verify_published(fs, ckpt)
                doomed = (nos + [no])[:-max_checkpoint_num]
                if max_checkpoint_bytes is not None:
                    # bytes-budget rotation: oldest survivors join the
                    # doomed list until the plane fits (remote dirs
                    # measure 0 bytes, so the budget no-ops there); the
                    # completeness/chain sparing below still overrides
                    survivors = [
                        n for n in (nos + [no]) if n not in doomed
                    ]
                    sizes = {
                        n: _dir_bytes(
                            os.path.join(path, f"{_CHECKPOINT_PREFIX}{n}")
                        )
                        for n in survivors
                    }
                    total = sum(sizes.values())
                    while (total > int(max_checkpoint_bytes)
                           and len(survivors) > 1):
                        victim = survivors.pop(0)
                        doomed.append(victim)
                        total -= sizes[victim]
                    doomed.sort()
                if per_rank and doomed:
                    # the new checkpoint is complete only once every PEER
                    # attached its shard (asynchronously, after this
                    # return); if no surviving checkpoint is complete yet,
                    # spare the newest complete predecessor so a peer
                    # dying before its attach can never leave zero
                    # resumable checkpoints
                    def _complete(n):
                        d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{n}")
                        try:
                            return not self._missing_shards(
                                fs, d, self._read_commit(fs, d)
                            )
                        except Exception:
                            # corrupt commit or transient scan failure:
                            # treat as not-complete (errs toward sparing
                            # more) — the save itself already succeeded, a
                            # completeness probe must not turn it into a
                            # failure
                            return False

                    survivors = [
                        n for n in (nos + [no]) if n not in doomed
                    ]
                    if not any(_complete(n) for n in survivors):
                        spared = next(
                            (n for n in reversed(doomed) if _complete(n)),
                            None,
                        )
                        doomed = [n for n in doomed if n != spared]
                if doomed:
                    # a surviving delta's chain must stay loadable: spare
                    # every ancestor some survivor still reaches (they
                    # rotate once the next full save supersedes the chain)
                    survivors = [
                        n for n in (nos + [no]) if n not in doomed
                    ]
                    required = set()
                    for n in survivors:
                        try:
                            required.update(
                                self._resolve_chain(fs, path, n)
                            )
                        except Exception:
                            # already-broken chain: nothing to protect
                            pass
                    doomed = [n for n in doomed if n not in required]
                for old in doomed:
                    fs.delete(
                        os.path.join(path, f"{_CHECKPOINT_PREFIX}{old}")
                    )
        finally:
            pulse.__exit__(None, None, None)
        return no

    @staticmethod
    def _verify_published(fs, ckpt):
        """Readback-verify a published checkpoint via the FS backend; a
        failure raises CheckpointCorruptionError (and the caller skips
        rotation, keeping the older checkpoints loadable)."""
        import shutil
        import tempfile

        from .. import io as _io
        from .. import observability as _obs
        from ..resilience import retry

        def _readback():
            local = tempfile.mkdtemp(prefix="paddle_tpu_verify_")
            try:
                try:
                    # only the replicated payload + manifest are verified:
                    # fetching the rank shards too would roughly double the
                    # readback bytes on every rotation-enabled save
                    for fname in ("__params__.npz", _io.MANIFEST_NAME):
                        fs.download(
                            os.path.join(ckpt, fname),
                            os.path.join(local, fname),
                        )
                except Exception:
                    # backend without single-file download: whole dir
                    shutil.rmtree(local, ignore_errors=True)
                    os.makedirs(local, exist_ok=True)
                    fs.download(ckpt, local)
                _io.verify_checkpoint_dir(local)
            finally:
                shutil.rmtree(local, ignore_errors=True)

        try:
            # transient download faults heal; actual corruption
            # (CheckpointCorruptionError) is non-retryable and surfaces
            retry(
                max_attempts=3, base_delay=0.05, max_delay=1.0,
                name="checkpoint.verify",
            ).call(_readback)
        except Exception:
            _obs.add("resilience.checkpoint_publish_verify_failures")
            raise
        _obs.add("resilience.checkpoint_publish_verified")

    @staticmethod
    def _scan_retry():
        """Shared retry policy for read-side FS scans (list_dirs carries a
        fault seam now, and a flaky remote listing must heal on the load
        path exactly as it does in the save prelude)."""
        from ..resilience import retry

        return retry(
            max_attempts=3, base_delay=0.05, max_delay=1.0,
            name="checkpoint.scan",
        )

    def has_check_point(self, path, fs=None):
        """Whether at least one numbered checkpoint exists under `path` —
        distinguishes 'load would be a cold start' from 'load would
        restore real weights' (TrainGuard's rollback gate; a loaded
        checkpoint can legitimately carry TrainStatus(-1))."""
        from .fs_wrapper import LocalFS

        fs = fs or LocalFS()
        return bool(
            fs.is_exist(path)
            and self._scan_retry().call(_checkpoint_numbers, fs, path)
        )

    def _missing_shards(self, fs, ckpt, commit):
        """Rank dirs the checkpoint's commit record promises but that are
        absent — a save interrupted between the replicated publish and the
        last rank's shard upload. [] for complete or pre-v2 checkpoints."""
        if not commit:
            return []
        present = set(fs.list_dirs(ckpt))
        return [
            _rank_dir_name(i)
            for i in range(int(commit.get("nranks", 1)))
            if _rank_dir_name(i) not in present
        ]

    def _fetch_for_rank(self, fs, ckpt, local, tid, commit, with_aux=False):
        """Stage the slice of a checkpoint THIS rank needs: the replicated
        top-level files plus its own ``rank_<tid>/`` shard. Skipping the
        peers' shards keeps resume traffic O(shard) per rank instead of
        O(nranks * shard) — across the pod, linear instead of quadratic.
        Backends that cannot fetch single paths fall back to the whole
        directory. `with_aux` additionally stages the auxiliary payload
        (fetched only on request: it can be embedding-table sized)."""
        import shutil

        if not commit or int(commit.get("nranks", 1)) <= 1:
            fs.download(ckpt, local)
            return
        try:
            os.makedirs(local, exist_ok=True)
            from .. import io as _io

            fnames = ("__params__.npz", _io.MANIFEST_NAME,
                      _TRAIN_STATUS_FILE, _COMMIT_FILE, _DELTA_FILE)
            if with_aux:
                fnames += (_AUX_FILE, _AUX_MANIFEST)
            for fname in fnames:
                src = os.path.join(ckpt, fname)
                if fs.is_exist(src):
                    fs.download(src, os.path.join(local, fname))
            shard = os.path.join(ckpt, _rank_dir_name(tid))
            if fs.is_exist(shard):
                fs.download(shard, os.path.join(local, _rank_dir_name(tid)))
        except Exception:
            shutil.rmtree(local, ignore_errors=True)
            os.makedirs(local, exist_ok=True)
            fs.download(ckpt, local)

    def _load_rank_shard(self, locals_, trainer_id, dir_commit):
        """This rank's slice of a downloaded checkpoint chain (`locals_`
        is the staged chain, oldest-first; a full checkpoint is a chain of
        one): verify the newest shard's commit record against the
        checkpoint-level one (the rank-coherence check — a shard that
        belongs to a different checkpoint number or global step means the
        ranks would silently train on different timelines), merge the
        per-rank payloads along the chain, overlay the result onto the
        scope, and return the newest shard's TrainStatus. None when the
        checkpoint predates shards or this rank joined after the save
        (elastic resize)."""
        import jax.numpy as jnp

        from ..errors import ResumeMismatchError
        from .. import io as _io

        newest = os.path.join(locals_[-1], _rank_dir_name(trainer_id))
        if not os.path.isdir(newest):
            return None
        commit_file = os.path.join(newest, _COMMIT_FILE)
        if dir_commit is not None and os.path.exists(commit_file):
            with open(commit_file) as f:
                shard_commit = json.load(f)
            for field in ("checkpoint_no", "global_step"):
                if shard_commit.get(field) != dir_commit.get(field):
                    from .. import observability as _obs

                    _obs.add("resilience.resume_mismatches")
                    raise ResumeMismatchError(
                        f"rank {trainer_id} shard disagrees with its "
                        f"checkpoint on {field}: shard has "
                        f"{shard_commit.get(field)!r}, commit record has "
                        f"{dir_commit.get(field)!r} — refusing a resume "
                        "that would silently diverge the ranks"
                    )
        merged = {}
        have_payload = False
        for local in locals_:
            payload = os.path.join(
                local, _rank_dir_name(trainer_id), "__params__.npz"
            )
            if os.path.exists(payload):
                have_payload = True
                _io.merge_checkpoint_arrays(
                    merged, _io._load_npz_verified(payload), payload
                )
        if have_payload:
            from .. import observability as _obs
            from ..framework.scope import global_scope

            scope = global_scope()
            for name, arr in merged.items():
                if _SLICE_MARK in name:
                    if not _overlay_slice(scope, name, arr):
                        _obs.add("resilience.shard_overlay_skipped")
                    continue
                scope.set_var(name, jnp.asarray(arr))
        status_file = os.path.join(newest, _TRAIN_STATUS_FILE)
        if os.path.exists(status_file):
            with open(status_file) as f:
                return TrainStatus.from_dict(json.load(f))
        return None

    @staticmethod
    def _read_aux_chain(locals_):
        """Chain-merged auxiliary payload of a staged checkpoint chain
        (oldest-first), or None when no link carries one."""
        from .. import io as _io

        merged = {}
        found = False
        for local in locals_:
            p = os.path.join(local, _AUX_FILE)
            if os.path.exists(p):
                found = True
                _io.merge_checkpoint_arrays(
                    merged,
                    _io._load_npz_verified(
                        p, os.path.join(local, _AUX_MANIFEST)
                    ),
                    p,
                )
        return merged if found else None

    def load_check_point(
        self, executor, path, trainer_id=None, main_program=None, fs=None,
        checkpoint_no=None, load_aux=False,
    ):
        """Load the newest (or requested) checkpoint via the FS backend;
        returns its TrainStatus. Missing dir -> TrainStatus(-1) (cold
        start, reference behavior).

        Candidate selection skips checkpoints that fail integrity
        verification (CheckpointCorruptionError from io.py's manifest/CRC
        check), checkpoints whose commit record promises rank shards
        that never landed (a save interrupted after the replicated
        publish), AND delta checkpoints whose base chain is broken or
        incomplete (``resilience.checkpoint_chain_broken``) — every rank
        walks the same FS view newest-first, so all ranks settle on the
        same newest COMPLETE checkpoint instead of silently diverging. An
        explicitly requested checkpoint_no never falls back: corruption
        raises CheckpointCorruptionError, incompleteness raises
        ResumeMismatchError.

        A delta candidate is reconstructed by loading its full base and
        overlaying each chain link's changed arrays/rows, then applied to
        the scope in one pass. `load_aux=True` additionally stages the
        auxiliary payload chain (e.g. embedding host stores) and returns
        it on ``status.aux``.

        When this rank's ``rank_<i>/`` shard is present its commit record
        must match the checkpoint's (number + global step) — mismatch
        raises ResumeMismatchError — and the returned TrainStatus is the
        shard's (per-rank cursor and RNG position), with any per-rank
        payload overlaid on the scope after the replicated load."""
        import tempfile

        from ..errors import CheckpointCorruptionError, ResumeMismatchError
        from .fs_wrapper import LocalFS
        from .. import io as _io

        fs = fs or LocalFS()
        tid = trainer_id
        if tid is None:
            tid = self.worker_index() if self._inited else 0
        nos = (
            self._scan_retry().call(_checkpoint_numbers, fs, path)
            if fs.is_exist(path) else []
        )
        if not nos:
            return TrainStatus(-1)
        import shutil

        candidates = (
            [checkpoint_no] if checkpoint_no is not None else list(reversed(nos))
        )
        last_err = None
        saw_my_shard = had_corruption = False
        for i, no in enumerate(candidates):
            from .. import observability as _obs

            ckpt = os.path.join(path, f"{_CHECKPOINT_PREFIX}{no}")
            try:
                remote_commit = self._read_commit(fs, ckpt)
            except CheckpointCorruptionError as e:
                # a garbled commit record is corruption like any other:
                # fall back to an older checkpoint instead of bricking
                # resume on every rank
                _obs.add("resilience.checkpoint_corrupt")
                last_err = e
                had_corruption = True
                if checkpoint_no is not None:
                    raise
                continue
            missing = self._scan_retry().call(
                self._missing_shards, fs, ckpt, remote_commit
            )
            if missing:
                _obs.add("resilience.checkpoint_incomplete")
                if _rank_dir_name(tid) not in missing:
                    saw_my_shard = True
                last_err = ResumeMismatchError(
                    f"checkpoint {no} under {path!r} is missing rank "
                    f"shards {missing} its commit record promises (save "
                    "died between the replicated publish and the last "
                    "shard upload)"
                )
                if checkpoint_no is not None:
                    raise last_err
                continue
            # a delta candidate is only loadable through its whole chain:
            # resolve it (and check every PRIOR link's completeness) before
            # fetching anything, so a broken chain falls back cheaply
            try:
                chain = self._resolve_chain(fs, path, no)
                for prior in chain[:-1]:
                    d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{prior}")
                    pm = self._scan_retry().call(
                        self._missing_shards, fs, d,
                        self._read_commit(fs, d),
                    )
                    if pm:
                        raise ResumeMismatchError(
                            f"delta chain link {prior} of checkpoint {no} "
                            f"under {path!r} is missing rank shards {pm}"
                        )
            except (CheckpointCorruptionError, ResumeMismatchError) as e:
                _obs.add("resilience.checkpoint_chain_broken")
                last_err = e
                had_corruption = True
                if checkpoint_no is not None:
                    raise
                continue
            locals_ = []
            try:
                for cno in chain:
                    d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{cno}")
                    local = tempfile.mkdtemp(prefix="paddle_tpu_ckpt_")
                    locals_.append(local)
                    self._fetch_for_rank(
                        fs, d, local, tid,
                        remote_commit if cno == no
                        else self._read_commit(fs, d),
                        with_aux=load_aux,
                    )
                # replicated payload: chain-merge host-side, apply ONCE
                merged = {}
                for local in locals_:
                    _io.merge_checkpoint_arrays(
                        merged, _io.read_persistables(local), local
                    )
                _io.apply_persistables(merged, main_program)
                if i > 0:
                    _obs.add("resilience.checkpoint_fallbacks")
                if len(chain) > 1:
                    _obs.add("resilience.checkpoint_chain_loads")
                local = locals_[-1]
                dir_commit = remote_commit
                commit_file = os.path.join(local, _COMMIT_FILE)
                if dir_commit is None and os.path.exists(commit_file):
                    # backend without read_file support: the commit rode
                    # along in the full-directory download
                    with open(commit_file) as f:
                        dir_commit = json.load(f)
                status = self._load_rank_shard(locals_, tid, dir_commit)
                if status is None:
                    status_file = os.path.join(local, _TRAIN_STATUS_FILE)
                    if os.path.exists(status_file):
                        with open(status_file) as f:
                            status = TrainStatus.from_dict(json.load(f))
                    else:
                        status = TrainStatus(-1)
                status.checkpoint_no = no
                if load_aux:
                    status.aux = self._read_aux_chain(locals_)
                if status.global_step or status.cursor:
                    # a v2 mid-run position is being restored, not a bare
                    # epoch boundary: the exact-resume path fired
                    _obs.add("resilience.resumes")
                return status
            except CheckpointCorruptionError as e:
                _obs.add("resilience.checkpoint_corrupt")
                last_err = e
                had_corruption = True
            finally:
                for local in locals_:
                    shutil.rmtree(local, ignore_errors=True)
        if (
            isinstance(last_err, ResumeMismatchError)
            and not saw_my_shard and not had_corruption
        ):
            # every candidate was merely incomplete and NONE of them holds
            # this rank's shard: this rank never completed a save, so there
            # is nothing to resume — a cold start, not an error. (The
            # common shape: a peer published the replicated payload while
            # this rank was still starting up and hadn't attached yet.)
            from .. import observability as _obs

            _obs.add("resilience.resume_cold_starts")
            return TrainStatus(-1)
        raise last_err



class TrainStatus:
    """Checkpoint metadata, v2: the FULL training-loop position, not just
    the last finished epoch (reference :49 stored only that, which made a
    resumed run replay the interrupted epoch from example 0 with fresh RNG
    streams — elastic restart silently changed what the model trained on).

    Fields beyond ``epoch_no``:

    * ``global_step`` — optimizer updates applied so far;
    * ``rng`` — :meth:`Program.rng_state` (seed mode, per-run step
      counter, unseeded-program nonce) so replayed steps draw the same
      dropout masks;
    * ``amp`` — ``OptimizerWithMixedPrecision.state_dict()`` (dynamic
      loss scale + good/bad step counters);
    * ``guard`` — ``TrainGuard.state_dict()`` (bad-step counters and the
      spent rollback budget);
    * ``cursor`` — ``DataLoader.state_dict()`` (epoch + batches consumed
      + sampler seed), the resumable-input-pipeline position.

    :meth:`capture` fills them from live objects; :meth:`restore` applies
    them back after ``load_check_point``. Serialization is versioned:
    v1 payloads (bare ``{"epoch_no": N}``) load with defaulted v2 fields.
    Equality stays epoch-based (the v1 contract callers rely on)."""

    def __init__(self, epoch_no=-1, global_step=0, rng=None, amp=None,
                 guard=None, cursor=None):
        self._epoch_no = epoch_no
        self.global_step = int(global_step)
        self.rng = dict(rng) if rng else {}
        self.amp = dict(amp) if amp else {}
        self.guard = dict(guard) if guard else {}
        self.cursor = dict(cursor) if cursor else {}
        self.checkpoint_no = None  # set by load_check_point
        # auxiliary (non-scope) payload — e.g. embedding host stores —
        # populated by load_check_point(load_aux=True); never serialized
        # into train_status.json (it is array data, not metadata)
        self.aux = None

    @property
    def epoch_no(self):
        return self._epoch_no

    def next(self):
        return self._epoch_no + 1

    # -- capture / restore -------------------------------------------------
    @classmethod
    def capture(cls, epoch_no=-1, global_step=0, program=None, amp=None,
                guard=None, loader=None, scope=None):
        """Snapshot the full training-loop state from live objects. Every
        source is optional — pass what the loop uses; omitted parts stay
        empty and restore as no-ops."""
        return cls(
            epoch_no,
            global_step=global_step,
            rng=program.rng_state() if program is not None else None,
            amp=amp.state_dict(scope=scope) if amp is not None else None,
            guard=guard.state_dict() if guard is not None else None,
            cursor=loader.state_dict() if loader is not None else None,
        )

    def restore(self, program=None, amp=None, guard=None, loader=None,
                scope=None):
        """Apply the captured state back onto live objects (call after
        ``load_check_point`` repopulated the scope). Empty parts — e.g.
        from a v1 checkpoint — are no-ops. Returns self for chaining."""
        if program is not None:
            program.set_rng_state(self.rng)
        if amp is not None:
            amp.load_state_dict(self.amp, scope=scope)
        if guard is not None:
            guard.load_state_dict(self.guard)
        if loader is not None and self.cursor:
            loader.load_state_dict(self.cursor)
        return self

    # -- versioned serialization --------------------------------------------
    def to_dict(self):
        return {
            "version": TRAIN_STATUS_VERSION,
            "epoch_no": self._epoch_no,
            "global_step": self.global_step,
            "rng": self.rng,
            "amp": self.amp,
            "guard": self.guard,
            "cursor": self.cursor,
        }

    @classmethod
    def from_dict(cls, payload):
        from ..errors import CheckpointCorruptionError

        if not isinstance(payload, dict):
            raise CheckpointCorruptionError(
                f"train status payload is not a dict: {type(payload).__name__}"
            )
        version = int(payload.get("version", 1))
        if version > TRAIN_STATUS_VERSION:
            raise CheckpointCorruptionError(
                f"train status version {version} is newer than this build "
                f"understands (max {TRAIN_STATUS_VERSION}); refusing a "
                "lossy partial load"
            )
        return cls(
            payload.get("epoch_no", -1),
            global_step=payload.get("global_step", 0),
            rng=payload.get("rng"),
            amp=payload.get("amp"),
            guard=payload.get("guard"),
            cursor=payload.get("cursor"),
        )

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and self._epoch_no == other._epoch_no

    def __ne__(self, other):
        # the reference defined __eq__ only, so `status != other` fell back
        # to identity on py2-style consumers; keep the pair consistent
        return not self.__eq__(other)

    def __repr__(self):
        extra = f", global_step={self.global_step}" if self.global_step else ""
        return f"TrainStatus(epoch_no={self._epoch_no}{extra})"


class PendingSave:
    """Handle for one queued async save. :meth:`result` blocks until this
    snapshot — or a newer one that superseded it via coalescing — is
    durably committed, and returns the checkpoint number. A cancelled
    save (rollback quiesce) raises UnavailableError; a failed publish
    re-raises the publisher's error."""

    __slots__ = ("checkpoint_no", "error", "cancelled", "is_full",
                 "snapshot", "row_marks", "ctx", "_event", "_successor",
                 "_fp_proposals")

    def __init__(self):
        self._event = threading.Event()
        self.checkpoint_no = None
        self.error = None
        self.cancelled = False
        self.is_full = True
        self.snapshot = None
        self.row_marks = {}
        # TraceContext captured on the saving thread: the publisher
        # activates it so the publish span parents under the save that
        # actually publishes (a coalesced-away save's context dies with
        # its snapshot — the surviving save owns the publish)
        self.ctx = None
        self._successor = None
        self._fp_proposals = []

    def done(self):
        p = self
        while p._successor is not None:
            p = p._successor
        return p._event.is_set()

    def result(self, timeout=None):
        from ..errors import ExecutionTimeoutError, UnavailableError

        deadline = None if timeout is None else time.monotonic() + timeout
        p = self
        while True:
            remaining = (
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            if not p._event.wait(remaining):
                raise ExecutionTimeoutError(
                    "async checkpoint publish did not finish within "
                    f"{timeout}s"
                )
            if p._successor is not None:
                # coalesced: durability is carried by the newer snapshot
                p = p._successor
                continue
            if p.cancelled:
                raise UnavailableError(
                    "async checkpoint save was cancelled before publish "
                    "(rollback quiesce dropped the queued snapshot)"
                )
            if p.error is not None:
                raise p.error
            return p.checkpoint_no


class AsyncCheckpointer:
    """Async snapshot/publish checkpoint pipeline — take the save stall
    off the step loop (ROADMAP item 5).

    :meth:`save` splits the synchronous ``Fleet.save_check_point`` stall
    into (1) a **snapshot stage** the step loop waits for — device→host
    copies of every persistable (plus this rank's ``local_vars`` and any
    auxiliary payload) into an immutable staging buffer — and (2) a
    **publish stage** on a background thread: serialize, CRC-manifest,
    temp+fsync+``os.replace`` publish, per-rank shard attach, commit
    record, readback verify, rotation — all through
    ``Fleet.save_check_point(snapshot=...)``, so sync and async saves
    share one durability contract, retry policy, and fault-seam catalog
    (plus the new ``checkpoint.snapshot`` / ``checkpoint.publish``
    seams).

    The queue is bounded: one pending snapshot behind the in-flight
    publish. ``queue_policy="coalesce"`` (default) replaces a still-
    queued snapshot with the newer one — the superseded handle resolves
    when its successor commits, so the caller's state *or newer* is
    always what lands; ``"block"`` makes :meth:`save` wait for the slot.
    Either way durability is never silently dropped: a publish failure
    re-raises from the handle, from :meth:`wait`/:meth:`close`, and from
    the next :meth:`save`.

    Tiered saves: ``delta=True`` writes, after each full save, up to
    ``full_every`` delta checkpoints carrying only arrays whose content
    CRC changed since the chain's last write — plus row-level deltas for
    names with a registered oracle in ``row_oracles`` (e.g.
    ``EmbeddingEngine.delta_row_oracles()``, keyed off the cache's
    write-back ticks), stored as ``<name>@@rows``/``<name>@@ridx``
    pairs. Per-rank shard payloads tier at array granularity, following
    the published dir's own full/delta shape. ``compress=True`` writes
    zlib-compressed payloads. ``Fleet.load_check_point`` reconstructs
    the chain (never longer than ``full_every`` deltas) and skips
    candidates whose chain is broken.

    Lifecycle: :meth:`quiesce` (cancel the queued snapshot, await the
    in-flight publish) before a TrainGuard rollback; :meth:`wait` after
    the drain save so exit-75 never leaves a half-published final
    checkpoint; :meth:`close` / context-manager exit drains the queue.
    `heartbeat` (a health.Heartbeat or zero-arg callable) is pulsed for
    the whole publish so a slow save never reads as a hung step."""

    def __init__(self, fleet, path, executor=None, main_program=None,
                 fs=None, scope=None, local_vars=None, per_rank=None,
                 max_checkpoint_num=3, remain_all_checkpoint=False,
                 queue_policy="coalesce", delta=False, full_every=4,
                 compress=False, row_oracles=None, heartbeat=None,
                 shard_wait_timeout=120.0, max_checkpoint_bytes=None):
        from ..errors import InvalidArgumentError

        if queue_policy not in ("coalesce", "block"):
            raise InvalidArgumentError(
                f"AsyncCheckpointer queue_policy={queue_policy!r}: "
                "supported: 'coalesce' | 'block'"
            )
        if not 1 <= int(full_every) <= 256:
            raise InvalidArgumentError(
                f"AsyncCheckpointer full_every must be in [1, 256], got "
                f"{full_every}: resume replays the whole delta chain, and "
                "256 links is already far past any sane RPO/replay "
                f"trade-off (the load-side cap is {Fleet.CHAIN_LIMIT})"
            )
        if row_oracles and not delta:
            raise InvalidArgumentError(
                "AsyncCheckpointer row_oracles requires delta=True: row "
                "oracles only shape delta payloads"
            )
        self._fleet = fleet
        self.path = path
        self._executor = executor
        self._main_program = main_program
        self._fs = fs
        self._scope = scope
        self._local_vars = list(local_vars) if local_vars else None
        self._per_rank = per_rank
        self._max_num = int(max_checkpoint_num)
        self._remain_all = bool(remain_all_checkpoint)
        self._queue_policy = queue_policy
        self._delta = bool(delta)
        self._full_every = int(full_every)
        self._compress = bool(compress)
        self._row_oracles = dict(row_oracles or {})
        self._heartbeat = heartbeat
        self._shard_wait_timeout = shard_wait_timeout
        self._max_bytes = max_checkpoint_bytes
        #: storage SOFT rung (resilience.storage ladder): while set,
        #: publishes are forced compressed and full-save cadence defers
        #: to delta-only (the chain's base obligation still wins)
        self._storage_degraded = False

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = None
        self._inflight = None
        self._closed = False
        self._failed = None
        self._last_no = None
        #: delta checkpoints published since the last full one (None =
        #: no full published yet → the next save must be full)
        self._published_since_full = None
        #: per-payload content fingerprints of the chain's last written
        #: value ({name: manifest entry}); an array matching its
        #: fingerprint is omitted from a delta payload
        self._fp = {"main": {}, "aux": {}, "shard": {}}
        #: per-name row-oracle marks of the last PUBLISHED save — marks
        #: advance only on publish, so a coalesced-away snapshot can
        #: never lose rows dirtied in its window
        self._row_marks = {}
        #: identity-reuse cache for snapshot_persistables: a scope value
        #: still held by the SAME object since the last snapshot (values
        #: are replaced via set_var, never mutated in place) reuses its
        #: host copy, so steady-state snapshots cost O(changed bytes)
        self._snap_cache = {}

        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ckpt-publisher"
        )
        self._thread.start()

    # -- step-loop side ----------------------------------------------------
    def save(self, train_status, aux=None):
        """Snapshot now (the only part the step loop waits for), publish
        in the background. `aux` is an optional ``{key: array}`` payload
        saved alongside the replicated payload with its own manifest
        (``load_check_point(load_aux=True)`` returns it on
        ``status.aux``). Returns a :class:`PendingSave`."""
        from .. import observability as _obs
        from ..observability import trace as _trace
        from ..resilience import retry
        from ..resilience.faults import fault_point

        with self._lock:
            self._raise_if_failed()
            if self._closed:
                raise RuntimeError("AsyncCheckpointer is closed")
            is_full = self._decide_full_locked()
        t0 = time.perf_counter()

        def _snap():
            fault_point("checkpoint.snapshot")
            return self._snapshot(train_status, aux, is_full)

        # the snapshot span files under the caller's active trace (the
        # step loop's); its context is captured onto the job so the
        # background publish — possibly seconds later, on the publisher
        # thread — parents under THIS save in the same trace
        with _obs.span("checkpoint.snapshot", category="checkpoint",
                       full=bool(is_full)):
            job = retry(
                max_attempts=3, base_delay=0.05, max_delay=1.0,
                name="checkpoint.snapshot",
            ).call(_snap)
            # inside the span the active context IS (trace, snapshot
            # span), so the publish parents under the snapshot
            job.ctx = _trace.capture()
        _obs.observe(
            "checkpoint.snapshot_latency", time.perf_counter() - t0
        )
        _obs.add("checkpoint.async_saves")
        with self._lock:
            self._raise_if_failed()
            while (
                self._pending is not None
                and (
                    self._queue_policy == "block"
                    # a row-filtered delta snapshot can never absorb a
                    # queued FULL's obligation (its payload is already
                    # rows-only); if one slipped in while we were off the
                    # lock snapshotting, wait for the publisher to take
                    # it instead of coalescing it away
                    or (self._pending.is_full and not job.is_full)
                )
                and not self._closed and self._failed is None
            ):
                self._cond.wait()
            self._raise_if_failed()
            if self._closed:
                # closed while we were snapshotting/waiting: the
                # publisher may already have drained and exited — an
                # enqueue now would be silently dropped
                raise RuntimeError("AsyncCheckpointer is closed")
            if self._pending is not None:  # coalesce (same-kind only)
                old = self._pending
                # a queued FULL save anchors the chain math — a newer
                # full (or a full-payload job) inherits the obligation;
                # the wait above guarantees old.is_full implies
                # job.is_full here
                job.is_full = job.is_full or old.is_full
                old._successor = job
                old._event.set()
                _obs.add("checkpoint.coalesced")
            self._pending = job
            self._update_pending_gauge_locked()
            self._cond.notify_all()
        return job

    def wait(self, timeout=None):
        """Block until the queue and any in-flight publish drain;
        re-raises a publish failure, else returns the newest committed
        checkpoint number (None when nothing was ever saved)."""
        from ..errors import ExecutionTimeoutError

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while (
                (self._pending is not None or self._inflight is not None)
                and self._failed is None
            ):
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise ExecutionTimeoutError(
                        "async checkpoint publish still in flight after "
                        f"{timeout}s"
                    )
                self._cond.wait(remaining)
            self._raise_if_failed()
            return self._last_no

    def quiesce(self, cancel_pending=True, timeout=None):
        """Settle the pipeline before a rollback decision: drop the
        queued (not yet started) snapshot when `cancel_pending` — it was
        captured from, or after, the state being abandoned — then await
        any in-flight publish (which is atomic: it either commits
        durably or leaves only a ``*.tmp`` dir, so awaiting is always
        safe; an uncommitted dir can never be loaded). Returns True when
        the pipeline is idle. A prior publish failure is NOT raised here
        — recovery paths must proceed to the rollback regardless."""
        from .. import observability as _obs

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            if cancel_pending and self._pending is not None:
                job = self._pending
                self._pending = None
                job.cancelled = True
                job._event.set()
                _obs.add("checkpoint.cancelled")
                self._update_pending_gauge_locked()
                self._cond.notify_all()
            while self._pending is not None or self._inflight is not None:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def close(self, timeout=None):
        """Drain the queue (anything accepted by :meth:`save` still
        publishes — durability), stop the publisher thread, re-raise any
        publish failure. Idempotent."""
        with self._lock:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        with self._lock:
            self._raise_if_failed()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            try:
                self.close()
            except Exception:
                pass  # don't mask the body's exception
        return False

    # -- internals ---------------------------------------------------------
    def _raise_if_failed(self):
        if self._failed is not None:
            raise self._failed

    def _update_pending_gauge_locked(self):
        from .. import observability as _obs

        _obs.set_gauge(
            "checkpoint.pending",
            (self._pending is not None) + (self._inflight is not None),
        )

    def _decide_full_locked(self):
        if not self._delta:
            return True
        if self._pending is not None and self._pending.is_full:
            # this snapshot may coalesce the queued FULL away; a delta
            # (row-filtered) payload cannot carry a full save's
            # obligation, so inherit it up front
            return True
        queued = [
            j for j in (self._pending, self._inflight) if j is not None
        ]
        if self._published_since_full is None and not any(
            j.is_full for j in queued
        ):
            return True  # no full anywhere in the chain yet
        if self._storage_degraded:
            # SOFT rung: delta-only while the chain has its base — a
            # full save is the most expensive write the plane makes,
            # and the full_every cadence resumes on recovery
            return False
        queued_deltas = sum(1 for j in queued if not j.is_full)
        return (
            (self._published_since_full or 0) + queued_deltas
            >= self._full_every
        )

    def set_storage_degraded(self, active):
        """Storage-pressure SOFT rung (called by
        ``resilience.storage.StoragePressureController``): while active,
        publishes are forced ``compress=True`` and the full-save cadence
        defers to delta-only. No-op churn is fine — the controller
        re-applies its rungs every poll. Requires ``delta=True`` for the
        delta-only half; a non-delta checkpointer still gains the forced
        compression."""
        from .. import observability as _obs

        with self._lock:
            changed = self._storage_degraded != bool(active)
            self._storage_degraded = bool(active)
        if changed:
            _obs.add(
                "checkpoint.storage_degraded"
                if active else "checkpoint.storage_restored"
            )

    def _snapshot(self, train_status, aux, is_full):
        from .. import io as _io

        exclude = [
            v if isinstance(v, str) else v.name
            for v in (self._local_vars or ())
        ]
        arrays = _io.snapshot_persistables(
            self._main_program, scope=self._scope, exclude=exclude,
            reuse_cache=self._snap_cache,
        )
        local_arrays = (
            self._fleet._collect_local_arrays(self._local_vars, self._scope)
            if self._local_vars else {}
        )
        aux_arrays = None
        if aux is not None:
            aux_arrays = {
                k: _io._private_host_copy(v) for k, v in aux.items()
            }
        job = PendingSave()
        job.is_full = bool(is_full)
        # row tier: each oracle names the rows dirtied since the last
        # PUBLISHED mark (e.g. embedding write-back ticks); on a delta
        # save the full array is replaced by a (rows, indices) pair.
        # local_arrays stay row-unfiltered: the shard tier follows the
        # published dir's shape, decided at publish time.
        if self._row_oracles:
            with self._lock:
                marks = dict(self._row_marks)
            for name, oracle in self._row_oracles.items():
                last = marks.get(name)
                rows, mark = oracle(last)
                job.row_marks[name] = mark
                if job.is_full or last is None or rows is None:
                    continue
                rows = np.asarray(rows, dtype=np.int64)
                for payload in (arrays, aux_arrays or {}):
                    if name in payload:
                        full = payload.pop(name)
                        payload[name + _io.ROW_VAL_MARK] = (
                            np.ascontiguousarray(full[rows])
                        )
                        payload[name + _io.ROW_IDX_MARK] = rows
        job.snapshot = CheckpointSnapshot(
            arrays, local_arrays, aux_arrays,
            TrainStatus.from_dict(train_status.to_dict()),
        )
        return job

    @staticmethod
    def _fingerprint_all(payload):
        from .. import io as _io

        return {
            name: _io._array_entry(arr)
            for name, arr in payload.items()
            if not (
                name.endswith(_io.ROW_VAL_MARK)
                or name.endswith(_io.ROW_IDX_MARK)
            )
        }

    def _filter_unchanged(self, payload, fingerprints):
        """Drop arrays whose content CRC matches the chain's last written
        value; returns (kept payload, fingerprint updates for the kept
        arrays). Row-delta pairs always pass (already minimal)."""
        from .. import io as _io
        from .. import observability as _obs

        out, fp = {}, {}
        dropped = 0
        for name, arr in payload.items():
            if name.endswith(_io.ROW_VAL_MARK) or name.endswith(
                _io.ROW_IDX_MARK
            ):
                out[name] = arr
                continue
            entry = _io._array_entry(arr)
            if fingerprints.get(name) == entry:
                dropped += int(arr.nbytes)
                continue
            out[name] = arr
            fp[name] = entry
        if dropped:
            _obs.add("checkpoint.delta_bytes_dropped", dropped)
        return out, fp

    def _publish(self, job):
        from .. import observability as _obs

        snap = job.snapshot
        delta_meta = None
        arrays, aux = snap.arrays, snap.aux
        proposals = job._fp_proposals = []
        if not job.is_full:
            with self._lock:
                base_no = self._last_no
                chain_len = (self._published_since_full or 0) + 1
            arrays, fp = self._filter_unchanged(arrays, self._fp["main"])
            proposals.append(("main", fp, False))
            if aux is not None:
                aux, fpa = self._filter_unchanged(aux, self._fp["aux"])
                proposals.append(("aux", fpa, False))
            delta_meta = {
                "base_checkpoint_no": int(base_no),
                "chain_len": int(chain_len),
            }
        else:
            proposals.append(("main", self._fingerprint_all(arrays), True))
            if aux is not None:
                proposals.append(
                    ("aux", self._fingerprint_all(aux), True)
                )

        def shard_arrays_fn(dir_is_delta):
            # the shard tier follows the published dir's shape: full
            # shard into a full dir (and reset fingerprints), delta
            # shard into a delta link. A fresh saver (elastic restart)
            # has no fingerprints and fails open to a full shard.
            # Without local_vars there is no shard payload at all;
            # WITH them, even an empty snapshot dict must flow through
            # (never None — _write_rank_shard would re-read the live
            # scope on the publisher thread)
            if self._local_vars is None:
                return None
            if not snap.local_arrays:
                return dict(snap.local_arrays)
            if not dir_is_delta:
                proposals.append(
                    ("shard", self._fingerprint_all(snap.local_arrays),
                     True)
                )
                return dict(snap.local_arrays)
            filtered, fps = self._filter_unchanged(
                snap.local_arrays, self._fp["shard"]
            )
            proposals.append(("shard", fps, False))
            return filtered

        t0 = time.perf_counter()
        no = self._fleet.save_check_point(
            self._executor, self.path, snap.status,
            main_program=self._main_program, fs=self._fs,
            remain_all_checkpoint=self._remain_all,
            max_checkpoint_num=self._max_num,
            local_vars=self._local_vars, per_rank=self._per_rank,
            shard_wait_timeout=self._shard_wait_timeout,
            snapshot=snap._replace_payloads(arrays, aux)
            if (arrays is not snap.arrays or aux is not snap.aux)
            else snap,
            heartbeat=self._heartbeat,
            compress=self._compress or self._storage_degraded,
            delta_meta=delta_meta, shard_arrays_fn=shard_arrays_fn,
            max_checkpoint_bytes=self._max_bytes,
        )
        _obs.observe(
            "checkpoint.async_publish_latency", time.perf_counter() - t0
        )
        return no

    def _run(self):
        from .. import observability as _obs
        from ..observability import trace as _trace

        while True:
            with self._lock:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                if self._pending is None:
                    break  # closed and drained
                job = self._pending
                self._pending = None
                self._inflight = job
                self._update_pending_gauge_locked()
                self._cond.notify_all()
            try:
                # activate the SURVIVING job's captured context (a
                # coalesced-away snapshot never publishes): the publish
                # span — and the liveness pulse under it — chain into
                # the saving step's trace across the thread boundary
                with _trace.activate(job.ctx), \
                        _obs.span("checkpoint.publish",
                                  category="checkpoint",
                                  full=bool(job.is_full)):
                    no = self._publish(job)
            except BaseException as e:  # noqa: BLE001 — surfaced to callers
                _obs.add("checkpoint.publish_failures")
                with self._lock:
                    self._failed = e
                    self._inflight = None
                    job.error = e
                    job._event.set()
                    p, self._pending = self._pending, None
                    if p is not None:
                        p.error = e
                        p._event.set()
                    self._update_pending_gauge_locked()
                    self._cond.notify_all()
                break
            with self._lock:
                self._inflight = None
                self._last_no = no
                if job.is_full:
                    self._published_since_full = 0
                elif self._published_since_full is not None:
                    self._published_since_full += 1
                self._row_marks.update(job.row_marks)
                for kind, fp, replace in job._fp_proposals:
                    if replace:
                        self._fp[kind] = dict(fp)
                    else:
                        self._fp[kind].update(fp)
                job.checkpoint_no = no
                job._event.set()
                self._update_pending_gauge_locked()
                self._cond.notify_all()


class CollectiveOptimizer:
    """Wraps any Optimizer; minimize() produces an SPMD data-parallel program
    (reference CollectiveOptimizer :384, but transpile → mesh+shard_map)."""

    def __init__(self, fleet, inner_opt, strategy):
        self._fleet = fleet
        self._inner = inner_opt
        self._strategy = strategy

    def backward(self, loss, **kw):
        return self._inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def _check_shardable(self):
        """shard_weight_update preconditions: grad clipping and
        regularization both read FULL-tensor gradients after the
        reduce-scatter would land (a shard-local norm silently changes
        the math), so they refuse to compose until a sharded global-norm
        path exists."""
        opt = self._inner
        seen = set()
        while opt is not None and id(opt) not in seen:
            seen.add(id(opt))
            if getattr(opt, "_grad_clip", None) is not None:
                raise NotImplementedError(
                    "shard_weight_update does not compose with grad_clip "
                    "yet: clipping norms are full-tensor reductions"
                )
            if getattr(opt, "regularization", None) is not None:
                raise NotImplementedError(
                    "shard_weight_update does not compose with "
                    "regularization yet (weight decay via AdamW is fine)"
                )
            opt = getattr(opt, "_inner", None) or getattr(
                opt, "inner_optimizer", None
            )

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        inner = self._inner
        strategy = self._strategy
        if strategy.forward_recompute:
            from ..incubate.recompute import RecomputeOptimizer

            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(strategy.recompute_checkpoints)
        if strategy.use_amp:
            from ..contrib.mixed_precision import decorate

            inner = decorate(
                inner, init_loss_scaling=strategy.amp_loss_scaling
            )

        main = loss.block.program
        startup = startup_program or default_startup_program()
        from ..framework.program import program_guard

        with program_guard(main, startup):
            params_grads = inner.backward(
                loss, startup, parameter_list, no_grad_set
            )
            mesh = self._fleet.mesh(strategy)
            if strategy.local_sgd:
                raise NotImplementedError(
                    "strategy.local_sgd: parameters are replicated under "
                    "synchronous SPMD, so LocalSGD has no effect; use "
                    "parallel.LocalSGD explicitly for multi-copy setups"
                )
            # no dp axis in the mesh -> pure model parallel, no grad allreduce
            dp = mesh.shape.get(DATA_AXIS, 1)
            sharded = bool(strategy.shard_weight_update) and dp > 1
            quant = strategy.collective_quant
            if quant not in (None, "", "none", "int8"):
                raise ValueError(
                    f"DistributedStrategy.collective_quant={quant!r} is "
                    "unknown; supported: None | 'int8'"
                )
            if quant in ("int8",) and not strategy.shard_weight_update:
                # a silently ignored knob would let users believe they
                # bought the 4x wire reduction
                raise ValueError(
                    "DistributedStrategy.collective_quant requires "
                    "shard_weight_update=True: quantized payloads exist "
                    "only on the reduce-scatter/all-gather path"
                )
            if sharded:
                self._check_shardable()
            bucket_mb = getattr(strategy, "collective_bucket_mb", 0)
            if bucket_mb and float(bucket_mb) < 0:
                raise ValueError(
                    f"DistributedStrategy.collective_bucket_mb="
                    f"{bucket_mb!r}: bucket size must be a positive MB "
                    "count (or 0/None for the per-grad schedule)"
                )
            bucket_bytes = (
                int(float(bucket_mb) * 1e6) if bucket_mb else None
            )
            if dp > 1 and not sharded:
                # the non-ZeRO data-parallel path routes through the SAME
                # bucketing machinery (one c_bucket_allreduce_sum per
                # size-targeted bucket instead of a per-grad allreduce
                # stream — fp32 bitwise-identical, far fewer dispatches)
                GradAllReduce(
                    dp, bucket_bytes=bucket_bytes
                ).transpile(main, params_grads)
                from .. import observability as _obs

                _obs.add("collective.grad_allreduce_tensors",
                         len(params_grads))
                _obs.set_gauge("collective.dp_degree", dp)
            ops = inner.apply_gradients(params_grads)
            if sharded:
                # the update ops exist now: rewrite them onto 1/dp shards
                # (reduce-scatter grads, shard-local update, param
                # all-gather) — the ZeRO transpile, bucketed + overlapped
                # per the strategy's bucket/prefetch knobs
                from ..parallel.transpiler import ShardedWeightUpdate

                ShardedWeightUpdate(
                    dp,
                    quant=strategy.collective_quant,
                    quant_block=strategy.collective_quant_block,
                    bucket_bytes=bucket_bytes,
                    prefetch=bool(
                        getattr(strategy, "collective_prefetch", True)
                    ),
                ).transpile(main, startup, params_grads)
                from .. import observability as _obs

                _obs.set_gauge("collective.dp_degree", dp)
            if dp > 1:
                # fetched metrics (loss) are shard-local means; average them
                # across dp so exe.run returns the global-batch value (the
                # reference's dist tests instead compare per-trainer losses
                # with loose tolerance — a global mean is strictly better)
                blk = main.global_block
                blk.append_op(
                    "scale",
                    inputs={"X": [loss.name]},
                    outputs={"Out": [loss.name]},
                    attrs={"scale": 1.0 / dp, "bias": 0.0},
                )
                blk.append_op(
                    "c_allreduce_sum",
                    inputs={"X": [loss.name]},
                    outputs={"Out": [loss.name]},
                    attrs={"axis_name": DATA_AXIS},
                )

        # SPMD: attach mesh; shard feed batch dims over dp. The startup
        # program stays single-device — replication to the mesh happens on
        # first main-program dispatch (jit resharding), which is exactly
        # BCastParamsToDevices (parallel_executor.cc:570) done lazily.
        main._mesh = mesh
        main._sharding.update(strategy.sharding)
        main._bump()
        for var in main.list_vars():
            if var.is_data and var.name not in main._sharding:
                main._sharding[var.name] = (DATA_AXIS,)
        return ops, params_grads


fleet = Fleet()

