"""Fleet collective mode: data-parallel (and hybrid) training over a mesh.

Reference parity: incubate/fleet/collective/__init__.py — Collective(Fleet)
:64, CollectiveOptimizer :384, DistributedStrategy :334. TPU-native design:
`minimize` builds fwd+bwd+update as usual, then

  1. inserts per-grad `c_allreduce_sum` ops (parallel/transpiler.py), the
     analog of the reference's GradAllReduce transpile;
  2. attaches the device Mesh to the Program and shards every feed variable's
     batch dim over the "dp" axis — replacing ParallelExecutor's feed-split
     (FeedAndSplitTensorIntoLocalScopes) and param broadcast
     (BCastParamsToDevices, parallel_executor.cc:570);
  3. the Executor then runs the block under shard_map: gradients allreduce
     over ICI, parameters stay replicated.

Strategies the reference toggles by hand (nccl_comm_num, hierarchical
allreduce, fuse_all_reduce) are XLA scheduler concerns here and intentionally
accepted-and-ignored for API compatibility.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..framework.program import default_main_program, default_startup_program
from ..parallel.mesh import DATA_AXIS, make_mesh
from ..parallel.transpiler import GradAllReduce


class DistributedStrategy:
    """Knob bag (reference :334). XLA makes most of these no-ops; kept for
    source compatibility and for the ones that DO change the program."""

    def __init__(self):
        self.nccl_comm_num = 1  # ignored: XLA owns collective scheduling
        self.use_hierarchical_allreduce = False  # ignored: mesh expresses it
        self.hierarchical_allreduce_inter_nranks = 8
        self.fuse_all_reduce_ops = True  # ignored: XLA collective combiner
        self.local_sgd = False
        self.local_sgd_steps = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2.0**15
        self.mesh_axes = None  # {axis: size}; default all-dp
        self.sharding = {}  # extra var->spec annotations (TP etc.)


_CHECKPOINT_PREFIX = "__paddle_checkpoint__"
_TRAIN_STATUS_FILE = "train_status.json"


def _dir_numbers(dirs):
    nos = []
    for d in dirs:
        if d.startswith(_CHECKPOINT_PREFIX):
            try:
                nos.append(int(d[len(_CHECKPOINT_PREFIX):]))
            except ValueError:
                continue  # e.g. a stale "<prefix>N.tmp" from a crashed save
    return sorted(nos)


def _checkpoint_numbers(fs, path):
    return _dir_numbers(fs.list_dirs(path))


class Fleet:
    """Singleton facade (reference fleet_base.py)."""

    def __init__(self):
        self._role_maker = None
        self._inited = False
        self._mesh = None
        self._mesh_key = None
        self._strategy = None

    # -- lifecycle ---------------------------------------------------------
    def init(self, role_maker=None):
        from .role_maker import PaddleCloudRoleMaker

        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._inited = True
        return self

    def _require_init(self):
        if not self._inited:
            raise RuntimeError("call fleet.init(role_maker) first")

    # -- identity ----------------------------------------------------------
    def is_first_worker(self):
        self._require_init()
        return self._role_maker.is_first_worker()

    def worker_index(self):
        self._require_init()
        return self._role_maker.worker_index()

    def worker_num(self):
        self._require_init()
        return self._role_maker.worker_num()

    def is_worker(self):
        self._require_init()
        return self._role_maker.is_worker()

    def is_server(self):
        self._require_init()
        return self._role_maker.is_server()

    def barrier_worker(self):
        """Host-level barrier (reference: gloo barrier). Multi-host JAX gives
        this via a trivial collective; single host it is a no-op."""
        pass

    # -- training ----------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._require_init()
        self._strategy = strategy or DistributedStrategy()
        return CollectiveOptimizer(self, optimizer, self._strategy)

    def mesh(self, strategy=None):
        axes = (strategy or self._strategy or DistributedStrategy()).mesh_axes
        key = tuple(sorted(axes.items())) if axes else None
        if self._mesh is None or key != self._mesh_key:
            self._mesh = make_mesh(axes)
            self._mesh_key = key
        return self._mesh

    # -- io (delegates; first-worker gated like the reference) -------------
    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io

        if self.is_first_worker():
            io.save_persistables(executor, dirname, main_program)

    def save_inference_model(
        self, executor, dirname, feeded_var_names, target_vars,
        main_program=None,
    ):
        from .. import io

        if self.is_first_worker():
            io.save_inference_model(
                dirname, feeded_var_names, target_vars, executor, main_program
            )


    # -- fault-tolerant checkpointing (reference incubate/fleet/collective/
    # __init__.py:155-240: _save_train_status :155,
    # clean_redundant_check_points :205, save/load_check_point :236+) ------
    def save_check_point(
        self, executor, path, train_status, main_program=None, fs=None,
        remain_all_checkpoint=False, max_checkpoint_num=3,
    ):
        """Save persistables + TrainStatus into a new numbered checkpoint
        dir and rotate old ones. The payload is written locally and
        published through the FS backend (upload + atomic mv), so remote
        backends only implement the FS contract; write + publish are
        retried with backoff (transient FS faults heal, the final state is
        idempotent). First worker only; returns the checkpoint number."""
        import tempfile

        from .fs_wrapper import LocalFS
        from .. import io as _io
        from ..resilience import retry

        fs = fs or LocalFS()
        if not self.is_first_worker():
            return None
        import shutil

        fs.mkdir(path)
        dirs = fs.list_dirs(path)
        # a *.tmp dir is a crashed prior save's half-published payload:
        # sweep it here, the only writer (list once, reuse for numbering)
        for d in dirs:
            if d.startswith(_CHECKPOINT_PREFIX) and d.endswith(".tmp"):
                fs.delete(os.path.join(path, d))
        nos = _dir_numbers(dirs)
        no = (nos[-1] + 1) if nos else 0
        ckpt = os.path.join(path, f"{_CHECKPOINT_PREFIX}{no}")
        tmp = ckpt + ".tmp"
        local = tempfile.mkdtemp(prefix="paddle_tpu_ckpt_")

        def _write_and_publish():
            # a prior attempt's mv may have landed even though it REPORTED
            # failure (remote rename applied, response lost); mv onto an
            # existing dir would nest tmp inside the live checkpoint, so
            # treat an existing ckpt as "already published"
            if fs.is_exist(ckpt):
                fs.delete(tmp)
                return
            _io.save_persistables(executor, local, main_program)
            with open(os.path.join(local, _TRAIN_STATUS_FILE), "w") as f:
                json.dump({"epoch_no": train_status._epoch_no}, f)
            fs.delete(tmp)
            fs.upload(local, tmp)
            # atomic publish: a crash mid-save leaves only a .tmp dir
            # behind, never a half-written numbered checkpoint
            fs.mv(tmp, ckpt)

        try:
            retry(
                max_attempts=4, base_delay=0.05, max_delay=2.0,
                name="checkpoint.save",
            ).call(_write_and_publish)
        finally:
            shutil.rmtree(local, ignore_errors=True)
        if not remain_all_checkpoint:
            for old in (nos + [no])[:-max_checkpoint_num]:
                fs.delete(os.path.join(path, f"{_CHECKPOINT_PREFIX}{old}"))
        return no

    def has_check_point(self, path, fs=None):
        """Whether at least one numbered checkpoint exists under `path` —
        distinguishes 'load would be a cold start' from 'load would
        restore real weights' (TrainGuard's rollback gate; a loaded
        checkpoint can legitimately carry TrainStatus(-1))."""
        from .fs_wrapper import LocalFS

        fs = fs or LocalFS()
        return bool(fs.is_exist(path) and _checkpoint_numbers(fs, path))

    def load_check_point(
        self, executor, path, trainer_id=None, main_program=None, fs=None,
        checkpoint_no=None,
    ):
        """Load the newest (or requested) checkpoint via the FS backend;
        returns its TrainStatus. Missing dir -> TrainStatus(-1) (cold
        start, reference behavior).

        When the newest checkpoint fails integrity verification
        (CheckpointCorruptionError from io.py's manifest/CRC check), falls
        back to the next-newest until one loads — never silently-wrong
        weights, and a torn latest save costs one rotation step, not the
        run. An explicitly requested checkpoint_no never falls back."""
        import tempfile

        from ..errors import CheckpointCorruptionError
        from .fs_wrapper import LocalFS
        from .. import io as _io

        fs = fs or LocalFS()
        nos = _checkpoint_numbers(fs, path) if fs.is_exist(path) else []
        if not nos:
            return TrainStatus(-1)
        import shutil

        candidates = (
            [checkpoint_no] if checkpoint_no is not None else list(reversed(nos))
        )
        last_err = None
        for i, no in enumerate(candidates):
            ckpt = os.path.join(path, f"{_CHECKPOINT_PREFIX}{no}")
            local = tempfile.mkdtemp(prefix="paddle_tpu_ckpt_")
            try:
                fs.download(ckpt, local)
                _io.load_persistables(executor, local, main_program)
                if i > 0:
                    from .. import observability as _obs

                    _obs.add("resilience.checkpoint_fallbacks")
                status_file = os.path.join(local, _TRAIN_STATUS_FILE)
                if os.path.exists(status_file):
                    with open(status_file) as f:
                        return TrainStatus(json.load(f).get("epoch_no", -1))
                return TrainStatus(-1)
            except CheckpointCorruptionError as e:
                from .. import observability as _obs

                _obs.add("resilience.checkpoint_corrupt")
                last_err = e
            finally:
                shutil.rmtree(local, ignore_errors=True)
        raise last_err



class TrainStatus:
    """Checkpoint metadata (reference :49): last finished epoch."""

    def __init__(self, epoch_no=-1):
        self._epoch_no = epoch_no

    def next(self):
        return self._epoch_no + 1

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and self._epoch_no == other._epoch_no

    def __ne__(self, other):
        # the reference defined __eq__ only, so `status != other` fell back
        # to identity on py2-style consumers; keep the pair consistent
        return not self.__eq__(other)

    def __repr__(self):
        return f"TrainStatus(epoch_no={self._epoch_no})"


class CollectiveOptimizer:
    """Wraps any Optimizer; minimize() produces an SPMD data-parallel program
    (reference CollectiveOptimizer :384, but transpile → mesh+shard_map)."""

    def __init__(self, fleet, inner_opt, strategy):
        self._fleet = fleet
        self._inner = inner_opt
        self._strategy = strategy

    def backward(self, loss, **kw):
        return self._inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        inner = self._inner
        strategy = self._strategy
        if strategy.forward_recompute:
            from ..incubate.recompute import RecomputeOptimizer

            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(strategy.recompute_checkpoints)
        if strategy.use_amp:
            from ..contrib.mixed_precision import decorate

            inner = decorate(
                inner, init_loss_scaling=strategy.amp_loss_scaling
            )

        main = loss.block.program
        startup = startup_program or default_startup_program()
        from ..framework.program import program_guard

        with program_guard(main, startup):
            params_grads = inner.backward(
                loss, startup, parameter_list, no_grad_set
            )
            mesh = self._fleet.mesh(strategy)
            if strategy.local_sgd:
                raise NotImplementedError(
                    "strategy.local_sgd: parameters are replicated under "
                    "synchronous SPMD, so LocalSGD has no effect; use "
                    "parallel.LocalSGD explicitly for multi-copy setups"
                )
            # no dp axis in the mesh -> pure model parallel, no grad allreduce
            dp = mesh.shape.get(DATA_AXIS, 1)
            if dp > 1:
                GradAllReduce(dp).transpile(main, params_grads)
                from .. import observability as _obs

                _obs.add("collective.grad_allreduce_tensors",
                         len(params_grads))
                _obs.set_gauge("collective.dp_degree", dp)
            ops = inner.apply_gradients(params_grads)
            if dp > 1:
                # fetched metrics (loss) are shard-local means; average them
                # across dp so exe.run returns the global-batch value (the
                # reference's dist tests instead compare per-trainer losses
                # with loose tolerance — a global mean is strictly better)
                blk = main.global_block
                blk.append_op(
                    "scale",
                    inputs={"X": [loss.name]},
                    outputs={"Out": [loss.name]},
                    attrs={"scale": 1.0 / dp, "bias": 0.0},
                )
                blk.append_op(
                    "c_allreduce_sum",
                    inputs={"X": [loss.name]},
                    outputs={"Out": [loss.name]},
                    attrs={"axis_name": DATA_AXIS},
                )

        # SPMD: attach mesh; shard feed batch dims over dp. The startup
        # program stays single-device — replication to the mesh happens on
        # first main-program dispatch (jit resharding), which is exactly
        # BCastParamsToDevices (parallel_executor.cc:570) done lazily.
        main._mesh = mesh
        main._sharding.update(strategy.sharding)
        main._bump()
        for var in main.list_vars():
            if var.is_data and var.name not in main._sharding:
                main._sharding[var.name] = (DATA_AXIS,)
        return ops, params_grads


fleet = Fleet()

