"""Fleet collective mode: data-parallel (and hybrid) training over a mesh.

Reference parity: incubate/fleet/collective/__init__.py — Collective(Fleet)
:64, CollectiveOptimizer :384, DistributedStrategy :334. TPU-native design:
`minimize` builds fwd+bwd+update as usual, then

  1. inserts per-grad `c_allreduce_sum` ops (parallel/transpiler.py), the
     analog of the reference's GradAllReduce transpile;
  2. attaches the device Mesh to the Program and shards every feed variable's
     batch dim over the "dp" axis — replacing ParallelExecutor's feed-split
     (FeedAndSplitTensorIntoLocalScopes) and param broadcast
     (BCastParamsToDevices, parallel_executor.cc:570);
  3. the Executor then runs the block under shard_map: gradients allreduce
     over ICI, parameters stay replicated.

Strategies the reference toggles by hand (nccl_comm_num, hierarchical
allreduce, fuse_all_reduce) are XLA scheduler concerns here and intentionally
accepted-and-ignored for API compatibility.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..framework.program import default_main_program, default_startup_program
from ..parallel.mesh import DATA_AXIS, make_mesh
from ..parallel.transpiler import GradAllReduce


class DistributedStrategy:
    """Knob bag (reference :334). XLA makes most of these no-ops; kept for
    source compatibility and for the ones that DO change the program."""

    def __init__(self):
        self.nccl_comm_num = 1  # ignored: XLA owns collective scheduling
        self.use_hierarchical_allreduce = False  # ignored: mesh expresses it
        self.hierarchical_allreduce_inter_nranks = 8
        self.fuse_all_reduce_ops = True  # ignored: XLA collective combiner
        self.local_sgd = False
        self.local_sgd_steps = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2.0**15
        self.mesh_axes = None  # {axis: size}; default all-dp
        self.sharding = {}  # extra var->spec annotations (TP etc.)
        # ZeRO-style cross-replica weight-update sharding
        # (arXiv:2004.13336): per-grad allreduce becomes reduce-scatter +
        # shard-local optimizer update + param all-gather; optimizer state
        # is 1/dp per rank (parallel/transpiler.py ShardedWeightUpdate)
        self.shard_weight_update = False
        # opt-in block-quantized collectives for the sharded update
        # (arXiv:2506.17615 EQuARX): None/"none" = full precision wire,
        # "int8" = int8 blocks with per-block fp32 scales, fp32 accumulate
        self.collective_quant = None
        self.collective_quant_block = 256


_CHECKPOINT_PREFIX = "__paddle_checkpoint__"
_TRAIN_STATUS_FILE = "train_status.json"
_COMMIT_FILE = "commit.json"
_RANK_PREFIX = "rank_"

#: Schema version written into train_status.json / commit.json. v1 was the
#: bare ``{"epoch_no": N}`` payload; v2 adds global step, per-program RNG
#: state, AMP loss-scale state, TrainGuard counters, the data-pipeline
#: cursor, and the per-rank shard + commit-record layout. v1 files still
#: load (missing fields keep their defaults).
TRAIN_STATUS_VERSION = 2


def _rank_dir_name(rank):
    return f"{_RANK_PREFIX}{int(rank)}"


#: npz-key marker for a process-local dim-0 slice of a cross-process-
#: sharded persistable: "<var name>@@off<global dim0 start>"
_SLICE_MARK = "@@off"


def _local_dim0_slices(name, value):
    """{key: np.ndarray} for every dim-0 slice of `value` addressable from
    this process (deduped: replicated-over-submesh shards repeat). Only
    dim-0 sharding is expressible in the ``@@off<start>`` key layout —
    anything else (e.g. a TP column-parallel persistable) would collapse
    distinct shards onto one key and silently drop data, so it refuses."""
    import numpy as np

    out = {}
    for sh in value.addressable_shards:
        idx = tuple(sh.index)
        for d, s in enumerate(idx[1:], start=1):
            if isinstance(s, slice) and not (
                s.start in (None, 0)
                and s.stop in (None, int(value.shape[d]))
            ):
                raise ValueError(
                    f"local_vars persistable {name!r} is sharded over "
                    f"dim {d}; per-rank checkpoint slices support dim-0 "
                    "sharding only (the ZeRO flat-state layout)"
                )
        start = 0
        if idx and isinstance(idx[0], slice):
            start = int(idx[0].start or 0)
        out[f"{name}{_SLICE_MARK}{start}"] = np.asarray(sh.data)
    return out


def _overlay_slice(scope, key, arr):
    """Write a persisted dim-0 slice back over the startup-initialized
    full-shape value (the inverse of :func:`_local_dim0_slices`); the
    SPMD staging then slices each rank's part out again, so the untouched
    remainder is never read. Returns False when the base value is absent
    or itself not materializable host-side."""
    import jax.numpy as jnp
    import numpy as np

    name, off = key.rsplit(_SLICE_MARK, 1)
    base = scope.find_var(name)
    if base is None or not getattr(base, "is_fully_addressable", True):
        return False
    full = np.asarray(base).copy()
    start = int(off)
    full[start:start + arr.shape[0]] = arr
    scope.set_var(name, jnp.asarray(full))
    return True


def _dir_numbers(dirs):
    nos = []
    for d in dirs:
        if d.startswith(_CHECKPOINT_PREFIX):
            try:
                nos.append(int(d[len(_CHECKPOINT_PREFIX):]))
            except ValueError:
                continue  # e.g. a stale "<prefix>N.tmp" from a crashed save
    return sorted(nos)


def _checkpoint_numbers(fs, path):
    return _dir_numbers(fs.list_dirs(path))


class Fleet:
    """Singleton facade (reference fleet_base.py)."""

    def __init__(self):
        self._role_maker = None
        self._inited = False
        self._mesh = None
        self._mesh_key = None
        self._strategy = None

    # -- lifecycle ---------------------------------------------------------
    def init(self, role_maker=None):
        from .role_maker import PaddleCloudRoleMaker

        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._inited = True
        return self

    def _require_init(self):
        if not self._inited:
            raise RuntimeError("call fleet.init(role_maker) first")

    # -- identity ----------------------------------------------------------
    def is_first_worker(self):
        self._require_init()
        return self._role_maker.is_first_worker()

    def worker_index(self):
        self._require_init()
        return self._role_maker.worker_index()

    def worker_num(self):
        self._require_init()
        return self._role_maker.worker_num()

    def is_worker(self):
        self._require_init()
        return self._role_maker.is_worker()

    def is_server(self):
        self._require_init()
        return self._role_maker.is_server()

    def barrier_worker(self):
        """Host-level barrier (reference: gloo barrier). Multi-host JAX gives
        this via a trivial collective; single host it is a no-op."""
        pass

    # -- training ----------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._require_init()
        self._strategy = strategy or DistributedStrategy()
        return CollectiveOptimizer(self, optimizer, self._strategy)

    def mesh(self, strategy=None):
        axes = (strategy or self._strategy or DistributedStrategy()).mesh_axes
        key = tuple(sorted(axes.items())) if axes else None
        if self._mesh is None or key != self._mesh_key:
            self._mesh = make_mesh(axes)
            self._mesh_key = key
        return self._mesh

    # -- io (delegates; first-worker gated like the reference) -------------
    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io

        if self.is_first_worker():
            io.save_persistables(executor, dirname, main_program)

    def save_inference_model(
        self, executor, dirname, feeded_var_names, target_vars,
        main_program=None,
    ):
        from .. import io

        if self.is_first_worker():
            io.save_inference_model(
                dirname, feeded_var_names, target_vars, executor, main_program
            )


    # -- fault-tolerant checkpointing (reference incubate/fleet/collective/
    # __init__.py:155-240: _save_train_status :155,
    # clean_redundant_check_points :205, save/load_check_point :236+) ------
    # per-rank shard + commit-record helpers -------------------------------
    def _commit_record(self, train_status, no, per_rank):
        return {
            "version": TRAIN_STATUS_VERSION,
            "checkpoint_no": int(no),
            "epoch_no": train_status._epoch_no,
            "global_step": train_status.global_step,
            # the commit only PROMISES shards that will actually be
            # published: per_rank off -> just the first worker's own
            # shard, so the load-side completeness check stays satisfied
            # for callers using the classic first-worker-only pattern
            "nranks": self.worker_num() if (per_rank and self._inited) else 1,
        }

    @staticmethod
    def _read_commit(fs, ckpt):
        """The checkpoint's commit record, or None for a pre-v2 checkpoint
        (no commit.json) or an FS backend without read_file support."""
        from ..errors import CheckpointCorruptionError

        try:
            blob = fs.read_file(os.path.join(ckpt, _COMMIT_FILE))
        except NotImplementedError:
            return None
        if blob is None:
            return None
        try:
            return json.loads(blob.decode())
        except (UnicodeDecodeError, ValueError) as e:
            raise CheckpointCorruptionError(
                f"undecodable commit record in {ckpt!r}: {e}"
            ) from e

    def _write_rank_shard(self, local_dir, rank, commit, train_status,
                          local_vars, scope=None):
        """Materialize one ``rank_<i>/`` shard into `local_dir`: this
        rank's full TrainStatus, a commit record echoing the checkpoint it
        belongs to (the rank-coherence check compares the two on load),
        and — when `local_vars` names non-replicated persistables (sharded
        optimizer state, per-rank tables) — a CRC-manifested payload of
        their scope values."""
        import numpy as np

        from .. import io as _io
        from ..framework.scope import global_scope

        shard = os.path.join(local_dir, _rank_dir_name(rank))
        os.makedirs(shard, exist_ok=True)
        with open(os.path.join(shard, _TRAIN_STATUS_FILE), "w") as f:
            json.dump(train_status.to_dict(), f)
        with open(os.path.join(shard, _COMMIT_FILE), "w") as f:
            json.dump(dict(commit, rank=int(rank)), f)
        if local_vars:
            scope = scope or global_scope()
            arrays = {}
            for v in local_vars:
                name = v if isinstance(v, str) else v.name
                value = scope.find_var(name)
                if value is None:
                    continue
                if getattr(value, "is_fully_addressable", True):
                    arrays[name] = np.asarray(value)
                else:
                    # cross-process-sharded state (ZeRO optimizer shards):
                    # persist only the dim-0 slices THIS process holds,
                    # keyed by their global offset; load overlays them
                    # back into the startup-initialized full value
                    arrays.update(_local_dim0_slices(name, value))
            payload = os.path.join(shard, "__params__.npz")
            _io._atomic_write(payload, lambda f: np.savez(f, **arrays))
            _io._write_manifest(
                os.path.join(shard, _io.MANIFEST_NAME), payload, arrays
            )
        return shard

    def _publish_rank_shard(self, fs, path, train_status, local_vars,
                            wait_timeout):
        """Non-first-worker half of save_check_point: wait for the first
        worker to publish the replicated checkpoint whose commit record
        matches this save (same epoch/global step), then publish this
        rank's shard into it with the same tmp+mv discipline. Returns the
        checkpoint number."""
        import shutil
        import tempfile
        import time as _time

        from ..errors import ExecutionTimeoutError
        from ..resilience import retry

        from ..errors import CheckpointCorruptionError

        rank = self.worker_index()
        deadline = _time.monotonic() + wait_timeout
        ckpt = no = None
        inspected = set()  # a published commit is immutable: read it once
        delay = 0.05
        while True:
            try:
                cands = list(reversed(_checkpoint_numbers(fs, path)))
            except Exception:
                cands = []  # transient listing failure: this IS a poll loop
            for cand in cands:
                if cand in inspected:
                    continue
                d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{cand}")
                try:
                    commit = self._read_commit(fs, d)
                except CheckpointCorruptionError:
                    # one rotted old commit must not wedge every future
                    # save of this rank; the scan just skips it
                    inspected.add(cand)
                    continue
                if commit is None:
                    # pre-v2 dir (immutable) — unless read_file is simply
                    # unsupported, in which case nothing can ever match
                    inspected.add(cand)
                    continue
                inspected.add(cand)
                if (commit.get("global_step") == train_status.global_step
                        and commit.get("epoch_no") == train_status._epoch_no):
                    ckpt, no = d, cand
                    break
            if ckpt is not None:
                break
            if _time.monotonic() >= deadline:
                raise ExecutionTimeoutError(
                    f"rank {rank}: no checkpoint with epoch="
                    f"{train_status._epoch_no} step="
                    f"{train_status.global_step} was published under "
                    f"{path!r} within {wait_timeout}s (is the first worker "
                    "saving with the same TrainStatus?)"
                )
            _time.sleep(delay)
            delay = min(1.0, delay * 1.5)  # don't hammer a remote namenode
        local = tempfile.mkdtemp(prefix="paddle_tpu_shard_")
        shard_tmp = os.path.join(ckpt, _rank_dir_name(rank) + ".tmp")
        shard_dst = os.path.join(ckpt, _rank_dir_name(rank))

        def _publish():
            if fs.is_exist(shard_dst):  # prior attempt's mv already landed
                fs.delete(shard_tmp)
                return
            src = self._write_rank_shard(
                local, rank, self._commit_record(train_status, no, True),
                train_status, local_vars,
            )
            fs.delete(shard_tmp)
            fs.upload(src, shard_tmp)
            fs.mv(shard_tmp, shard_dst)

        try:
            retry(
                max_attempts=4, base_delay=0.05, max_delay=2.0,
                name="checkpoint.shard",
            ).call(_publish)
        finally:
            shutil.rmtree(local, ignore_errors=True)
        return no

    def save_check_point(
        self, executor, path, train_status, main_program=None, fs=None,
        remain_all_checkpoint=False, max_checkpoint_num=3, local_vars=None,
        per_rank=None, shard_wait_timeout=120.0,
    ):
        """Save persistables + the full TrainStatus into a new numbered
        checkpoint dir and rotate old ones. The payload is written locally
        and published through the FS backend (upload + atomic mv), so
        remote backends only implement the FS contract; write + publish
        are retried with backoff (transient FS faults heal, the final
        state is idempotent).

        Per-rank decomposition (``per_rank=True``, or implied by passing
        ``local_vars``; every rank then calls this): replicated state —
        the persistables plus the first worker's TrainStatus — is written
        ONCE by the first worker, together with a ``commit.json`` record
        naming the checkpoint number, global step, and world size; every
        rank (first worker included) then publishes a ``rank_<i>/`` shard
        carrying its own TrainStatus (data cursor, RNG position) and any
        `local_vars` — non-replicated per-rank persistables (sharded
        optimizer state, PS tables). Non-first workers wait (up to
        `shard_wait_timeout`) for the matching replicated publish before
        attaching their shard. With ``per_rank`` off (the default), the
        classic contract holds: non-first workers return None immediately
        and the commit promises only the first worker's shard — existing
        first-worker-only call sites (TrainGuard's preemption drain,
        epoch-boundary saves) keep their exact old behavior.

        The just-published checkpoint is spot-verified (manifest/CRC
        readback) BEFORE predecessors rotate away, so a bad publish can
        never leave zero loadable checkpoints. Returns the checkpoint
        number."""
        import tempfile

        from .fs_wrapper import LocalFS
        from .. import io as _io
        from ..errors import CheckpointCorruptionError
        from ..resilience import retry

        fs = fs or LocalFS()
        if per_rank is None:
            per_rank = local_vars is not None
        if not self.is_first_worker():
            if not per_rank:
                return None
            return self._publish_rank_shard(
                fs, path, train_status, local_vars, shard_wait_timeout
            )
        import shutil

        def _prepare():
            # the scan prelude hits the FS too (fs.mkdir / fs.list_dirs
            # seams): a flaky remote listing must heal, not fail the save
            fs.mkdir(path)
            dirs = fs.list_dirs(path)
            # a *.tmp dir is a crashed prior save's half-published payload:
            # sweep it here, the only writer (list once, reuse for numbering)
            for d in dirs:
                if d.startswith(_CHECKPOINT_PREFIX) and d.endswith(".tmp"):
                    fs.delete(os.path.join(path, d))
            return _dir_numbers(dirs)

        nos = retry(
            max_attempts=4, base_delay=0.05, max_delay=2.0,
            name="checkpoint.prepare",
        ).call(_prepare)
        no = (nos[-1] + 1) if nos else 0
        ckpt = os.path.join(path, f"{_CHECKPOINT_PREFIX}{no}")
        tmp = ckpt + ".tmp"
        local = tempfile.mkdtemp(prefix="paddle_tpu_ckpt_")

        def _write_and_publish():
            # a prior attempt's mv may have landed even though it REPORTED
            # failure (remote rename applied, response lost); mv onto an
            # existing dir would nest tmp inside the live checkpoint, so
            # treat an existing ckpt as "already published"
            if fs.is_exist(ckpt):
                fs.delete(tmp)
                return
            # local_vars travel in the per-rank shards, not the
            # replicated payload (on a cross-process mesh this process
            # could not materialize them anyway)
            _io.save_persistables(
                executor, local, main_program,
                exclude=[
                    v if isinstance(v, str) else v.name
                    for v in (local_vars or ())
                ],
            )
            with open(os.path.join(local, _TRAIN_STATUS_FILE), "w") as f:
                json.dump(train_status.to_dict(), f)
            commit = self._commit_record(train_status, no, per_rank)
            with open(os.path.join(local, _COMMIT_FILE), "w") as f:
                json.dump(commit, f)
            # the first worker's own shard rides inside the atomic publish
            self._write_rank_shard(local, 0, commit, train_status, local_vars)
            fs.delete(tmp)
            fs.upload(local, tmp)
            # atomic publish: a crash mid-save leaves only a .tmp dir
            # behind, never a half-written numbered checkpoint
            fs.mv(tmp, ckpt)

        try:
            retry(
                max_attempts=4, base_delay=0.05, max_delay=2.0,
                name="checkpoint.save",
            ).call(_write_and_publish)
        finally:
            shutil.rmtree(local, ignore_errors=True)
        if not remain_all_checkpoint:
            # spot-verify the JUST-PUBLISHED checkpoint (manifest/CRC
            # readback through the backend) before deleting predecessors:
            # rotating first and verifying never could leave a run with
            # zero loadable checkpoints after one bad publish
            self._verify_published(fs, ckpt)
            doomed = (nos + [no])[:-max_checkpoint_num]
            if per_rank and doomed:
                # the new checkpoint is complete only once every PEER
                # attached its shard (asynchronously, after this return);
                # if no surviving checkpoint is complete yet, spare the
                # newest complete predecessor so a peer dying before its
                # attach can never leave zero resumable checkpoints
                def _complete(n):
                    d = os.path.join(path, f"{_CHECKPOINT_PREFIX}{n}")
                    try:
                        return not self._missing_shards(
                            fs, d, self._read_commit(fs, d)
                        )
                    except Exception:
                        # corrupt commit or transient scan failure: treat
                        # as not-complete (errs toward sparing more) —
                        # the save itself already succeeded, a completeness
                        # probe must not turn it into a failure
                        return False

                survivors = (nos + [no])[-max_checkpoint_num:]
                if not any(_complete(n) for n in survivors):
                    spared = next(
                        (n for n in reversed(doomed) if _complete(n)), None
                    )
                    doomed = [n for n in doomed if n != spared]
            for old in doomed:
                fs.delete(os.path.join(path, f"{_CHECKPOINT_PREFIX}{old}"))
        return no

    @staticmethod
    def _verify_published(fs, ckpt):
        """Readback-verify a published checkpoint via the FS backend; a
        failure raises CheckpointCorruptionError (and the caller skips
        rotation, keeping the older checkpoints loadable)."""
        import shutil
        import tempfile

        from .. import io as _io
        from .. import observability as _obs
        from ..resilience import retry

        def _readback():
            local = tempfile.mkdtemp(prefix="paddle_tpu_verify_")
            try:
                try:
                    # only the replicated payload + manifest are verified:
                    # fetching the rank shards too would roughly double the
                    # readback bytes on every rotation-enabled save
                    for fname in ("__params__.npz", _io.MANIFEST_NAME):
                        fs.download(
                            os.path.join(ckpt, fname),
                            os.path.join(local, fname),
                        )
                except Exception:
                    # backend without single-file download: whole dir
                    shutil.rmtree(local, ignore_errors=True)
                    os.makedirs(local, exist_ok=True)
                    fs.download(ckpt, local)
                _io.verify_checkpoint_dir(local)
            finally:
                shutil.rmtree(local, ignore_errors=True)

        try:
            # transient download faults heal; actual corruption
            # (CheckpointCorruptionError) is non-retryable and surfaces
            retry(
                max_attempts=3, base_delay=0.05, max_delay=1.0,
                name="checkpoint.verify",
            ).call(_readback)
        except Exception:
            _obs.add("resilience.checkpoint_publish_verify_failures")
            raise
        _obs.add("resilience.checkpoint_publish_verified")

    @staticmethod
    def _scan_retry():
        """Shared retry policy for read-side FS scans (list_dirs carries a
        fault seam now, and a flaky remote listing must heal on the load
        path exactly as it does in the save prelude)."""
        from ..resilience import retry

        return retry(
            max_attempts=3, base_delay=0.05, max_delay=1.0,
            name="checkpoint.scan",
        )

    def has_check_point(self, path, fs=None):
        """Whether at least one numbered checkpoint exists under `path` —
        distinguishes 'load would be a cold start' from 'load would
        restore real weights' (TrainGuard's rollback gate; a loaded
        checkpoint can legitimately carry TrainStatus(-1))."""
        from .fs_wrapper import LocalFS

        fs = fs or LocalFS()
        return bool(
            fs.is_exist(path)
            and self._scan_retry().call(_checkpoint_numbers, fs, path)
        )

    def _missing_shards(self, fs, ckpt, commit):
        """Rank dirs the checkpoint's commit record promises but that are
        absent — a save interrupted between the replicated publish and the
        last rank's shard upload. [] for complete or pre-v2 checkpoints."""
        if not commit:
            return []
        present = set(fs.list_dirs(ckpt))
        return [
            _rank_dir_name(i)
            for i in range(int(commit.get("nranks", 1)))
            if _rank_dir_name(i) not in present
        ]

    def _fetch_for_rank(self, fs, ckpt, local, tid, commit):
        """Stage the slice of a checkpoint THIS rank needs: the replicated
        top-level files plus its own ``rank_<tid>/`` shard. Skipping the
        peers' shards keeps resume traffic O(shard) per rank instead of
        O(nranks * shard) — across the pod, linear instead of quadratic.
        Backends that cannot fetch single paths fall back to the whole
        directory."""
        import shutil

        if not commit or int(commit.get("nranks", 1)) <= 1:
            fs.download(ckpt, local)
            return
        try:
            os.makedirs(local, exist_ok=True)
            from .. import io as _io

            for fname in ("__params__.npz", _io.MANIFEST_NAME,
                          _TRAIN_STATUS_FILE, _COMMIT_FILE):
                src = os.path.join(ckpt, fname)
                if fs.is_exist(src):
                    fs.download(src, os.path.join(local, fname))
            shard = os.path.join(ckpt, _rank_dir_name(tid))
            if fs.is_exist(shard):
                fs.download(shard, os.path.join(local, _rank_dir_name(tid)))
        except Exception:
            shutil.rmtree(local, ignore_errors=True)
            os.makedirs(local, exist_ok=True)
            fs.download(ckpt, local)

    def _load_rank_shard(self, local, trainer_id, dir_commit):
        """This rank's slice of a downloaded checkpoint: verify the shard's
        commit record against the checkpoint-level one (the rank-coherence
        check — a shard that belongs to a different checkpoint number or
        global step means the ranks would silently train on different
        timelines), overlay its per-rank payload onto the scope, and
        return its TrainStatus. None when the checkpoint predates shards
        or this rank joined after the save (elastic resize)."""
        import jax.numpy as jnp

        from ..errors import ResumeMismatchError
        from .. import io as _io

        shard = os.path.join(local, _rank_dir_name(trainer_id))
        if not os.path.isdir(shard):
            return None
        commit_file = os.path.join(shard, _COMMIT_FILE)
        if dir_commit is not None and os.path.exists(commit_file):
            with open(commit_file) as f:
                shard_commit = json.load(f)
            for field in ("checkpoint_no", "global_step"):
                if shard_commit.get(field) != dir_commit.get(field):
                    from .. import observability as _obs

                    _obs.add("resilience.resume_mismatches")
                    raise ResumeMismatchError(
                        f"rank {trainer_id} shard disagrees with its "
                        f"checkpoint on {field}: shard has "
                        f"{shard_commit.get(field)!r}, commit record has "
                        f"{dir_commit.get(field)!r} — refusing a resume "
                        "that would silently diverge the ranks"
                    )
        payload = os.path.join(shard, "__params__.npz")
        if os.path.exists(payload):
            from .. import observability as _obs
            from ..framework.scope import global_scope

            arrays = _io._load_npz_verified(payload)
            scope = global_scope()
            for name, arr in arrays.items():
                if _SLICE_MARK in name:
                    if not _overlay_slice(scope, name, arr):
                        _obs.add("resilience.shard_overlay_skipped")
                    continue
                scope.set_var(name, jnp.asarray(arr))
        status_file = os.path.join(shard, _TRAIN_STATUS_FILE)
        if os.path.exists(status_file):
            with open(status_file) as f:
                return TrainStatus.from_dict(json.load(f))
        return None

    def load_check_point(
        self, executor, path, trainer_id=None, main_program=None, fs=None,
        checkpoint_no=None,
    ):
        """Load the newest (or requested) checkpoint via the FS backend;
        returns its TrainStatus. Missing dir -> TrainStatus(-1) (cold
        start, reference behavior).

        Candidate selection skips checkpoints that fail integrity
        verification (CheckpointCorruptionError from io.py's manifest/CRC
        check) AND checkpoints whose commit record promises rank shards
        that never landed (a save interrupted after the replicated publish)
        — every rank walks the same FS view newest-first, so all ranks
        settle on the same newest COMPLETE checkpoint instead of silently
        diverging. An explicitly requested checkpoint_no never falls back:
        corruption raises CheckpointCorruptionError, incompleteness raises
        ResumeMismatchError.

        When this rank's ``rank_<i>/`` shard is present its commit record
        must match the checkpoint's (number + global step) — mismatch
        raises ResumeMismatchError — and the returned TrainStatus is the
        shard's (per-rank cursor and RNG position), with any per-rank
        payload overlaid on the scope after the replicated load."""
        import tempfile

        from ..errors import CheckpointCorruptionError, ResumeMismatchError
        from .fs_wrapper import LocalFS
        from .. import io as _io

        fs = fs or LocalFS()
        tid = trainer_id
        if tid is None:
            tid = self.worker_index() if self._inited else 0
        nos = (
            self._scan_retry().call(_checkpoint_numbers, fs, path)
            if fs.is_exist(path) else []
        )
        if not nos:
            return TrainStatus(-1)
        import shutil

        candidates = (
            [checkpoint_no] if checkpoint_no is not None else list(reversed(nos))
        )
        last_err = None
        saw_my_shard = had_corruption = False
        for i, no in enumerate(candidates):
            from .. import observability as _obs

            ckpt = os.path.join(path, f"{_CHECKPOINT_PREFIX}{no}")
            try:
                remote_commit = self._read_commit(fs, ckpt)
            except CheckpointCorruptionError as e:
                # a garbled commit record is corruption like any other:
                # fall back to an older checkpoint instead of bricking
                # resume on every rank
                _obs.add("resilience.checkpoint_corrupt")
                last_err = e
                had_corruption = True
                if checkpoint_no is not None:
                    raise
                continue
            missing = self._scan_retry().call(
                self._missing_shards, fs, ckpt, remote_commit
            )
            if missing:
                _obs.add("resilience.checkpoint_incomplete")
                if _rank_dir_name(tid) not in missing:
                    saw_my_shard = True
                last_err = ResumeMismatchError(
                    f"checkpoint {no} under {path!r} is missing rank "
                    f"shards {missing} its commit record promises (save "
                    "died between the replicated publish and the last "
                    "shard upload)"
                )
                if checkpoint_no is not None:
                    raise last_err
                continue
            local = tempfile.mkdtemp(prefix="paddle_tpu_ckpt_")
            try:
                self._fetch_for_rank(fs, ckpt, local, tid, remote_commit)
                _io.load_persistables(executor, local, main_program)
                if i > 0:
                    _obs.add("resilience.checkpoint_fallbacks")
                dir_commit = remote_commit
                commit_file = os.path.join(local, _COMMIT_FILE)
                if dir_commit is None and os.path.exists(commit_file):
                    # backend without read_file support: the commit rode
                    # along in the full-directory download
                    with open(commit_file) as f:
                        dir_commit = json.load(f)
                status = self._load_rank_shard(local, tid, dir_commit)
                if status is None:
                    status_file = os.path.join(local, _TRAIN_STATUS_FILE)
                    if os.path.exists(status_file):
                        with open(status_file) as f:
                            status = TrainStatus.from_dict(json.load(f))
                    else:
                        status = TrainStatus(-1)
                status.checkpoint_no = no
                if status.global_step or status.cursor:
                    # a v2 mid-run position is being restored, not a bare
                    # epoch boundary: the exact-resume path fired
                    _obs.add("resilience.resumes")
                return status
            except CheckpointCorruptionError as e:
                _obs.add("resilience.checkpoint_corrupt")
                last_err = e
                had_corruption = True
            finally:
                shutil.rmtree(local, ignore_errors=True)
        if (
            isinstance(last_err, ResumeMismatchError)
            and not saw_my_shard and not had_corruption
        ):
            # every candidate was merely incomplete and NONE of them holds
            # this rank's shard: this rank never completed a save, so there
            # is nothing to resume — a cold start, not an error. (The
            # common shape: a peer published the replicated payload while
            # this rank was still starting up and hadn't attached yet.)
            from .. import observability as _obs

            _obs.add("resilience.resume_cold_starts")
            return TrainStatus(-1)
        raise last_err



class TrainStatus:
    """Checkpoint metadata, v2: the FULL training-loop position, not just
    the last finished epoch (reference :49 stored only that, which made a
    resumed run replay the interrupted epoch from example 0 with fresh RNG
    streams — elastic restart silently changed what the model trained on).

    Fields beyond ``epoch_no``:

    * ``global_step`` — optimizer updates applied so far;
    * ``rng`` — :meth:`Program.rng_state` (seed mode, per-run step
      counter, unseeded-program nonce) so replayed steps draw the same
      dropout masks;
    * ``amp`` — ``OptimizerWithMixedPrecision.state_dict()`` (dynamic
      loss scale + good/bad step counters);
    * ``guard`` — ``TrainGuard.state_dict()`` (bad-step counters and the
      spent rollback budget);
    * ``cursor`` — ``DataLoader.state_dict()`` (epoch + batches consumed
      + sampler seed), the resumable-input-pipeline position.

    :meth:`capture` fills them from live objects; :meth:`restore` applies
    them back after ``load_check_point``. Serialization is versioned:
    v1 payloads (bare ``{"epoch_no": N}``) load with defaulted v2 fields.
    Equality stays epoch-based (the v1 contract callers rely on)."""

    def __init__(self, epoch_no=-1, global_step=0, rng=None, amp=None,
                 guard=None, cursor=None):
        self._epoch_no = epoch_no
        self.global_step = int(global_step)
        self.rng = dict(rng) if rng else {}
        self.amp = dict(amp) if amp else {}
        self.guard = dict(guard) if guard else {}
        self.cursor = dict(cursor) if cursor else {}
        self.checkpoint_no = None  # set by load_check_point

    @property
    def epoch_no(self):
        return self._epoch_no

    def next(self):
        return self._epoch_no + 1

    # -- capture / restore -------------------------------------------------
    @classmethod
    def capture(cls, epoch_no=-1, global_step=0, program=None, amp=None,
                guard=None, loader=None, scope=None):
        """Snapshot the full training-loop state from live objects. Every
        source is optional — pass what the loop uses; omitted parts stay
        empty and restore as no-ops."""
        return cls(
            epoch_no,
            global_step=global_step,
            rng=program.rng_state() if program is not None else None,
            amp=amp.state_dict(scope=scope) if amp is not None else None,
            guard=guard.state_dict() if guard is not None else None,
            cursor=loader.state_dict() if loader is not None else None,
        )

    def restore(self, program=None, amp=None, guard=None, loader=None,
                scope=None):
        """Apply the captured state back onto live objects (call after
        ``load_check_point`` repopulated the scope). Empty parts — e.g.
        from a v1 checkpoint — are no-ops. Returns self for chaining."""
        if program is not None:
            program.set_rng_state(self.rng)
        if amp is not None:
            amp.load_state_dict(self.amp, scope=scope)
        if guard is not None:
            guard.load_state_dict(self.guard)
        if loader is not None and self.cursor:
            loader.load_state_dict(self.cursor)
        return self

    # -- versioned serialization --------------------------------------------
    def to_dict(self):
        return {
            "version": TRAIN_STATUS_VERSION,
            "epoch_no": self._epoch_no,
            "global_step": self.global_step,
            "rng": self.rng,
            "amp": self.amp,
            "guard": self.guard,
            "cursor": self.cursor,
        }

    @classmethod
    def from_dict(cls, payload):
        from ..errors import CheckpointCorruptionError

        if not isinstance(payload, dict):
            raise CheckpointCorruptionError(
                f"train status payload is not a dict: {type(payload).__name__}"
            )
        version = int(payload.get("version", 1))
        if version > TRAIN_STATUS_VERSION:
            raise CheckpointCorruptionError(
                f"train status version {version} is newer than this build "
                f"understands (max {TRAIN_STATUS_VERSION}); refusing a "
                "lossy partial load"
            )
        return cls(
            payload.get("epoch_no", -1),
            global_step=payload.get("global_step", 0),
            rng=payload.get("rng"),
            amp=payload.get("amp"),
            guard=payload.get("guard"),
            cursor=payload.get("cursor"),
        )

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and self._epoch_no == other._epoch_no

    def __ne__(self, other):
        # the reference defined __eq__ only, so `status != other` fell back
        # to identity on py2-style consumers; keep the pair consistent
        return not self.__eq__(other)

    def __repr__(self):
        extra = f", global_step={self.global_step}" if self.global_step else ""
        return f"TrainStatus(epoch_no={self._epoch_no}{extra})"


class CollectiveOptimizer:
    """Wraps any Optimizer; minimize() produces an SPMD data-parallel program
    (reference CollectiveOptimizer :384, but transpile → mesh+shard_map)."""

    def __init__(self, fleet, inner_opt, strategy):
        self._fleet = fleet
        self._inner = inner_opt
        self._strategy = strategy

    def backward(self, loss, **kw):
        return self._inner.backward(loss, **kw)

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def _check_shardable(self):
        """shard_weight_update preconditions: grad clipping and
        regularization both read FULL-tensor gradients after the
        reduce-scatter would land (a shard-local norm silently changes
        the math), so they refuse to compose until a sharded global-norm
        path exists."""
        opt = self._inner
        seen = set()
        while opt is not None and id(opt) not in seen:
            seen.add(id(opt))
            if getattr(opt, "_grad_clip", None) is not None:
                raise NotImplementedError(
                    "shard_weight_update does not compose with grad_clip "
                    "yet: clipping norms are full-tensor reductions"
                )
            if getattr(opt, "regularization", None) is not None:
                raise NotImplementedError(
                    "shard_weight_update does not compose with "
                    "regularization yet (weight decay via AdamW is fine)"
                )
            opt = getattr(opt, "_inner", None) or getattr(
                opt, "inner_optimizer", None
            )

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        inner = self._inner
        strategy = self._strategy
        if strategy.forward_recompute:
            from ..incubate.recompute import RecomputeOptimizer

            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(strategy.recompute_checkpoints)
        if strategy.use_amp:
            from ..contrib.mixed_precision import decorate

            inner = decorate(
                inner, init_loss_scaling=strategy.amp_loss_scaling
            )

        main = loss.block.program
        startup = startup_program or default_startup_program()
        from ..framework.program import program_guard

        with program_guard(main, startup):
            params_grads = inner.backward(
                loss, startup, parameter_list, no_grad_set
            )
            mesh = self._fleet.mesh(strategy)
            if strategy.local_sgd:
                raise NotImplementedError(
                    "strategy.local_sgd: parameters are replicated under "
                    "synchronous SPMD, so LocalSGD has no effect; use "
                    "parallel.LocalSGD explicitly for multi-copy setups"
                )
            # no dp axis in the mesh -> pure model parallel, no grad allreduce
            dp = mesh.shape.get(DATA_AXIS, 1)
            sharded = bool(strategy.shard_weight_update) and dp > 1
            quant = strategy.collective_quant
            if quant not in (None, "", "none", "int8"):
                raise ValueError(
                    f"DistributedStrategy.collective_quant={quant!r} is "
                    "unknown; supported: None | 'int8'"
                )
            if quant in ("int8",) and not strategy.shard_weight_update:
                # a silently ignored knob would let users believe they
                # bought the 4x wire reduction
                raise ValueError(
                    "DistributedStrategy.collective_quant requires "
                    "shard_weight_update=True: quantized payloads exist "
                    "only on the reduce-scatter/all-gather path"
                )
            if sharded:
                self._check_shardable()
            if dp > 1 and not sharded:
                GradAllReduce(dp).transpile(main, params_grads)
                from .. import observability as _obs

                _obs.add("collective.grad_allreduce_tensors",
                         len(params_grads))
                _obs.set_gauge("collective.dp_degree", dp)
            ops = inner.apply_gradients(params_grads)
            if sharded:
                # the update ops exist now: rewrite them onto 1/dp shards
                # (reduce-scatter grads, shard-local update, param
                # all-gather) — the ZeRO transpile
                from ..parallel.transpiler import ShardedWeightUpdate

                ShardedWeightUpdate(
                    dp,
                    quant=strategy.collective_quant,
                    quant_block=strategy.collective_quant_block,
                ).transpile(main, startup, params_grads)
                from .. import observability as _obs

                _obs.set_gauge("collective.dp_degree", dp)
            if dp > 1:
                # fetched metrics (loss) are shard-local means; average them
                # across dp so exe.run returns the global-batch value (the
                # reference's dist tests instead compare per-trainer losses
                # with loose tolerance — a global mean is strictly better)
                blk = main.global_block
                blk.append_op(
                    "scale",
                    inputs={"X": [loss.name]},
                    outputs={"Out": [loss.name]},
                    attrs={"scale": 1.0 / dp, "bias": 0.0},
                )
                blk.append_op(
                    "c_allreduce_sum",
                    inputs={"X": [loss.name]},
                    outputs={"Out": [loss.name]},
                    attrs={"axis_name": DATA_AXIS},
                )

        # SPMD: attach mesh; shard feed batch dims over dp. The startup
        # program stays single-device — replication to the mesh happens on
        # first main-program dispatch (jit resharding), which is exactly
        # BCastParamsToDevices (parallel_executor.cc:570) done lazily.
        main._mesh = mesh
        main._sharding.update(strategy.sharding)
        main._bump()
        for var in main.list_vars():
            if var.is_data and var.name not in main._sharding:
                main._sharding[var.name] = (DATA_AXIS,)
        return ops, params_grads


fleet = Fleet()

