"""Pluggable checkpoint filesystems (reference
incubate/fleet/collective/fs_wrapper.py: FS / LocalFS / BDFS).

LocalFS covers single-host and NFS-mounted checkpoint dirs; a HadoopFS-style
backend plugs in by implementing the same five methods (the reference
shelled out to `hadoop fs`, framework/io/fs.cc)."""

from __future__ import annotations

import os
import shutil


class FS:
    def list_dirs(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdir(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst):
        raise NotImplementedError

    def upload(self, local_path, remote_path):
        """Publish a locally-written payload dir to the backend."""
        raise NotImplementedError

    def download(self, remote_path, local_path):
        """Fetch a checkpoint dir into a local staging dir."""
        raise NotImplementedError


class LocalFS(FS):
    def list_dirs(self, path):
        if not os.path.isdir(path):
            return []
        return [
            d for d in sorted(os.listdir(path))
            if os.path.isdir(os.path.join(path, d))
        ]

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdir(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst):
        shutil.move(src, dst)

    def upload(self, local_path, remote_path):
        shutil.copytree(local_path, remote_path, dirs_exist_ok=True)

    def download(self, remote_path, local_path):
        shutil.copytree(remote_path, local_path, dirs_exist_ok=True)
