"""Pluggable checkpoint filesystems (reference
incubate/fleet/collective/fs_wrapper.py: FS / LocalFS / BDFS).

LocalFS covers single-host and NFS-mounted checkpoint dirs; a HadoopFS-style
backend plugs in by implementing the same five methods (the reference
shelled out to `hadoop fs`, framework/io/fs.cc).

Mutating entry points carry resilience fault seams (``fs.upload`` /
``fs.download`` / ``fs.mv`` / ``fs.delete`` for LocalFS, ``fs.hadoop`` for
every HadoopFS shell-out) so checkpoint publish/fetch paths are
chaos-testable; callers (Fleet.save_check_point) retry around them."""

from __future__ import annotations

import os
import shutil

from ..resilience.faults import fault_point


class FS:
    def list_dirs(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdir(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst):
        raise NotImplementedError

    def upload(self, local_path, remote_path):
        """Publish a locally-written payload dir to the backend."""
        raise NotImplementedError

    def download(self, remote_path, local_path):
        """Fetch a checkpoint dir into a local staging dir."""
        raise NotImplementedError


class LocalFS(FS):
    def list_dirs(self, path):
        if not os.path.isdir(path):
            return []
        return [
            d for d in sorted(os.listdir(path))
            if os.path.isdir(os.path.join(path, d))
        ]

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdir(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        fault_point("fs.delete")
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst):
        fault_point("fs.mv")
        shutil.move(src, dst)

    def upload(self, local_path, remote_path):
        fault_point("fs.upload")
        shutil.copytree(local_path, remote_path, dirs_exist_ok=True)

    def download(self, remote_path, local_path):
        fault_point("fs.download")
        shutil.copytree(remote_path, local_path, dirs_exist_ok=True)


class HadoopFS(FS):
    """HDFS backend shelling out to `hadoop fs` — exactly the reference's
    approach (framework/io/fs.cc hdfs_* commands, incubate/fleet/utils/
    hdfs.py HDFSClient). Checkpoint payloads are written locally and moved
    through upload/download, so only these six commands are needed."""

    def __init__(self, hadoop_bin="hadoop", configs=None):
        self._bin = hadoop_bin
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]

    def _run(self, *args, check=True):
        import subprocess

        fault_point("fs.hadoop")
        cmd = [self._bin, "fs"] + self._cfg + list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed (rc={proc.returncode}): "
                f"{proc.stderr.strip()}"
            )
        return proc

    def list_dirs(self, path):
        proc = self._run("-ls", path, check=False)
        if proc.returncode != 0:
            return []
        out = []
        for line in proc.stdout.splitlines():
            parts = line.split()
            # "drwxr-xr-x - user group 0 date time /path/dir"
            if len(parts) >= 8 and parts[0].startswith("d"):
                out.append(parts[-1].rstrip("/").rsplit("/", 1)[-1])
        return sorted(out)

    def is_exist(self, path):
        return self._run("-test", "-e", path, check=False).returncode == 0

    def mkdir(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path, check=False)

    def mv(self, src, dst):
        self._run("-mv", src, dst)

    def upload(self, local_path, remote_path):
        self._run("-put", "-f", local_path, remote_path)

    def download(self, remote_path, local_path):
        import os

        # -get refuses an existing destination dir; fetch into it instead
        os.makedirs(local_path, exist_ok=True)
        proc = self._run("-get", f"{remote_path.rstrip('/')}/*", local_path,
                         check=False)
        if proc.returncode != 0:
            self._run("-get", remote_path, local_path)
