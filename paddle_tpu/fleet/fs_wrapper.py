"""Pluggable checkpoint filesystems (reference
incubate/fleet/collective/fs_wrapper.py: FS / LocalFS / BDFS).

LocalFS covers single-host and NFS-mounted checkpoint dirs; a HadoopFS-style
backend plugs in by implementing the same five methods (the reference
shelled out to `hadoop fs`, framework/io/fs.cc).

Entry points carry resilience fault seams (``fs.upload`` /
``fs.download`` / ``fs.mv`` / ``fs.delete`` / ``fs.mkdir`` /
``fs.list_dirs`` for LocalFS, ``fs.hadoop`` for every HadoopFS shell-out)
so checkpoint publish/fetch paths are chaos-testable — including the
directory-scan prelude of a save, which a flaky remote listing can fail
just as easily as the upload; callers (Fleet.save_check_point) retry
around them."""

from __future__ import annotations

import os
import shutil

from ..resilience.faults import fault_point


class FS:
    def list_dirs(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdir(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst):
        raise NotImplementedError

    def upload(self, local_path, remote_path):
        """Publish a locally-written payload dir to the backend."""
        raise NotImplementedError

    def download(self, remote_path, local_path):
        """Fetch a checkpoint dir into a local staging dir."""
        raise NotImplementedError

    def read_file(self, path):
        """Bytes of a small remote file (commit records / status JSON), or
        None when it does not exist. Lets rank-coherence checks read a
        checkpoint's commit record without downloading the payload."""
        raise NotImplementedError


class LocalFS(FS):
    def list_dirs(self, path):
        fault_point("fs.list_dirs")
        if not os.path.isdir(path):
            return []
        return [
            d for d in sorted(os.listdir(path))
            if os.path.isdir(os.path.join(path, d))
        ]

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdir(self, path):
        fault_point("fs.mkdir")
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        fault_point("fs.delete")
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst):
        fault_point("fs.mv")
        shutil.move(src, dst)

    def upload(self, local_path, remote_path):
        fault_point("fs.upload")
        shutil.copytree(local_path, remote_path, dirs_exist_ok=True)

    def download(self, remote_path, local_path):
        fault_point("fs.download")
        if os.path.isfile(remote_path):
            # single-file fetch: lets a rank pull just the replicated
            # payload + its own shard instead of every peer's
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            shutil.copy2(remote_path, local_path)
        else:
            shutil.copytree(remote_path, local_path, dirs_exist_ok=True)

    def read_file(self, path):
        if not os.path.isfile(path):
            return None
        with open(path, "rb") as f:
            return f.read()


class HadoopFS(FS):
    """HDFS backend shelling out to `hadoop fs` — exactly the reference's
    approach (framework/io/fs.cc hdfs_* commands, incubate/fleet/utils/
    hdfs.py HDFSClient). Checkpoint payloads are written locally and moved
    through upload/download, so only these six commands are needed."""

    def __init__(self, hadoop_bin="hadoop", configs=None):
        self._bin = hadoop_bin
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]

    def _run(self, *args, check=True):
        import subprocess

        fault_point("fs.hadoop")
        cmd = [self._bin, "fs"] + self._cfg + list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed (rc={proc.returncode}): "
                f"{proc.stderr.strip()}"
            )
        return proc

    def list_dirs(self, path):
        proc = self._run("-ls", path, check=False)
        if proc.returncode != 0:
            return []
        out = []
        for line in proc.stdout.splitlines():
            parts = line.split()
            # "drwxr-xr-x - user group 0 date time /path/dir"
            if len(parts) >= 8 and parts[0].startswith("d"):
                out.append(parts[-1].rstrip("/").rsplit("/", 1)[-1])
        return sorted(out)

    def is_exist(self, path):
        return self._run("-test", "-e", path, check=False).returncode == 0

    def mkdir(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path, check=False)

    def mv(self, src, dst):
        self._run("-mv", src, dst)

    def upload(self, local_path, remote_path):
        self._run("-put", "-f", local_path, remote_path)

    def download(self, remote_path, local_path):
        import os

        if self._run("-test", "-d", remote_path, check=False).returncode != 0:
            # a single file: fetch it under the local name directly
            if os.path.exists(local_path):
                os.remove(local_path)
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            self._run("-get", remote_path, local_path)
            return
        # -get refuses an existing destination dir; fetch into it instead
        os.makedirs(local_path, exist_ok=True)
        proc = self._run("-get", f"{remote_path.rstrip('/')}/*", local_path,
                         check=False)
        if proc.returncode != 0:
            self._run("-get", remote_path, local_path)

    def read_file(self, path):
        proc = self._run("-cat", path, check=False)
        if proc.returncode != 0:
            # only a genuinely absent file maps to None; a transient HDFS
            # error must surface (callers treat None as "pre-v2
            # checkpoint" and would skip the rank-coherence check)
            if "No such file" in (proc.stderr or ""):
                return None
            raise RuntimeError(
                f"hadoop fs -cat {path} failed (rc={proc.returncode}): "
                f"{(proc.stderr or '').strip()}"
            )
        return proc.stdout.encode()
