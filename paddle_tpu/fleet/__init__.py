"""Fleet: the distributed-training facade (reference incubate/fleet/).

Collective mode is the TPU mainline (mesh + SPMD, fleet/collective.py).
Role makers mirror the reference's env-driven discovery (role_maker.py).
"""

from .collective import (  # noqa: F401
    AsyncCheckpointer,
    CheckpointSnapshot,
    CollectiveOptimizer,
    DistributedStrategy,
    Fleet,
    PendingSave,
    TrainStatus,
    fleet,
)
from .publish import (  # noqa: F401
    ModelPublisher,
    ModelSubscriber,
    block_version,
    committed_versions,
    latest_version,
    read_blocked,
)
from .role_maker import (  # noqa: F401
    PaddleCloudRoleMaker,
    Role,
    RoleMakerBase,
    UserDefinedRoleMaker,
)

init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
worker_num = fleet.worker_num
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
is_worker = fleet.is_worker
is_server = fleet.is_server
