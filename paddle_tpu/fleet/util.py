"""FleetUtil (reference incubate/fleet/utils/fleet_util.py): cross-worker
metric aggregation — global AUC from distributed confusion stats, global
scalar reductions, barrier-style helpers.

TPU-native: the reduction is one tiny compiled program with a
c_allreduce over the process mesh (the GeoCommunicator pattern) instead of
the reference's gloo all_reduce; single-process runs degrade to identity,
so the math is testable without a cluster.
"""

from __future__ import annotations

import numpy as np


class FleetUtil:
    def __init__(self, mesh=None, exe=None):
        self._mesh = mesh
        self._exe = exe
        self._progs = {}

    # -- low-level ----------------------------------------------------------
    def all_reduce(self, value, mode="sum"):
        """Reduce a host numpy array across workers (reference
        fleet_util.all_reduce over gloo). mode: sum | max | min.

        Result is float64: sums split the value into 2^20-radix digits so
        integer counts survive exactly up to ~2^40 even though the compiled
        reduce runs in float32 (jax x64 is off) — CTR impression counts
        routinely exceed float32's 2^24 integer range."""
        arr64 = np.asarray(value, dtype=np.float64)
        if self._mesh is None or self._exe is None:
            return arr64  # single process: reduction is identity
        if mode != "sum":
            return self._reduce_f32(arr64.astype(np.float32), mode).astype(
                np.float64
            )
        radix = float(2 ** 20)
        hi = np.floor(arr64 / radix)
        lo = arr64 - hi * radix
        hi_sum = self._reduce_f32(hi.astype(np.float32), "sum")
        lo_sum = self._reduce_f32(lo.astype(np.float32), "sum")
        return hi_sum.astype(np.float64) * radix + lo_sum.astype(np.float64)

    def _reduce_f32(self, arr, mode):
        key = (arr.shape, mode)
        if key not in self._progs:
            self._progs[key] = self._build_reduce(arr.shape, mode)
        prog, out = self._progs[key]
        (res,) = self._exe.run(
            prog, feed={"fu_in": arr}, fetch_list=[out]
        )
        return np.asarray(res)

    def _build_reduce(self, shape, mode):
        import jax

        import paddle_tpu as fluid
        from ..parallel.spmd import shard_program

        op_type = {
            "sum": "c_allreduce_sum",
            "max": "c_allreduce_max",
            "min": "c_allreduce_min",
        }[mode]
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data("fu_in", list(shape) or [1])
            blk = prog.global_block
            out = blk.create_var(
                name="fu_out", shape=list(shape) or [1], dtype="float32"
            )
            blk.append_op(
                op_type, {"X": [x.name]}, {"Out": [out.name]},
                {"ring_id": 0},
            )
            if mode == "sum":
                # replicated feeds over each process's local devices count
                # every process local_device_count times
                from .. import layers

                out = layers.scale(
                    blk.var("fu_out"),
                    scale=1.0 / jax.local_device_count(),
                )
        shard_program(prog, self._mesh)
        return prog, out

    # -- metrics ------------------------------------------------------------
    def calc_global_auc(self, stat_pos, stat_neg):
        """Global AUC from per-worker positive/negative prediction
        histograms (reference fleet_util.get_global_auc: workers hold
        bucketed counts; sum across workers, then the trapezoidal AUC the
        in-graph auc op uses)."""
        pos = self.all_reduce(stat_pos, "sum")
        neg = self.all_reduce(stat_neg, "sum")
        return self._auc_from_stats(pos, neg)

    @staticmethod
    def _auc_from_stats(pos, neg):
        # walk buckets from highest score to lowest accumulating TP/FP
        pos = np.asarray(pos, np.float64).reshape(-1)
        neg = np.asarray(neg, np.float64).reshape(-1)
        tot_pos = pos.sum()
        tot_neg = neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        area = 0.0
        tp = fp = 0.0
        for i in range(len(pos) - 1, -1, -1):
            new_tp = tp + pos[i]
            new_fp = fp + neg[i]
            area += (new_fp - fp) * (tp + new_tp) / 2.0
            tp, fp = new_tp, new_fp
        return float(area / (tot_pos * tot_neg))

    def get_global_metrics(self, values):
        """Sum-reduce a dict of host scalars across workers (float64 end
        to end — the radix-split sum keeps large counts exact)."""
        keys = sorted(values)
        arr = np.asarray([float(values[k]) for k in keys], np.float64)
        red = self.all_reduce(arr, "sum")
        return dict(zip(keys, red.tolist()))


fleet_util = FleetUtil()
