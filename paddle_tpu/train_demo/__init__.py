"""Native C++ training demo (reference paddle/fluid/train/demo/
demo_trainer.cc + its README build recipe): `build_demo()` compiles the
embedded-CPython trainer binary, `save_train_bundle()` writes the
pickled {main, startup, feeds, loss} bundle it consumes. Exercised
end-to-end by tests/test_train_demo.py."""

from __future__ import annotations

import os
import pickle
import subprocess
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "demo_trainer.cc")
_BIN = os.path.join(_HERE, "demo_trainer")


def _embed_flags():
    cflags = [f"-I{sysconfig.get_path('include')}"]
    ldver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    libdir = sysconfig.get_config_var("LIBDIR")
    ldflags = [f"-L{libdir}", f"-Wl,-rpath,{libdir}", f"-lpython{ldver}"]
    return cflags, ldflags


def build_demo(force=False):
    """Compile the demo trainer binary (cached by source mtime)."""
    if (
        not force
        and os.path.exists(_BIN)
        and os.path.getmtime(_BIN) >= os.path.getmtime(_SRC)
    ):
        return _BIN
    cflags, ldflags = _embed_flags()
    cmd = ["g++", "-O2", "-std=c++17", *cflags, _SRC, "-o", _BIN, *ldflags]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _BIN


def save_train_bundle(path, main_program, startup_program, feeds, loss_name):
    """Pickle the training bundle the C++ demo consumes (the analog of the
    reference's saved ProgramDesc file)."""
    with open(path, "wb") as f:
        pickle.dump(
            {
                "main": main_program,
                "startup": startup_program,
                "feeds": dict(feeds),
                "loss": str(loss_name),
            },
            f,
        )
    return path
