// Native training demo (reference paddle/fluid/train/demo/demo_trainer.cc:
// a C++ binary that loads a saved training ProgramDesc and drives the
// Executor through N SGD steps, printing the loss).
//
// TPU-native shape: the Program pair is pickled python (Program IR is
// picklable by design); this binary embeds CPython — exactly as the
// inference C API does (inference_capi/capi.cpp) — loads the pair, runs
// the startup program, then drives train steps through the whole-block
// XLA executor. The C++ side owns main(), argument parsing, and the
// training loop; the interpreter is the runtime library underneath.
//
// Usage: demo_trainer <train_bundle.pkl> [steps]
// where the bundle is {"main": Program, "startup": Program,
// "feeds": {name: ndarray}, "loss": varname} (see
// paddle_tpu/train_demo/__init__.py save_train_bundle).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>

namespace {

const char* kHelper = R"PY(
import pickle

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework.scope import Scope


def load_bundle(path):
    with open(path, "rb") as f:
        b = pickle.load(f)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(b["startup"], scope=scope)
    return (b, scope, exe)


def train_step(state, step):
    b, scope, exe = state
    (lv,) = exe.run(b["main"], feed=b["feeds"], fetch_list=[b["loss"]],
                    scope=scope)
    return float(np.asarray(lv).reshape(-1)[0])
)PY";

PyObject* g_mod = nullptr;

bool init_python() {
  Py_InitializeEx(0);
  PyObject* mod = PyModule_New("demo_trainer_helper");
  PyObject* globals = PyModule_GetDict(mod);
  PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
  PyObject* res = PyRun_String(kHelper, Py_file_input, globals, globals);
  if (res == nullptr) {
    PyErr_Print();
    return false;
  }
  Py_DECREF(res);
  g_mod = mod;
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <train_bundle.pkl> [steps]\n", argv[0]);
    return 1;
  }
  const int steps = argc > 2 ? std::atoi(argv[2]) : 10;
  if (!init_python()) return 2;

  PyObject* load = PyObject_GetAttrString(g_mod, "load_bundle");
  PyObject* state =
      PyObject_CallFunction(load, "s", argv[1]);
  Py_DECREF(load);
  if (state == nullptr) {
    PyErr_Print();
    return 3;
  }
  PyObject* step_fn = PyObject_GetAttrString(g_mod, "train_step");
  double first = 0.0, last = 0.0;
  for (int i = 0; i < steps; ++i) {
    PyObject* lv = PyObject_CallFunction(step_fn, "Oi", state, i);
    if (lv == nullptr) {
      PyErr_Print();
      return 4;
    }
    last = PyFloat_AsDouble(lv);
    if (i == 0) first = last;
    std::printf("step %d loss %.6f\n", i, last);
    Py_DECREF(lv);
  }
  Py_DECREF(step_fn);
  Py_DECREF(state);
  std::printf("train_demo done: loss %.6f -> %.6f (%s)\n", first, last,
              last < first ? "decreased" : "NOT decreased");
  Py_Finalize();
  return last < first ? 0 : 5;
}
