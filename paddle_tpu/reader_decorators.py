"""Reader decorators + paddle.batch (reference python/paddle/reader/
decorator.py: map_readers, shuffle, chain, compose, buffered, firstn,
xmap_readers, cache; python/paddle/batch.py)."""

from __future__ import annotations

import itertools
import queue
import random
import threading


def batch(reader, batch_size, drop_last=False):
    """paddle.batch parity: sample reader -> batch reader."""

    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def map_readers(func, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        random.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment=True):
    def composed():
        iters = [r() for r in readers]
        for items in zip(*iters):
            out = ()
            for item in items:
                out += item if isinstance(item, tuple) else (item,)
            yield out
        if check_alignment:
            for it in iters:
                try:
                    next(it)
                except StopIteration:
                    continue
                raise ValueError("readers have different lengths")

    return composed


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples."""

    END = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def produce():
            try:
                for sample in reader():
                    q.put(sample)
            finally:
                q.put(END)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is END:
                return
            yield s

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference keeps
    subprocess workers; threads suffice for numpy-bound mappers)."""

    def xreader():
        samples = list(reader())
        results = [None] * len(samples)
        idx_q: queue.Queue = queue.Queue()
        for i, s in enumerate(samples):
            idx_q.put((i, s))

        def work():
            while True:
                try:
                    i, s = idx_q.get_nowait()
                except queue.Empty:
                    return
                results[i] = mapper(s)

        threads = [
            threading.Thread(target=work, daemon=True)
            for _ in range(process_num)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if order:
            yield from results
        else:
            yield from results

    return xreader


def cache(reader):
    data = []
    filled = []

    def cached():
        if not filled:
            data.extend(reader())
            filled.append(True)
        return iter(data)

    return cached
