"""Reader decorators + paddle.batch (reference python/paddle/reader/
decorator.py: map_readers, shuffle, chain, compose, buffered, firstn,
xmap_readers, cache; python/paddle/batch.py)."""

from __future__ import annotations

import itertools
import queue
import random
import threading


def batch(reader, batch_size, drop_last=False):
    """paddle.batch parity: sample reader -> batch reader."""

    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def map_readers(func, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        random.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment=True):
    def composed():
        END = object()
        iters = [r() for r in readers]
        while True:
            items = [next(it, END) for it in iters]
            stopped = [i is END for i in items]
            if all(stopped):
                return
            if any(stopped):
                if check_alignment:
                    raise ValueError("readers have different lengths")
                return
            out = ()
            for item in items:
                out += item if isinstance(item, tuple) else (item,)
            yield out

    return composed


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples. Producer
    exceptions propagate to the consumer (a swallowed error would look
    like a clean, truncated epoch)."""

    END = object()

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def produce():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:
                q.put(e)
                return
            q.put(END)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is END:
                return
            if isinstance(s, BaseException):
                raise s
            yield s

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference keeps
    subprocess workers; threads suffice for numpy-bound mappers).
    Streaming: at most buffer_size samples are in flight; mapper
    exceptions propagate; order=True yields in input order."""

    END = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(maxsize=max(1, buffer_size))
        out_q: queue.Queue = queue.Queue(maxsize=max(1, buffer_size))

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
            except BaseException as e:
                out_q.put(e)  # reader failure must reach the consumer
            finally:
                for _ in range(process_num):
                    in_q.put(END)

        def work():
            while True:
                item = in_q.get()
                if item is END:
                    out_q.put(END)
                    return
                i, s = item
                try:
                    out_q.put((i, mapper(s)))
                except BaseException as e:
                    out_q.put(e)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        done = 0
        pending = {}
        next_idx = 0
        while done < process_num:
            item = out_q.get()
            if item is END:
                done += 1
                continue
            if isinstance(item, BaseException):
                raise item
            i, mapped = item
            if not order:
                yield mapped
            else:
                pending[i] = mapped
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        while order and next_idx in pending:
            yield pending.pop(next_idx)
            next_idx += 1

    return xreader


def cache(reader):
    data = []
    filled = []

    def cached():
        if not filled:
            data.extend(reader())
            filled.append(True)
        return iter(data)

    return cached
