"""IMDB sentiment reader (reference python/paddle/dataset/imdb.py:
word_dict() builds a frequency-ranked vocabulary, train/test yield
(word-id sequence, 0/1 label)).

Download-or-synthetic (dataset/common.py pattern): with the aclImdb
tarball under DATA_HOME the real corpus is parsed; otherwise a
deterministic synthetic corpus with class-correlated token distributions
stands in (same reader contract, usable offline)."""

from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from .common import data_path, have_file, synthetic_rng

_TAR = "aclImdb_v1.tar.gz"
_VOCAB = 2048


def _tokenize(text):
    return re.sub(
        f"[{re.escape(string.punctuation)}]", " ", text.lower()
    ).split()


def _real_docs(pattern):
    with tarfile.open(data_path("imdb", _TAR)) as tf:
        for m in tf.getmembers():
            if bool(pattern.match(m.name)):
                yield _tokenize(tf.extractfile(m).read().decode("latin1"))


def _synthetic_docs(split, label, n=200):
    r = synthetic_rng("imdb", f"{split}-{label}")
    # class-dependent token bias so models can actually learn
    for _ in range(n):
        ln = int(r.randint(8, 40))
        base = r.randint(0, _VOCAB // 2, ln)
        if label:
            base = base + _VOCAB // 2  # positive docs use the upper half
        yield [f"w{t}" for t in base]


def word_dict():
    """token -> id, frequency-ranked (reference imdb.py word_dict)."""
    freq = {}
    if have_file("imdb", _TAR):
        pat = re.compile(r"aclImdb/train/[pn]\w+/.*\.txt$")
        for doc in _real_docs(pat):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
    else:
        for label in (0, 1):
            for doc in _synthetic_docs("train", label):
                for w in doc:
                    freq[w] = freq.get(w, 0) + 1
    ranked = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    wd = {w: i for i, (w, _) in enumerate(ranked)}
    wd["<unk>"] = len(wd)
    return wd


def _reader(split, word_idx):
    unk = word_idx.get("<unk>", len(word_idx) - 1)

    def read():
        if have_file("imdb", _TAR):
            for label, sub in ((0, "neg"), (1, "pos")):
                pat = re.compile(rf"aclImdb/{split}/{sub}/.*\.txt$")
                for doc in _real_docs(pat):
                    yield [word_idx.get(w, unk) for w in doc], label
        else:
            for label in (0, 1):
                for doc in _synthetic_docs(split, label):
                    yield [word_idx.get(w, unk) for w in doc], label

    return read


def train(word_idx):
    return _reader("train", word_idx)


def test(word_idx):
    return _reader("test", word_idx)
