"""Canned dataset readers (reference python/paddle/dataset/: mnist, cifar,
uci_housing, imdb, ... download from public mirrors and yield samples).

This environment has no network egress, so each dataset loads from
$PADDLE_TPU_DATA_HOME/<name>/ when the reference file layout is present and
otherwise yields a DETERMINISTIC SYNTHETIC sample stream with the real
shapes/dtypes/label ranges — the reader CONTRACT (generator of tuples,
paddle.batch-composable) is what the framework tests and examples exercise.
Each reader documents which mode produced its data via `.synthetic`.
"""

from . import (  # noqa: F401
    cifar,
    conll05,
    flowers,
    imdb,
    mnist,
    movielens,
    uci_housing,
    wmt16,
)
from .factory import (  # noqa: F401
    DatasetFactory,
    InMemoryDataset,
    QueueDataset,
)
