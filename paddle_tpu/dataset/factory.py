"""fluid.DatasetFactory / InMemoryDataset / QueueDataset — the
train_from_dataset data path (reference python/paddle/fluid/dataset.py:328,
852 over the C++ Dataset/DataFeed runtime, framework/data_set.h:43).

TPU-native: files parse through the native MultiSlot parser
(paddle_tpu/native, GIL-free C++) into CSR slots, pack to the padded shapes
declared by set_use_var, and stream batches into the jitted train step —
the role of the reference's per-thread DataFeed + HogwildWorker op loop
(hogwild_worker.cc:189) with XLA replacing the per-op interpreter."""

from __future__ import annotations

import numpy as np

from .. import native
from ..core.dtypes import to_numpy_dtype


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._use_vars = []
        self._filelist = []
        self._thread_num = 1

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_use_var(self, var_list):
        self._use_vars = list(var_list)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_pipe_command(self, cmd):
        # the reference piped raw lines through a user command before
        # parsing; with the native parser in-process this stage is unused
        self._pipe_command = cmd

    # -- iteration ---------------------------------------------------------
    def _slot_spec(self, var):
        shape = tuple(var.shape or (1,))
        trailing = shape[1:] if len(shape) > 1 else (1,)
        width = int(np.prod(trailing)) if trailing else 1
        return width, to_numpy_dtype(var.dtype)

    def _records(self):
        num_slots = len(self._use_vars)
        for fname in self._filelist:
            with open(fname, "rb") as f:
                vals, offs = native.parse_multislot(f.read(), num_slots)
            n_records = (len(offs) - 1) // num_slots
            for r in range(n_records):
                row = []
                for s in range(num_slots):
                    c = r * num_slots + s
                    row.append(vals[offs[c]:offs[c + 1]])
                yield row

    def _batches(self, records):
        batch = []
        for row in records:
            batch.append(row)
            if len(batch) == self._batch_size:
                yield self._pack(batch)
                batch = []
        if batch:
            yield self._pack(batch)

    def _pack(self, batch):
        feed = {}
        for s, var in enumerate(self._use_vars):
            width, dtype = self._slot_spec(var)
            counts = [len(row[s]) for row in batch]
            bad = [c for c in counts if c != width]
            if bad:
                raise ValueError(
                    f"slot {s} ({var.name!r}): records hold {sorted(set(bad))} "
                    f"values but the variable declares {width} — refusing to "
                    "truncate/zero-pad silently (reference MultiSlotDataFeed "
                    "fails such batches too)"
                )
            vals = np.concatenate([row[s] for row in batch]) if batch else \
                np.empty(0, np.float32)
            offsets = np.cumsum([0] + counts).astype(np.int64)
            padded, _ = native.pack_padded(
                vals, offsets, width, pad_value=0,
                dtype=np.int64 if np.issubdtype(dtype, np.integer) else
                np.float32,
            )
            feed[var.name] = padded.astype(dtype).reshape(
                (len(batch),) + tuple((var.shape or (1,))[1:] or (1,))
            )
        return feed

    def batches(self):
        yield from self._batches(self._records())


class QueueDataset(DatasetBase):
    """Streaming (reference :852): files parsed on the fly per epoch."""


class InMemoryDataset(DatasetBase):
    """load_into_memory + local/global shuffle (reference :328)."""

    def __init__(self):
        super().__init__()
        self._memory = None

    def load_into_memory(self):
        self._memory = list(self._records())

    def local_shuffle(self, seed=None):
        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        np.random.RandomState(seed).shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=None, seed=0):
        """Every worker shuffles with the SAME seed then takes its
        interleaved shard — the reference shuffled across nodes through
        fleet RPC (data_set.h:111); a shared-seed permutation + rank
        striding is equivalent for shared-filesystem filelists."""
        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        rank, nranks = 0, 1
        if fleet is not None:
            rank, nranks = fleet.worker_index(), fleet.worker_num()
        np.random.RandomState(seed).shuffle(self._memory)
        self._memory = self._memory[rank::nranks]

    def release_memory(self):
        self._memory = None

    def batches(self):
        if self._memory is None:
            raise RuntimeError("call load_into_memory() first")
        yield from self._batches(iter(self._memory))


class DatasetFactory:
    """reference dataset.py DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")
