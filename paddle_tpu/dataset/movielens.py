"""MovieLens-1M reader (reference python/paddle/dataset/movielens.py:
train/test yield (user_id, gender_id, age_id, job_id, movie_id,
category_ids, title_ids, rating); max_user_id/max_movie_id/max_job_id and
the category/title dicts size the embedding tables — the recommender_system
book model's inputs).

Synthetic fallback: deterministic users/movies with a low-rank latent
rating structure so the recommender can actually fit."""

from __future__ import annotations

import numpy as np

from .common import synthetic_rng

_N_USERS = 200
_N_MOVIES = 120
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 256
age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS


def movie_categories():
    return {f"cat{i}": i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(_TITLE_VOCAB)}


def _profiles():
    r = synthetic_rng("movielens", "latent")
    u = r.rand(_N_USERS + 1, 4)
    m = r.rand(_N_MOVIES + 1, 4)
    return u, m


def _reader(split, n=400):
    def read():
        u_lat, m_lat = _profiles()
        r = synthetic_rng("movielens", split)
        for _ in range(n):
            uid = int(r.randint(1, _N_USERS + 1))
            mid = int(r.randint(1, _N_MOVIES + 1))
            gender = uid % 2
            age = int(uid % len(age_table))
            job = uid % _N_JOBS
            cats = [int(mid % _N_CATEGORIES)]
            title = ((np.arange(3) * 31 + mid) % _TITLE_VOCAB).tolist()
            rating = float(
                np.clip(1.0 + 4.0 * u_lat[uid] @ m_lat[mid], 1.0, 5.0)
            )
            yield uid, gender, age, job, mid, cats, title, rating

    return read


def train():
    return _reader("train")


def test():
    return _reader("test", n=100)
