"""MNIST readers (reference python/paddle/dataset/mnist.py: train/test yield
(784-float image in [-1,1], int label))."""

from __future__ import annotations

import gzip
import struct

import numpy as np

from .common import data_path, have_file, synthetic_rng

_N_TRAIN, _N_TEST = 60000, 10000


def _idx_reader(images_gz, labels_gz):
    with gzip.open(labels_gz, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(images_gz, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    for img, lab in zip(images, labels):
        yield (img.astype(np.float32) / 127.5 - 1.0), int(lab)


def _synthetic(split, n):
    rng = synthetic_rng("mnist", split)
    # class-conditional blobs: linearly separable enough that a softmax
    # regression visibly learns (keeps convergence tests meaningful)
    protos = rng.randn(10, 784).astype(np.float32)

    def gen():
        r = synthetic_rng("mnist", split + "-stream")
        for _ in range(n):
            lab = int(r.randint(0, 10))
            img = np.clip(
                protos[lab] * 0.5 + r.randn(784).astype(np.float32) * 0.5,
                -1, 1,
            ).astype(np.float32)
            yield img, lab

    return gen


def _reader(split, images, labels, n):
    if have_file("mnist", images) and have_file("mnist", labels):
        def real():
            return _idx_reader(
                data_path("mnist", images), data_path("mnist", labels)
            )

        real.synthetic = False
        return real
    gen = _synthetic(split, min(n, 2048))
    gen.synthetic = True
    return gen


def train():
    return _reader(
        "train", "train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
        _N_TRAIN,
    )


def test():
    return _reader(
        "test", "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz",
        _N_TEST,
    )
