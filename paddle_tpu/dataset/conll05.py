"""CoNLL-2005 SRL reader (reference python/paddle/dataset/conll05.py:
get_dict() -> (word, verb, label) dicts; test() yields the 9-slot SRL
tuple (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark,
label_ids) the label_semantic_roles book model consumes).

Synthetic fallback: deterministic sentences with IOB label structure —
same slot contract."""

from __future__ import annotations

import numpy as np

from .common import synthetic_rng

WORD_DICT_LEN = 500
VERB_DICT_LEN = 30
LABEL_DICT_LEN = 13  # B-/I- over 6 roles + O


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(VERB_DICT_LEN)}
    labels = ["O"]
    for r in range((LABEL_DICT_LEN - 1) // 2):
        labels += [f"B-A{r}", f"I-A{r}"]
    label_dict = {l: i for i, l in enumerate(labels)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """[WORD_DICT_LEN, 32] deterministic embedding table (the reference
    ships pre-trained emb vectors; synthetic stand-in keeps the shape)."""
    r = synthetic_rng("conll05", "emb")
    return r.rand(WORD_DICT_LEN, 32).astype(np.float32)


def _reader(split, n=150):
    def read():
        r = synthetic_rng("conll05", split)
        for _ in range(n):
            ln = int(r.randint(5, 20))
            words = r.randint(0, WORD_DICT_LEN, ln)
            verb_pos = int(r.randint(0, ln))
            verb = int(r.randint(0, VERB_DICT_LEN))
            ctx = [np.roll(words, k) for k in (2, 1, 0, -1, -2)]
            mark = (np.arange(ln) == verb_pos).astype(np.int64)
            # one argument span near the verb, rest O (label 0)
            labels = np.zeros(ln, np.int64)
            role = int(r.randint(0, (LABEL_DICT_LEN - 1) // 2))
            start = max(0, verb_pos - 2)
            labels[start] = 1 + 2 * role
            if start + 1 < ln:
                labels[start + 1] = 2 + 2 * role
            yield (
                words.astype(np.int64),
                *[c.astype(np.int64) for c in ctx],
                np.full(ln, verb, np.int64),
                mark,
                labels,
            )

    return read


def test():
    return _reader("test")


def train():
    return _reader("train")
