"""Oxford-102 flowers reader (reference python/paddle/dataset/flowers.py:
train/test/valid yield (3x224x224 float image flattened, label in
[0, 102))).

Synthetic fallback: per-class color prototypes + noise at a configurable
resolution (the reference decodes JPEGs through PIL; image decode belongs
to the data pipeline and the synthetic generator keeps the contract
offline)."""

from __future__ import annotations

import numpy as np

from .common import synthetic_rng

N_CLASSES = 102


def _reader(split, n=120, size=224):
    def read():
        protos = synthetic_rng("flowers", "protos").rand(
            N_CLASSES, 3
        ).astype(np.float32)
        r = synthetic_rng("flowers", split)
        for _ in range(n):
            lab = int(r.randint(0, N_CLASSES))
            img = (
                0.7 * protos[lab][:, None, None]
                + 0.3 * r.rand(3, size, size)
            ).astype(np.float32)
            yield img.reshape(-1), lab

    return read


def train(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    return _reader("train")


def test(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    return _reader("test", n=40)


def valid(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    return _reader("valid", n=40)
