"""UCI housing readers (reference python/paddle/dataset/uci_housing.py:
13 normalized float features -> float target)."""

from __future__ import annotations

import numpy as np

from .common import data_path, have_file, synthetic_rng

FEATURE_NUM = 13


def _load_real():
    raw = np.loadtxt(data_path("uci_housing", "housing.data"))
    feats = raw[:, :-1]
    feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
    return feats.astype(np.float32), raw[:, -1:].astype(np.float32)


def _synthetic(split, n=512):
    rng = synthetic_rng("uci_housing", split)
    w = rng.randn(FEATURE_NUM, 1).astype(np.float32)
    x = rng.randn(n, FEATURE_NUM).astype(np.float32)
    y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y


def _reader(split, lo, hi):
    if have_file("uci_housing", "housing.data"):
        x, y = _load_real()
        x, y = x[int(lo * len(x)):int(hi * len(x))], y[int(lo * len(y)):int(hi * len(y))]
        synthetic = False
    else:
        x, y = _synthetic(split)
        synthetic = True

    def reader():
        for xi, yi in zip(x, y):
            yield xi, yi

    reader.synthetic = synthetic
    return reader


def train():
    return _reader("train", 0.0, 0.8)


def test():
    return _reader("test", 0.8, 1.0)
