"""Shared dataset plumbing (reference python/paddle/dataset/common.py)."""

from __future__ import annotations

import os

import numpy as np

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "dataset"),
)


def data_path(name, filename):
    return os.path.join(DATA_HOME, name, filename)


def have_file(name, filename):
    return os.path.exists(data_path(name, filename))


def synthetic_rng(name, split):
    """Deterministic per-(dataset, split) generator for synthetic mode."""
    import zlib

    return np.random.RandomState(
        zlib.crc32(f"{name}:{split}".encode()) & 0x7FFFFFFF
    )
