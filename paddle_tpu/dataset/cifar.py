"""CIFAR-10/100 readers (reference python/paddle/dataset/cifar.py: each
sample = (3072-float image in [0,1], int label))."""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from .common import data_path, have_file, synthetic_rng


def _tar_reader(tar_name, sub_names, label_key):
    def reader():
        with tarfile.open(data_path("cifar", tar_name)) as tf:
            for m in tf.getmembers():
                if not any(s in m.name for s in sub_names):
                    continue
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                for img, lab in zip(d[b"data"], d[label_key]):
                    yield img.astype(np.float32) / 255.0, int(lab)

    return reader


def _synthetic(split, n_classes, n=512):
    protos = synthetic_rng("cifar", f"protos{n_classes}").rand(
        n_classes, 3072
    ).astype(np.float32)

    def gen():
        r = synthetic_rng("cifar", split + str(n_classes))
        for _ in range(n):
            lab = int(r.randint(0, n_classes))
            img = np.clip(
                0.6 * protos[lab] + 0.4 * r.rand(3072), 0, 1
            ).astype(np.float32)
            yield img, lab

    gen.synthetic = True
    return gen


def _make(tar_name, subs, label_key, split, n_classes):
    if have_file("cifar", tar_name):
        r = _tar_reader(tar_name, subs, label_key)
        r.synthetic = False
        return r
    return _synthetic(split, n_classes)


def train10():
    return _make("cifar-10-python.tar.gz", [f"data_batch_{i}" for i in range(1, 6)],
                 b"labels", "train", 10)


def test10():
    return _make("cifar-10-python.tar.gz", ["test_batch"], b"labels", "test", 10)


def train100():
    return _make("cifar-100-python.tar.gz", ["train"], b"fine_labels", "train", 100)


def test100():
    return _make("cifar-100-python.tar.gz", ["test"], b"fine_labels", "test", 100)
