"""WMT16 en-de reader (reference python/paddle/dataset/wmt16.py:
train/test/validation(src_dict_size, trg_dict_size, src_lang) yield
(src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> framing;
get_dict(lang, dict_size) returns the vocab).

Synthetic fallback: deterministic integer "translation" pairs (target =
reversed source shifted into the target vocab) — enough structure for a
seq2seq model to learn, same contract offline."""

from __future__ import annotations

import numpy as np

from .common import synthetic_rng

BOS, EOS, UNK = 0, 1, 2


def get_dict(lang, dict_size, reverse=False):
    d = {"<s>": BOS, "<e>": EOS, "<unk>": UNK}
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _reader(split, src_dict_size, trg_dict_size, n=200):
    def read():
        r = synthetic_rng("wmt16", split)
        for _ in range(n):
            ln = int(r.randint(3, 12))
            src = r.randint(3, src_dict_size, ln)
            # deterministic mapping: "translation" = reversed + re-hashed
            trg = (src[::-1] * 7 + 3) % max(trg_dict_size - 3, 1) + 3
            src_ids = [BOS] + src.tolist() + [EOS]
            trg_ids = [BOS] + trg.tolist()
            trg_next = trg.tolist() + [EOS]
            yield src_ids, trg_ids, trg_next

    return read


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", src_dict_size, trg_dict_size, n=50)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("val", src_dict_size, trg_dict_size, n=50)
