"""Fused multihead attention as a Pallas TPU kernel (flash-attention style).

Replaces the reference's CUDA hand fusion (operators/fused/
multihead_matmul_op.cu, math/bert_encoder_functor.cu) with the TPU
equivalent: one Mosaic kernel per (batch, head) that computes
softmax(QK^T * scale + bias) V without ever writing the [S, S] probability
matrix to HBM. At BERT-base shapes (S=512) the probs tensor is the single
largest HBM stream in the dense formulation; keeping it in VMEM is the
memory-complexity win XLA cannot get on its own (it will not re-associate
softmax across two matmuls).

Semantics match the composed fluid ops exactly (matmul -> softmax ->
dropout -> matmul), including fluid's "downgrade_in_infer" dropout
(train: drop without rescale; infer: scale by 1-p — dropout_op.cc).

Backward is a second Pallas kernel over the same grid that recomputes the
probabilities from (q, k, v) — flash attention's standard recompute trade —
and regenerates the identical dropout mask from the same hardware PRNG seed,
so no mask tensor is ever materialized (the reference saves an explicit
uint8 mask; determinism makes that free here).

Scope: whole-row kernel — each grid step owns a full [S, S] score tile in
VMEM, so S is capped (fp32 scores: S=1024 -> 4 MB). Long-context beyond the
cap is the job of sequence parallelism (parallel/ring_attention.py), which
shards S before attention runs. Dispatch:
  * TPU backend  -> Pallas kernels (fwd + custom-vjp bwd)
  * other        -> jnp reference (same math; CPU tests + sharded fallback)
  * interpret=True forces the kernel through the Mosaic interpreter on CPU
    (kernel-logic tests; the interpreter's prng_random_bits is a zero stub,
    so dropout>0 training is TPU-only through the kernel path).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# whole-row kernel holds an [S, S] fp32 score tile in VMEM
MAX_SEQ = 1024


def supports(seq_len: int, head_dim: int, dtype) -> bool:
    """Can the Pallas kernel take these shapes? (else: jnp reference)."""
    return (
        seq_len % 128 == 0
        and seq_len <= MAX_SEQ
        and head_dim % 8 == 0
        and jnp.dtype(dtype) in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))
    )


def _probs(q, k, bias_row, scale, causal):
    """Softmax probabilities for one head: q [S,D], k [S,D], bias [1,S].

    Matmul inputs keep the MODEL dtype (bf16 under AMP) with fp32
    accumulation (preferred_element_type) — upcasting the inputs would run
    the MXU in fp32 mode at a fraction of bf16 throughput. The [S, S]
    elementwise tail (exp, normalize) follows the model dtype too (see
    _probs_unnorm); the scores and row statistics stay fp32."""
    e, l = _probs_unnorm(q, k, bias_row, scale, causal)
    if e.dtype == jnp.float32:
        return e / l
    # normalize in the compute dtype: a bf16 divide would promote; an
    # [S,1] reciprocal broadcast-mul keeps the full-tile pass in bf16
    return e * (1.0 / l).astype(e.dtype)


def _probs_unnorm(q, k, bias_row, scale, causal):
    """(exp(s - m), rowsum) — normalization deferred so the forward can
    scale the [S, D] output instead of the [S, S] probabilities (one less
    full-tile VPU pass; softmax cost dominates the kernel at D=64).

    Under AMP (bf16 q/k/v) the exp and everything downstream of it on the
    [S, S] tile runs in bf16 — the VPU packs 2x the lanes per op and the
    later MXU cast disappears. The scores, the row max, and the row sum
    (fp32 accumulation) stay fp32, so numerical stability is the standard
    flash-attention argument; what drops to 8 mantissa bits is the
    normalized probabilities (|p| <= 1), ~0.4% relative noise on an op
    whose training-mode consumer is a stochastic regularizer anyway."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    s = s + bias_row
    if causal:
        n = s.shape[0]
        row = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        s = jnp.where(col <= row, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    edt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
    e = jnp.exp((s - m).astype(edt))
    return e, jnp.sum(e, axis=-1, keepdims=True, dtype=jnp.float32)


def _seed_prng(seed_ref):
    # Mosaic accepts at most 2 seed words; mix the (batch, head) grid index
    # in arithmetically (Knuth/Murmur multiplicative constants, uint32 wrap)
    head = (
        pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
    ).astype(jnp.uint32)
    s0 = seed_ref[0] + head * jnp.uint32(0x9E3779B1)
    s1 = seed_ref[1] ^ (head * jnp.uint32(0x85EBCA6B))
    pltpu.prng_seed(s0, s1)


from .prng_mask import keep_mask as _keep_mask  # fwd/bwd mask parity


def _apply_dropout(p, rate, is_test, upscale):
    """fluid dropout semantics on probabilities p (static rate/flags)."""
    if rate == 0.0:
        return p
    if is_test:
        return p if upscale else p * (1.0 - rate)
    keep = _keep_mask(p.shape, rate)
    dropped = jnp.where(keep, p / (1.0 - rate) if upscale else p, 0.0)
    return dropped


def _head_fwd(q, k, v, bias_row, scale, rate, is_test, upscale, causal):
    """One head's attention output [S, D] (fp32). Draws ONE dropout mask
    from the already-seeded PRNG when training with dropout — callers must
    keep the per-head call order identical between forward and backward.

    The softmax division is applied to the [S, D] OUTPUT rows (1/l), not
    the [S, S] probabilities — dropout commutes with the row-scale, so the
    math is identical and a full score-tile VPU pass disappears."""
    e, l = _probs_unnorm(q, k, bias_row, scale, causal)
    e = _apply_dropout(e, rate, is_test, upscale)
    out = jnp.dot(e.astype(v.dtype), v, preferred_element_type=jnp.float32)
    return out / l


def _head_bwd(q, k, v, bias_row, do, scale, rate, is_test, upscale, causal):
    """One head's (dq, dk, dv [S,D] fp32, dbias [1,S]); same single PRNG
    draw as _head_fwd."""
    p = _probs(q, k, bias_row, scale, causal)
    dob = do.astype(v.dtype)  # bf16 MXU inputs, fp32 accumulation
    if rate > 0.0 and not is_test:
        keep = _keep_mask(p.shape, rate)
        inv = 1.0 / (1.0 - rate) if upscale else 1.0
        pm = jnp.where(keep, p * inv, 0.0)
        dpm = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        dp = jnp.where(keep, dpm * inv, 0.0)
    else:
        test_scale = 1.0 if (rate == 0.0 or upscale) else 1.0 - rate
        pm = p * test_scale
        dpm = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        dp = dpm * test_scale
    dv = jnp.dot(pm.astype(v.dtype).T, dob, preferred_element_type=jnp.float32)
    # softmax backward: dS = P * (dP - rowsum(dP * P))
    d = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = p * (dp - d)
    dsb = ds.astype(v.dtype)
    dq = jnp.dot(dsb, k, preferred_element_type=jnp.float32) * scale
    dk = jnp.dot(dsb.T, q, preferred_element_type=jnp.float32) * scale
    return dq, dk, dv, jnp.sum(ds, axis=0, keepdims=True)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref,
                *, scale, rate, is_test, upscale, causal):
    q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
    if rate > 0.0 and not is_test:
        _seed_prng(seed_ref)
    o_ref[0, 0] = _head_fwd(
        q, k, v, bias_ref[0], scale, rate, is_test, upscale, causal
    ).astype(o_ref.dtype)


def _bwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref,
                dq_ref, dk_ref, dv_ref, dbias_ref,
                *, scale, rate, is_test, upscale, causal):
    q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
    do = do_ref[0, 0].astype(jnp.float32)
    if rate > 0.0 and not is_test:
        # identical seeding sequence as _fwd_kernel -> identical mask
        _seed_prng(seed_ref)
    dq, dk, dv, db = _head_bwd(
        q, k, v, bias_ref[0], do, scale, rate, is_test, upscale, causal
    )
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)
    # bias broadcasts over heads and query rows -> grad reduces over both.
    # The h grid axis is innermost, so this output block (indexed by b only)
    # stays resident while heads accumulate into it.
    @pl.when(pl.program_id(1) == 0)
    def _init():
        dbias_ref[0] = db

    @pl.when(pl.program_id(1) != 0)
    def _acc():
        dbias_ref[0] = dbias_ref[0] + db


def _head_spec(S, D):
    return pl.BlockSpec(
        (1, 1, S, D), lambda b, h: (b, h, 0, 0), memory_space=pltpu.VMEM
    )


def _bias_spec(S):
    # bias is passed as [B, 1, S]: a (1, 1, S) block's trailing two dims
    # equal the array's, satisfying Mosaic's (8, 128)-divisibility rule
    return pl.BlockSpec(
        (1, 1, S), lambda b, h: (b, 0, 0), memory_space=pltpu.VMEM
    )


def _pallas_fwd(q, k, v, bias, seed, statics, interpret):
    B, H, S, D = q.shape
    bias = bias.reshape(B, 1, S)
    kern = functools.partial(_fwd_kernel, **statics)
    return pl.pallas_call(
        kern,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _head_spec(S, D),
            _head_spec(S, D),
            _head_spec(S, D),
            _bias_spec(S),
        ],
        out_specs=_head_spec(S, D),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, q, k, v, bias)


def _pallas_bwd(q, k, v, bias, seed, do, statics, interpret):
    B, H, S, D = q.shape
    bias = bias.reshape(B, 1, S)
    kern = functools.partial(_bwd_kernel, **statics)
    dq, dk, dv, dbias = pl.pallas_call(
        kern,
        grid=(B, H),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _head_spec(S, D),
            _head_spec(S, D),
            _head_spec(S, D),
            _bias_spec(S),
            _head_spec(S, D),
        ],
        out_specs=[
            _head_spec(S, D),
            _head_spec(S, D),
            _head_spec(S, D),
            _bias_spec(S),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
            jax.ShapeDtypeStruct(bias.shape, jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, q, k, v, bias, do)
    return dq, dk, dv, dbias.reshape(B, S)


# ---------------------------------------------------------------------------
# packed-QKV variant: reads the fused [B, S, 3H] projection directly
# ---------------------------------------------------------------------------
#
# The [B,H,S,D] kernels above force the model to materialize head-split
# transposes around the custom call (XLA cannot fuse a transpose INTO a
# Mosaic call): at BERT-base that is 8 copies of [B,S,H] per layer per
# step, ~2.4 GB of pure layout traffic. The packed kernels instead index
# the qkv projection output [B, S, 3*H*D] in place — q/k/v are the SAME
# operand passed three times with different column-block index maps — and
# emit [B, S, H*D]. Each grid step owns a 128-lane column group
# (G = 128//D heads) so the lane dimension is full.


def uses_tiled_path(seq_len: int, num_heads: int, head_dim: int, dtype):
    """True when fused_attention_qkv will dispatch the KV-tiled kernel for
    these static properties on the TPU backend — the op builder uses this
    at graph-build time to wire the saved (Out, Lse) into the dedicated
    grad op (ops/fused.py), so it must mirror the dispatch below."""
    from .flash_tiled import supports_tiled

    return (
        jax.default_backend() == "tpu"
        and not supports_packed(seq_len, num_heads, head_dim, dtype)
        and seq_len > MAX_SEQ
        and supports_tiled(seq_len, num_heads, head_dim, dtype)
    )


def supports_packed(seq_len: int, num_heads: int, head_dim: int, dtype):
    g = 128 // head_dim if head_dim and 128 % head_dim == 0 else 0
    return (
        g > 0
        and num_heads % g == 0
        and seq_len % 128 == 0
        and seq_len <= MAX_SEQ
        and jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.bfloat16))
    )


def _group_spec(S, section, num_groups):
    """(1, S, 128) blocks over [B, S, 3*H*D]; section 0/1/2 = q/k/v."""
    return pl.BlockSpec(
        (1, S, 128),
        lambda b, g: (b, 0, section * num_groups + g),
        memory_space=pltpu.VMEM,
    )


def _out_group_spec(S):
    return pl.BlockSpec(
        (1, S, 128), lambda b, g: (b, 0, g), memory_space=pltpu.VMEM
    )


def _bias_spec2(S):
    return pl.BlockSpec(
        (1, 1, S), lambda b, g: (b, 0, 0), memory_space=pltpu.VMEM
    )


def _fwd_kernel_qkv(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref,
                    *, D, scale, rate, is_test, upscale, causal):
    if rate > 0.0 and not is_test:
        _seed_prng(seed_ref)
    qg, kg, vg, bias = q_ref[0], k_ref[0], v_ref[0], bias_ref[0]
    for i in range(128 // D):
        sl = slice(i * D, (i + 1) * D)
        o_ref[0, :, sl] = _head_fwd(
            qg[:, sl], kg[:, sl], vg[:, sl], bias,
            scale, rate, is_test, upscale, causal,
        ).astype(o_ref.dtype)


def _bwd_kernel_qkv(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref,
                    dq_ref, dk_ref, dv_ref, dbias_ref,
                    *, D, scale, rate, is_test, upscale, causal):
    if rate > 0.0 and not is_test:
        # same seed + same per-head draw order as _fwd_kernel_qkv
        _seed_prng(seed_ref)
    qg, kg, vg, bias = q_ref[0], k_ref[0], v_ref[0], bias_ref[0]
    db_total = jnp.zeros((1, bias.shape[-1]), jnp.float32)
    for i in range(128 // D):
        sl = slice(i * D, (i + 1) * D)
        do = do_ref[0, :, sl].astype(jnp.float32)
        dq, dk, dv, db = _head_bwd(
            qg[:, sl], kg[:, sl], vg[:, sl], bias, do,
            scale, rate, is_test, upscale, causal,
        )
        dq_ref[0, :, sl] = dq.astype(dq_ref.dtype)
        dk_ref[0, :, sl] = dk.astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)
        db_total = db_total + db

    @pl.when(pl.program_id(1) == 0)
    def _init():
        dbias_ref[0] = db_total

    @pl.when(pl.program_id(1) != 0)
    def _acc():
        dbias_ref[0] = dbias_ref[0] + db_total


def _pallas_fwd_qkv(qkv, bias, seed, H, D, statics, interpret):
    B, S, _ = qkv.shape
    num_groups = H * D // 128
    bias = bias.reshape(B, 1, S)
    kern = functools.partial(_fwd_kernel_qkv, D=D, **statics)
    return pl.pallas_call(
        kern,
        grid=(B, num_groups),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _group_spec(S, 0, num_groups),
            _group_spec(S, 1, num_groups),
            _group_spec(S, 2, num_groups),
            _bias_spec2(S),
        ],
        out_specs=_out_group_spec(S),
        out_shape=jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, qkv, qkv, qkv, bias)


def _pallas_bwd_qkv(qkv, bias, seed, do, H, D, statics, interpret):
    B, S, _ = qkv.shape
    num_groups = H * D // 128
    bias = bias.reshape(B, 1, S)
    kern = functools.partial(_bwd_kernel_qkv, D=D, **statics)
    dq, dk, dv, dbias = pl.pallas_call(
        kern,
        grid=(B, num_groups),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _group_spec(S, 0, num_groups),
            _group_spec(S, 1, num_groups),
            _group_spec(S, 2, num_groups),
            _bias_spec2(S),
            _out_group_spec(S),
        ],
        out_specs=[
            _out_group_spec(S),
            _out_group_spec(S),
            _out_group_spec(S),
            _bias_spec2(S),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, 1, S), jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, qkv, qkv, qkv, bias, do)
    dqkv = jnp.concatenate([dq, dk, dv], axis=-1)
    return dqkv, dbias.reshape(B, S)


def _reference_qkv(qkv, bias, rng_key, H, **statics):
    B, S, three_hd = qkv.shape
    D = three_hd // 3 // H
    q, k, v = _unpack_qkv(qkv, H)
    out = _reference(q, k, v, bias, rng_key, **statics)
    return out.transpose(0, 2, 1, 3).reshape(B, S, H * D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_qkv(qkv, bias, seed, H, D, statics, interpret):
    return _pallas_fwd_qkv(qkv, bias, seed, H, D, dict(statics), interpret)


def _flash_qkv_fwd(qkv, bias, seed, H, D, statics, interpret):
    out = _pallas_fwd_qkv(qkv, bias, seed, H, D, dict(statics), interpret)
    return out, (qkv, bias, seed)


def _flash_qkv_bwd(H, D, statics, interpret, res, g):
    qkv, bias, seed = res
    dqkv, dbias = _pallas_bwd_qkv(
        qkv, bias, seed, g, H, D, dict(statics), interpret
    )
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dqkv, dbias, dseed


_flash_qkv.defvjp(_flash_qkv_fwd, _flash_qkv_bwd)


def fused_attention_qkv(
    qkv,
    num_heads,
    key_bias=None,
    *,
    scale=None,
    dropout_rate=0.0,
    is_test=True,
    dropout_implementation="downgrade_in_infer",
    causal=False,
    rng_key=None,
    interpret=False,
    force_reference=False,
    return_lse=False,
):
    """Attention over a packed qkv projection [B, S, 3*H*D] -> [B, S, H*D].
    Same semantics as fused_attention; the packed layout avoids every
    head-split transpose/copy around the kernel. With return_lse=True the
    TILED path returns (out, lse) so a later backward can skip the
    forward re-run; every other path returns (out, None)."""
    from .. import observability as _obs

    _obs.add("kernels.fused_attention_qkv")
    B, S, three_hd = qkv.shape
    D = three_hd // 3 // num_heads
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    statics = dict(
        scale=float(scale),
        rate=float(dropout_rate),
        is_test=bool(is_test),
        upscale=dropout_implementation == "upscale_in_train",
        causal=bool(causal),
    )
    bias = (
        jnp.zeros((B, S), jnp.float32)
        if key_bias is None
        else key_bias.astype(jnp.float32)
    )
    training_dropout = dropout_rate > 0.0 and not is_test
    if rng_key is None:
        if training_dropout:
            raise ValueError("fused_attention_qkv: dropout needs rng_key")
        rng_key = jax.random.key(0)
    use_pallas = (
        not force_reference
        and (interpret or jax.default_backend() == "tpu")
        and supports_packed(S, num_heads, D, qkv.dtype)
    )
    if not use_pallas:
        from .flash_tiled import flash_tiled, supports_tiled

        if (
            not force_reference
            and (interpret or jax.default_backend() == "tpu")
            and S > MAX_SEQ
            and supports_tiled(S, num_heads, D, qkv.dtype)
        ):
            # beyond the whole-row cap: KV-tiled online-softmax kernel
            # (flash_tiled.py) — same packed layout, any S
            if interpret and training_dropout:
                raise ValueError(
                    "fused_attention_qkv: training dropout is unsupported "
                    "in interpret mode (interpreter PRNG is a stub)"
                )
            seed = _seed_words(rng_key)
            if return_lse:
                from .flash_tiled import flash_tiled_outs

                return flash_tiled_outs(
                    qkv, bias, seed, num_heads, D, tuple(statics.items()),
                    interpret,
                )
            return flash_tiled(
                qkv, bias, seed, num_heads, D, tuple(statics.items()),
                interpret,
            )
        if (
            not force_reference
            and not interpret
            and jax.default_backend() == "tpu"
            and supports(S, D, qkv.dtype)
        ):
            # packed layout unsupported (e.g. odd head grouping) but the
            # 4-D kernel can run: pay the unpack transposes, never the
            # dense [B,H,S,S]-in-HBM cliff
            q, k, v = _unpack_qkv(qkv, num_heads)
            seed = _seed_words(rng_key)
            out4 = _flash(q, k, v, bias, seed, tuple(statics.items()), False)
            B_, H_, S_, D_ = out4.shape
            out = out4.transpose(0, 2, 1, 3).reshape(B_, S_, H_ * D_)
            return (out, None) if return_lse else out
        out = _reference_qkv(qkv, bias, rng_key, num_heads, **statics)
        return (out, None) if return_lse else out
    if interpret and training_dropout:
        raise ValueError(
            "fused_attention_qkv: training dropout is unsupported in "
            "interpret mode (interpreter PRNG is a stub)"
        )
    seed = _seed_words(rng_key)
    out = _flash_qkv(
        qkv, bias, seed, num_heads, D, tuple(statics.items()), interpret
    )
    return (out, None) if return_lse else out


def _unpack_qkv(qkv, H):
    B, S, three_hd = qkv.shape
    D = three_hd // 3 // H
    def part(i):
        sec = qkv[..., i * H * D:(i + 1) * H * D]
        return sec.reshape(B, S, H, D).transpose(0, 2, 1, 3)
    return part(0), part(1), part(2)


def attention_grads_qkv(qkv, num_heads, key_bias, d_out, rng_key, *,
                        scale=None, dropout_rate=0.0, is_test=True,
                        dropout_implementation="downgrade_in_infer",
                        causal=False, force_reference=False,
                        interpret=False, saved_out=None, saved_lse=None):
    """(dqkv, dbias) without re-running the forward kernel (see
    attention_grads)."""
    B, S, three_hd = qkv.shape
    D = three_hd // 3 // num_heads
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    statics = dict(
        scale=float(scale),
        rate=float(dropout_rate),
        is_test=bool(is_test),
        upscale=dropout_implementation == "upscale_in_train",
        causal=bool(causal),
    )
    bias = (
        jnp.zeros((B, S), jnp.float32)
        if key_bias is None
        else key_bias.astype(jnp.float32)
    )
    if rng_key is None:
        if dropout_rate > 0.0 and not is_test:
            # silently substituting a fixed key would draw a mask UNRELATED
            # to the forward's -> silently wrong gradients
            raise ValueError("attention_grads_qkv: dropout needs rng_key")
        rng_key = jax.random.key(0)
    if interpret and dropout_rate > 0.0 and not is_test:
        # interpreter PRNG is a zero stub -> mask unrelated to any forward
        raise ValueError(
            "attention_grads_qkv: training dropout is unsupported in "
            "interpret mode (interpreter PRNG is a stub)"
        )
    use_pallas = (
        not force_reference
        and (interpret or jax.default_backend() == "tpu")
        and supports_packed(S, num_heads, D, qkv.dtype)
    )
    if use_pallas:
        seed = _seed_words(rng_key)
        return _pallas_bwd_qkv(
            qkv, bias, seed, d_out, num_heads, D, statics, interpret
        )
    from .flash_tiled import flash_tiled_bwd, flash_tiled_fwd, supports_tiled

    if (
        not force_reference
        and (interpret or jax.default_backend() == "tpu")
        and S > MAX_SEQ
        and supports_tiled(S, num_heads, D, qkv.dtype)
    ):
        seed = _seed_words(rng_key)
        if saved_out is not None and saved_lse is not None:
            # the forward op saved (out, lse): straight to the two-kernel
            # tiled backward — no forward re-run (a full extra fwd per
            # layer per step otherwise; XLA does not CSE custom calls)
            out, lse = saved_out, saved_lse
        else:
            out, lse = flash_tiled_fwd(
                qkv, bias, seed, num_heads, D, statics, interpret
            )
        return flash_tiled_bwd(
            qkv, bias, seed, d_out, out, lse, num_heads, D, statics,
            interpret,
        )
    if (
        not force_reference
        and not interpret
        and jax.default_backend() == "tpu"
        and supports(S, D, qkv.dtype)
    ):
        # mirror fused_attention_qkv's 4-D kernel fallback exactly (same
        # seed -> same dropout masks as the forward it pairs with)
        q, k, v = _unpack_qkv(qkv, num_heads)
        do4 = d_out.reshape(B, S, num_heads, D).transpose(0, 2, 1, 3)
        seed = _seed_words(rng_key)
        dq, dk, dv, dbias = _pallas_bwd(
            q, k, v, bias, seed, do4, statics, False
        )
        def pack(t):
            return t.transpose(0, 2, 1, 3).reshape(B, S, num_heads * D)
        return jnp.concatenate([pack(dq), pack(dk), pack(dv)], -1), dbias
    _, vjp = jax.vjp(
        lambda qkv_, b_: _reference_qkv(qkv_, b_, rng_key, num_heads,
                                        **statics),
        qkv, bias,
    )
    return vjp(d_out)


def _reference(q, k, v, bias, rng_key, *, scale, rate, is_test, upscale,
               causal):
    """Same math as the kernels in plain jnp (CPU path / oracle). Dropout
    masks come from jax.random instead of the TPU hardware PRNG — same
    distribution, different stream."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale
    s = s + bias[:, None, None, :]
    if causal:
        S = s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        s = jnp.where((col <= row)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if rate > 0.0:
        if is_test:
            p = p if upscale else p * (1.0 - rate)
        else:
            keep = jax.random.bernoulli(rng_key, 1.0 - rate, p.shape)
            p = jnp.where(keep, p / (1.0 - rate) if upscale else p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def attention_grads(q, k, v, key_bias, d_out, rng_key, *, scale=None,
                    dropout_rate=0.0, is_test=True,
                    dropout_implementation="downgrade_in_infer",
                    causal=False, force_reference=False, interpret=False):
    """(dq, dk, dv, dbias) for fused_attention, computed WITHOUT re-running
    the forward kernel — the flash backward needs no forward residuals.
    Used by the fused_multihead_attention_grad op so training programs run
    one forward + one backward Mosaic call (XLA does not CSE custom-calls,
    so the generic vjp-replay pattern would pay the forward twice)."""
    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    statics = dict(
        scale=float(scale),
        rate=float(dropout_rate),
        is_test=bool(is_test),
        upscale=dropout_implementation == "upscale_in_train",
        causal=bool(causal),
    )
    bias = (
        jnp.zeros((B, S), jnp.float32)
        if key_bias is None
        else key_bias.astype(jnp.float32)
    )
    if rng_key is None:
        if dropout_rate > 0.0 and not is_test:
            # a substitute key would draw a mask unrelated to the forward's
            raise ValueError("attention_grads: dropout needs rng_key")
        rng_key = jax.random.key(0)
    if interpret and dropout_rate > 0.0 and not is_test:
        raise ValueError(
            "attention_grads: training dropout is unsupported in interpret "
            "mode (interpreter PRNG is a stub)"
        )
    use_pallas = not force_reference and (
        interpret
        or (jax.default_backend() == "tpu" and supports(S, D, q.dtype))
    )
    if use_pallas:
        seed = _seed_words(rng_key)
        return _pallas_bwd(q, k, v, bias, seed, d_out, statics, interpret)
    _, vjp = jax.vjp(
        lambda q_, k_, v_, b_: _reference(q_, k_, v_, b_, rng_key, **statics),
        q, k, v, bias,
    )
    return vjp(d_out)


def _seed_words(rng_key):
    seed = jnp.ravel(jax.random.key_data(rng_key)).astype(jnp.uint32)[:2]
    if seed.shape[0] < 2:  # rbg/other impls may expose a single word
        seed = jnp.concatenate([seed, jnp.zeros(1, jnp.uint32)])
    return seed


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash(q, k, v, bias, seed, statics, interpret):
    return _pallas_fwd(q, k, v, bias, seed, dict(statics), interpret)


def _flash_fwd(q, k, v, bias, seed, statics, interpret):
    out = _pallas_fwd(q, k, v, bias, seed, dict(statics), interpret)
    return out, (q, k, v, bias, seed)


def _flash_bwd(statics, interpret, res, g):
    q, k, v, bias, seed = res
    dq, dk, dv, dbias = _pallas_bwd(
        q, k, v, bias, seed, g, dict(statics), interpret
    )
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def fused_attention(
    q,
    k,
    v,
    key_bias=None,
    *,
    scale=None,
    dropout_rate=0.0,
    is_test=True,
    dropout_implementation="downgrade_in_infer",
    causal=False,
    rng_key=None,
    interpret=False,
    force_reference=False,
):
    """softmax(q k^T * scale + key_bias) v with fused dropout.

    q, k, v: [B, H, S, D]; key_bias: additive [B, S] fp32 (e.g. padding mask
    as 0 / -1e4), broadcast over heads and query positions. Differentiable
    in q, k, v, key_bias. `rng_key` (a jax PRNG key) feeds dropout; required
    when dropout_rate > 0 and not is_test.
    """
    from .. import observability as _obs

    _obs.add("kernels.fused_attention")
    B, H, S, D = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    upscale = dropout_implementation == "upscale_in_train"
    statics = dict(
        scale=float(scale),
        rate=float(dropout_rate),
        is_test=bool(is_test),
        upscale=upscale,
        causal=bool(causal),
    )
    if key_bias is None:
        bias = jnp.zeros((B, S), jnp.float32)
    else:
        bias = key_bias.astype(jnp.float32)
    training_dropout = dropout_rate > 0.0 and not is_test
    if rng_key is None:
        if training_dropout:
            raise ValueError("fused_attention: dropout needs rng_key")
        rng_key = jax.random.key(0)
    use_pallas = not force_reference and (
        interpret
        or (jax.default_backend() == "tpu" and supports(S, D, q.dtype))
    )
    if not use_pallas:
        return _reference(q, k, v, bias, rng_key, **statics)
    if interpret and training_dropout:
        # the Mosaic interpreter's prng_random_bits is a zero stub: every
        # probability would be dropped and the kernel would silently return
        # zeros — refuse instead
        raise ValueError(
            "fused_attention: training dropout is unsupported in interpret "
            "mode (interpreter PRNG is a stub); test dropout on TPU or via "
            "the jnp reference path (force_reference=True)"
        )
    seed = _seed_words(rng_key)
    return _flash(q, k, v, bias, seed, tuple(statics.items()), interpret)
