"""Shared hardware-PRNG Bernoulli keep-mask for the Pallas kernels.

Draws HALF a 32-bit word per mask element when the row count allows: a
(r/2, c) uint32 draw supplies two 16-bit subwords stacked along rows. The
PRNG pass over the score tile was one of the profiled VPU limiters
(BASELINE.md round 3); 16-bit thresholds quantize the drop rate to 2^-16
(worst-case bias 1.5e-5 — invisible next to the rate itself).

Every kernel pair that regenerates a mask (forward/backward of
flash_attention, the fwd/dkv/dq trio of flash_tiled, fused_residual)
imports THIS function, so the word stream — and therefore the mask — is
identical by construction wherever the seed and shape agree.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu


def keep_mask(shape, rate):
    r, c = shape
    if r % 2 == 0:
        bits = pltpu.bitcast(pltpu.prng_random_bits((r // 2, c)), jnp.uint32)
        t = np.uint32(min(int(rate * 65536.0), 0xFFFF))
        return jnp.concatenate(
            [bits >> 16 >= t, (bits & jnp.uint32(0xFFFF)) >= t], axis=0
        )
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    # clamp to uint32 range: rate=1.0 would otherwise overflow (keeping a
    # ~2^-32 sliver of probability mass is the cost of the clamp)
    thresh = np.uint32(min(int(rate * 2**32), 0xFFFFFFFF))
    return bits >= thresh
