"""LayerNorm as Pallas TPU kernels (forward + backward).

Reference parity: operators/layer_norm_op.cc (+ the fused CUDA kernels in
layer_norm_op.cu). Motivation here is HBM traffic, not FLOPs: the jnp
formulation under AMP converts the bf16 activation to fp32 for the
mean/var/normalize chain, and XLA materializes fp32 temporaries between
the passes (profiled as the largest non-matmul cost in the BERT step).
The kernel reads each row block once, keeps the fp32 statistics in
registers, and writes bf16 — one read + one write per pass.

Backward recomputes the row statistics from x (cheaper than saving them:
one extra in-register reduction vs an HBM round-trip of mean/rstd) and
accumulates dscale/dbias across row-blocks in a resident output block
(the grid's row axis is innermost for those outputs).

Shapes: x [R, N] (callers flatten leading dims); N % 128 == 0 and the
row-block divides R. Dispatch mirrors flash_attention: Pallas on TPU,
jnp reference elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_BLOCK = 256


def supports(rows: int, n: int, dtype) -> bool:
    return (
        n % 128 == 0
        and n <= 8192
        and rows % 8 == 0
        and jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.bfloat16))
    )


def _row_block(rows):
    blk = min(ROW_BLOCK, rows)
    while rows % blk:
        blk //= 2
    return max(blk, 1)


def _fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, var_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x), axis=1, keepdims=True) - jnp.square(mean)
    var = jnp.maximum(var, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * scale_ref[0] + bias_ref[0]
    y_ref[:] = y.astype(y_ref.dtype)
    # stats kept 2-D [rows, 1]: 1-D outputs would need their block tiled
    # to XLA's 1-D layout (T(1024)), which Mosaic rejects
    mean_ref[:] = mean
    var_ref[:] = var


def _bwd_kernel(x_ref, scale_ref, dy_ref, dx_ref, dscale_ref, dbias_ref,
                *, eps):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.mean(jnp.square(x), axis=1, keepdims=True) - jnp.square(mean)
    rstd = jax.lax.rsqrt(jnp.maximum(var, 0.0) + eps)
    xhat = (x - mean) * rstd
    dyw = dy * scale_ref[0].astype(jnp.float32)
    m1 = jnp.mean(dyw, axis=1, keepdims=True)
    m2 = jnp.mean(dyw * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (dyw - m1 - xhat * m2)).astype(dx_ref.dtype)
    ds = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db = jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dscale_ref[:] = ds
        dbias_ref[:] = db

    @pl.when(pl.program_id(0) != 0)
    def _acc():
        dscale_ref[:] = dscale_ref[:] + ds
        dbias_ref[:] = dbias_ref[:] + db


def _vec_spec(n):
    return pl.BlockSpec((1, n), lambda r: (0, 0), memory_space=pltpu.VMEM)


def layer_norm_fwd(x2d, scale, bias, eps, interpret=False):
    """(y, mean, var) over rows of x2d [R, N]; scale/bias [N] or None."""
    from .. import observability as _obs

    _obs.add("kernels.layer_norm")
    R, N = x2d.shape
    if scale is None:
        scale = jnp.ones((N,), jnp.float32)
    if bias is None:
        bias = jnp.zeros((N,), jnp.float32)
    blk = _row_block(R)
    y, mean, var = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=float(eps)),
        grid=(R // blk,),
        in_specs=[
            pl.BlockSpec((blk, N), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
            _vec_spec(N),
            _vec_spec(N),
        ],
        out_specs=[
            pl.BlockSpec((blk, N), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk, 1), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk, 1), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, N), x2d.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x2d, scale.reshape(1, N), bias.reshape(1, N))
    return y, mean.reshape(R), var.reshape(R)


def layer_norm_bwd(x2d, scale, d_y, eps, interpret=False):
    """(dx, dscale, dbias); statistics recomputed from x2d."""
    R, N = x2d.shape
    if scale is None:
        scale = jnp.ones((N,), jnp.float32)
    blk = _row_block(R)
    dx, ds, db = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=float(eps)),
        grid=(R // blk,),
        in_specs=[
            pl.BlockSpec((blk, N), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
            _vec_spec(N),
            pl.BlockSpec((blk, N), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((blk, N), lambda r: (r, 0),
                         memory_space=pltpu.VMEM),
            _vec_spec(N),
            _vec_spec(N),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, N), x2d.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x2d, scale.reshape(1, N), d_y)
    return dx, ds.reshape(N), db.reshape(N)


# custom VJP so ANY differentiation path through the Pallas forward works
# (the dedicated layer_norm_grad op is the fast path; the generic __vjp__
# fallback and the dygraph tape differentiate the emitter directly, and a
# pallas_call has no built-in differentiation rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm_fwd_diff(x2d, scale, bias, eps, interpret=False):
    return layer_norm_fwd(x2d, scale, bias, eps, interpret)


def _lnd_fwd(x2d, scale, bias, eps, interpret):
    out = layer_norm_fwd(x2d, scale, bias, eps, interpret)
    return out, (x2d, scale)


def _lnd_bwd(eps, interpret, res, cts):
    x2d, scale = res
    dy, dmean, dvar = cts
    dx, ds, db = layer_norm_bwd(x2d, scale, dy, eps, interpret)
    # rare cotangents on the statistics outputs (only when a loss consumes
    # Mean/Variance directly): mean = sum(x)/N, var = E[x^2] - mean^2
    n = x2d.shape[1]
    xf = x2d.astype(jnp.float32)
    mean = jnp.mean(xf, axis=1, keepdims=True)
    extra = dmean[:, None] / n + dvar[:, None] * 2.0 * (xf - mean) / n
    dx = (dx.astype(jnp.float32) + extra).astype(dx.dtype)
    return dx, ds.astype(scale.dtype), db.astype(scale.dtype)


layer_norm_fwd_diff.defvjp(_lnd_fwd, _lnd_bwd)


def reference_fwd(x2d, scale, bias, eps):
    """jnp oracle with identical math (fp32 stats, E[x^2]-E[x]^2)."""
    xf = x2d.astype(jnp.float32)
    mean = jnp.mean(xf, axis=1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(xf), axis=1, keepdims=True) - jnp.square(mean),
        0.0,
    )
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x2d.dtype), mean[:, 0], var[:, 0]
