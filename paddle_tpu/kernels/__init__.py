"""Hand-written Pallas TPU kernels for ops where XLA fusion is not enough.

The reference hand-fused its hot paths in CUDA (operators/fused/
multihead_matmul_op.cu, fused_embedding_seq_pool, bert_encoder_functor.cu);
here the same role is played by Pallas/Mosaic kernels. Most ops do NOT need
this — the whole-block jit executor already lets XLA fuse elementwise chains
into matmuls — so kernels live here only when they change the memory-traffic
complexity class (e.g. flash attention: O(S^2) HBM -> O(S)).
"""

from .flash_attention import fused_attention  # noqa: F401
