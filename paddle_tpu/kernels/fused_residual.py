"""Fused dropout + residual add + LayerNorm as Pallas TPU kernels.

The transformer residual tail `LN(x + dropout(y))` appears twice per
encoder layer. Composed, it costs XLA four+ HBM passes per site (dropout
RNG + apply, mask store, add, fp32 normalization with a separate reduce
pass — profiled ~0.4 ms/site forward on BERT-base, BASELINE.md round 4);
fused it is one read of x and y and one write of out, with the dropout
mask regenerated from the hardware PRNG and the fp32 row statistics held
in registers.

This is the TPU analogue of the reference's hand-fused CUDA residual
kernels (operators/fused/fused_embedding_eltwise_layernorm, and the
add+LN fusions in math/bert_encoder_functor.cu): the fusion XLA cannot
get on its own because the RNG draw and the row reduction sit between
producer and consumer.

Backward recomputes z = x + dropout(y) and the row statistics from the
primal inputs (one extra in-register pass vs an HBM round-trip of
mean/rstd and z), regenerating the identical dropout mask from the same
per-row-block PRNG seeding — so no mask and no intermediate tensor ever
reach HBM.

Shapes: callers flatten to [R, N]; N % 128 == 0, R % 8 == 0 (else the
jnp reference path runs). Dropout semantics are fluid's dropout_op.cc,
as in flash_attention.py.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 256 rows x 8K cols max keeps the bwd kernel (x, y, dout in VMEM + fp32
# z/zhat temporaries) under the 16 MB scoped-vmem limit even when one
# operand arrives fp32 (mixed AMP boundaries)
ROW_BLOCK = 256


def supports(rows: int, n: int, dtype) -> bool:
    return (
        n % 128 == 0
        and n <= 8192
        and rows % 8 == 0
        and jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.bfloat16))
    )


def _row_block(rows):
    blk = min(ROW_BLOCK, rows)
    while rows % blk:
        blk //= 2
    return max(blk, 2)


def _seed_block(seed_ref):
    r = pl.program_id(0).astype(jnp.uint32)
    pltpu.prng_seed(
        seed_ref[0] + r * jnp.uint32(0x9E3779B1),
        seed_ref[1] ^ (r * jnp.uint32(0x85EBCA6B)),
    )


from .prng_mask import keep_mask as _keep_mask  # fwd/bwd mask parity


def _z_block(x_ref, y_ref, seed_ref, rate, is_test, upscale):
    """(fp32 z = x + dropout(y), keep mask or None) for one row block;
    seeds + draws the PRNG exactly once when training with dropout, so
    the forward and backward kernels regenerate the identical mask."""
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    keep = None
    if rate > 0.0:
        if is_test:
            y = y if upscale else y * (1.0 - rate)
        else:
            _seed_block(seed_ref)
            keep = _keep_mask(y.shape, rate)
            y = jnp.where(keep, y / (1.0 - rate) if upscale else y, 0.0)
    return x + y, keep


def _fwd_kernel(seed_ref, x_ref, y_ref, g_ref, c_ref, o_ref,
                *, rate, is_test, upscale, eps):
    z, _ = _z_block(x_ref, y_ref, seed_ref, rate, is_test, upscale)
    mean = jnp.mean(z, axis=1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(z), axis=1, keepdims=True) - jnp.square(mean), 0.0
    )
    rstd = jax.lax.rsqrt(var + eps)
    zhat = (z - mean) * rstd
    o_ref[:] = (
        zhat * g_ref[0].astype(jnp.float32) + c_ref[0].astype(jnp.float32)
    ).astype(o_ref.dtype)


def _bwd_kernel(seed_ref, x_ref, y_ref, g_ref, do_ref,
                dx_ref, dy_ref, dg_ref, dc_ref,
                *, rate, is_test, upscale, eps):
    z, keep = _z_block(x_ref, y_ref, seed_ref, rate, is_test, upscale)
    drop_scale = (1.0 / (1.0 - rate)) if upscale else 1.0
    mean = jnp.mean(z, axis=1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(z), axis=1, keepdims=True) - jnp.square(mean), 0.0
    )
    rstd = jax.lax.rsqrt(var + eps)
    zhat = (z - mean) * rstd
    do = do_ref[:].astype(jnp.float32)
    dyw = do * g_ref[0].astype(jnp.float32)
    m1 = jnp.mean(dyw, axis=1, keepdims=True)
    m2 = jnp.mean(dyw * zhat, axis=1, keepdims=True)
    dz = rstd * (dyw - m1 - zhat * m2)
    dx_ref[:] = dz.astype(dx_ref.dtype)
    if rate > 0.0:
        if is_test:
            dy = dz if upscale else dz * (1.0 - rate)
        else:
            dy = jnp.where(keep, dz * drop_scale, 0.0)
    else:
        dy = dz
    dy_ref[:] = dy.astype(dy_ref.dtype)
    dg = jnp.sum(do * zhat, axis=0, keepdims=True)
    dc = jnp.sum(do, axis=0, keepdims=True)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = dg
        dc_ref[:] = dc

    @pl.when(pl.program_id(0) != 0)
    def _acc():
        dg_ref[:] = dg_ref[:] + dg
        dc_ref[:] = dc_ref[:] + dc


def _vec_spec(n):
    return pl.BlockSpec((1, n), lambda r: (0, 0), memory_space=pltpu.VMEM)


def _row_spec(blk, n):
    return pl.BlockSpec((blk, n), lambda r: (r, 0), memory_space=pltpu.VMEM)


def fused_dropout_add_ln_fwd(x2d, y2d, g, c, seed, rate, is_test, upscale,
                             eps, interpret=False):
    R, N = x2d.shape
    if g is None:
        g = jnp.ones((N,), jnp.float32)
    if c is None:
        c = jnp.zeros((N,), jnp.float32)
    blk = _row_block(R)
    kern = functools.partial(
        _fwd_kernel, rate=float(rate), is_test=bool(is_test),
        upscale=bool(upscale), eps=float(eps),
    )
    return pl.pallas_call(
        kern,
        grid=(R // blk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _row_spec(blk, N),
            _row_spec(blk, N),
            _vec_spec(N),
            _vec_spec(N),
        ],
        out_specs=_row_spec(blk, N),
        out_shape=jax.ShapeDtypeStruct((R, N), x2d.dtype),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, x2d, y2d, g.reshape(1, N), c.reshape(1, N))


def fused_dropout_add_ln_bwd(x2d, y2d, g, seed, d_out, rate, is_test,
                             upscale, eps, interpret=False):
    """-> (dx [R,N], dy [R,N], dscale [N] f32, dlnbias [N] f32)."""
    R, N = x2d.shape
    if g is None:
        g = jnp.ones((N,), jnp.float32)
    blk = _row_block(R)
    kern = functools.partial(
        _bwd_kernel, rate=float(rate), is_test=bool(is_test),
        upscale=bool(upscale), eps=float(eps),
    )
    dx, dy, dg, dc = pl.pallas_call(
        kern,
        grid=(R // blk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _row_spec(blk, N),
            _row_spec(blk, N),
            _vec_spec(N),
            _row_spec(blk, N),
        ],
        out_specs=[
            _row_spec(blk, N),
            _row_spec(blk, N),
            _vec_spec(N),
            _vec_spec(N),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, N), x2d.dtype),
            jax.ShapeDtypeStruct((R, N), y2d.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, x2d, y2d, g.reshape(1, N), d_out)
    return dx, dy, dg.reshape(N), dc.reshape(N)


# differentiable wrapper (dygraph tape / any jax.vjp path); the static
# graph uses the dedicated fused_dropout_add_ln_grad op instead so the
# forward kernel is not replayed (XLA does not CSE custom-calls)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_dropout_add_ln(x2d, y2d, g, c, seed, statics, interpret):
    from .. import observability as _obs

    _obs.add("kernels.fused_dropout_add_ln")
    st = dict(statics)
    return fused_dropout_add_ln_fwd(
        x2d, y2d, g, c, seed, st["rate"], st["is_test"], st["upscale"],
        st["eps"], interpret,
    )


def _fdal_fwd(x2d, y2d, g, c, seed, statics, interpret):
    out = fused_dropout_add_ln(x2d, y2d, g, c, seed, statics, interpret)
    return out, (x2d, y2d, g, c, seed)


def _fdal_bwd(statics, interpret, res, dout):
    x2d, y2d, g, c, seed = res
    st = dict(statics)
    dx, dy, dg, dc = fused_dropout_add_ln_bwd(
        x2d, y2d, g, seed, dout, st["rate"], st["is_test"], st["upscale"],
        st["eps"], interpret,
    )
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dx, dy, dg.astype(g.dtype), dc.astype(c.dtype), dseed


fused_dropout_add_ln.defvjp(_fdal_fwd, _fdal_bwd)


def reference_fwd(x2d, y2d, g, c, rng_key, rate, is_test, upscale, eps):
    """jnp oracle (CPU path): same math, mask from jax.random."""
    x = x2d.astype(jnp.float32)
    y = y2d.astype(jnp.float32)
    if rate > 0.0:
        if is_test:
            y = y if upscale else y * (1.0 - rate)
        else:
            keep = jax.random.bernoulli(rng_key, 1.0 - rate, y.shape)
            y = jnp.where(keep, y / (1.0 - rate) if upscale else y, 0.0)
    z = x + y
    mean = jnp.mean(z, axis=1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(z), axis=1, keepdims=True) - jnp.square(mean), 0.0
    )
    zhat = (z - mean) * jax.lax.rsqrt(var + eps)
    out = zhat
    if g is not None:
        out = out * g.astype(jnp.float32)
    if c is not None:
        out = out + c.astype(jnp.float32)
    return out.astype(x2d.dtype)
