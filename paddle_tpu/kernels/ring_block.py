"""Per-shard flash-attention blocks for sequence parallelism (ring /
Ulysses) — the VMEM-resident inner kernel of parallel/ring_attention.py.

Contract: one K/V *shard* at a time. q is this device's resident query
shard [B, Sq, H*D] (heads packed in lanes, flash_tiled layout); k/v is the
visiting shard [B, Sk, H*D]. Global causal coordinates come in as TRACED
scalars (row0, col0) through SMEM — the ring loop is a lax.scan whose step
index decides which shard is visiting, so the offsets cannot be Python
ints. Outputs are fp32: the caller merges shards with logsumexp weights
(associative online-softmax merge), so per-shard results must not round
to bf16 between steps.

forward:  (o_s, lse_s) — the shard's own normalized attention and row
          logsumexp; a fully-masked row yields o=0, lse=NEG_INF, which the
          logaddexp merge treats as "contributes nothing".
backward: the standard flash two-kernel split given the GLOBAL lse and
          delta=rowsum(do*out): shard_dq accumulates this q-shard's dq
          over the visiting kv; shard_dkv produces dk/dv for the visiting
          shard (they travel around the ring with it).

Unlike flash_tiled there is no bias/dropout plumbing: the SP attention op
(layers.ring_attention) exposes neither, and dropping them halves the
kernel surface. Tile sizes follow flash_tiled (512/256/128 divisors); in
interpret mode any shape is allowed whole-block so the virtual-CPU mesh
tests and the driver dryrun exercise this exact kernel path.

Reference role: greenfield — the reference has no sequence parallelism
(SURVEY.md §5); the kernel structure follows kernels/flash_tiled.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _tile_dim(s: int, interpret: bool) -> int:
    # keep the divisor ladder in sync with flash_tiled._tile (the packed
    # QKV kernels); the interpret whole-block allowance is ring-only
    for b in (512, 256, 128):
        if s % b == 0:
            return b
    return s if interpret else 0


def ring_supports(sq: int, sk: int, num_heads: int, head_dim: int, dtype,
                  interpret: bool) -> bool:
    if not (head_dim and 128 % head_dim == 0):
        return False
    if num_heads % (128 // head_dim):
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    return _tile_dim(sq, interpret) > 0 and _tile_dim(sk, interpret) > 0


def _scores(q, k, row0, col0, qb, kb, BQ, BK, scale, causal):
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if causal:
        row = row0 + qb * BQ + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = col0 + kb * BK + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col <= row, s, NEG_INF)
    return s


def _live(row0, col0, qb, kb, BQ, BK, causal):
    """False iff the tile is strictly above the global causal diagonal."""
    if not causal:
        return True
    return col0 + kb * BK <= row0 + qb * BQ + (BQ - 1)


# ---------------------------------------------------------------------------
# forward: per-shard online softmax -> (normalized o_s, row lse_s)
# ---------------------------------------------------------------------------


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, D, BQ, BK, scale, causal):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)
    row0, col0 = off_ref[0], off_ref[1]
    G = 128 // D

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(_live(row0, col0, qb, kb, BQ, BK, causal))
    def _compute():
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            s = _scores(q, k, row0, col0, qb, kb, BQ, BK, scale, causal)
            m_prev = m_scr[:, sl][:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            e = jnp.exp(s - m_new)
            if causal:
                # a NEG_INF-masked entry exps to 0 already, but the fully-
                # masked-row case leaves m_new == NEG_INF and e == exp(0):
                # zero those lanes explicitly
                row = row0 + qb * BQ + jax.lax.broadcasted_iota(
                    jnp.int32, e.shape, 0)
                col = col0 + kb * BK + jax.lax.broadcasted_iota(
                    jnp.int32, e.shape, 1)
                e = jnp.where(col <= row, e, 0.0)
            l_prev = l_scr[:, sl][:, :1]
            l_new = l_prev * alpha + jnp.sum(e, axis=-1, keepdims=True)
            pv = jnp.dot(e.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
            acc_scr[:, sl] = acc_scr[:, sl] * alpha + pv
            m_scr[:, sl] = jnp.broadcast_to(m_new, (BQ, D))
            l_scr[:, sl] = jnp.broadcast_to(l_new, (BQ, D))

    @pl.when(kb == nk - 1)
    def _finalize():
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            l = l_scr[:, sl][:, :1]
            safe = l > 0.0
            o_ref[0, :, sl] = jnp.where(
                safe, acc_scr[:, sl] / jnp.maximum(l, 1e-30), 0.0
            )
            lse_ref[0, :, sl] = jnp.broadcast_to(
                jnp.where(
                    safe,
                    m_scr[:, sl][:, :1] + jnp.log(jnp.maximum(l, 1e-30)),
                    NEG_INF,
                ),
                (BQ, D),
            )


def _q_spec(BQ):
    return pl.BlockSpec((1, BQ, 128), lambda b, g, qb, kb: (b, qb, g),
                        memory_space=pltpu.VMEM)


def _kv_spec(BK):
    return pl.BlockSpec((1, BK, 128), lambda b, g, qb, kb: (b, kb, g),
                        memory_space=pltpu.VMEM)


def shard_fwd(q, k, v, offs, H, D, causal, scale, interpret):
    """q [B,Sq,H*D], k/v [B,Sk,H*D], offs int32[2] (row0, col0) ->
    (o_s f32 [B,Sq,H*D], lse_s f32 [B,Sq,H*D] column-replicated per head)."""
    B, Sq, HD = q.shape
    assert HD == H * D, (HD, H, D)
    Sk = k.shape[1]
    NG = HD // 128
    BQ = _tile_dim(Sq, interpret)
    BK = _tile_dim(Sk, interpret)
    kern = functools.partial(_fwd_kernel, D=D, BQ=BQ, BK=BK,
                             scale=scale, causal=causal)
    return pl.pallas_call(
        kern,
        grid=(B, NG, Sq // BQ, Sk // BK),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _q_spec(BQ), _kv_spec(BK), _kv_spec(BK),
        ],
        out_specs=[_q_spec(BQ), _q_spec(BQ)],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sq, HD), jnp.float32),
            jax.ShapeDtypeStruct((B, Sq, HD), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, 128), jnp.float32),
            pltpu.VMEM((BQ, 128), jnp.float32),
            pltpu.VMEM((BQ, 128), jnp.float32),
        ],
        interpret=bool(interpret),
    )(offs, q, k, v)


# ---------------------------------------------------------------------------
# backward: given GLOBAL lse + delta, per-shard dq and dk/dv
# ---------------------------------------------------------------------------


def _probs(q, k, lse_col, row0, col0, qb, kb, BQ, BK, scale, causal):
    s = _scores(q, k, row0, col0, qb, kb, BQ, BK, scale, causal)
    return jnp.exp(s - lse_col)  # masked entries: exp(NEG-lse) == 0


def _dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, D, BQ, BK, scale, causal):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)
    row0, col0 = off_ref[0], off_ref[1]
    G = 128 // D

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(_live(row0, col0, qb, kb, BQ, BK, causal))
    def _compute():
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse_col = lse_ref[0, :, sl][:, :1]
            delta_col = delta_ref[0, :, sl][:, :1]
            p = _probs(q, k, lse_col, row0, col0, qb, kb, BQ, BK, scale,
                       causal)
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta_col)
            dq_scr[:, sl] += jnp.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32
            ) * scale

    @pl.when(kb == nk - 1)
    def _write():
        dq_ref[0] = dq_scr[...]


def _dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, D, BQ, BK, scale, causal):
    kb = pl.program_id(2)
    qb = pl.program_id(3)
    nq = pl.num_programs(3)
    row0, col0 = off_ref[0], off_ref[1]
    G = 128 // D

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(_live(row0, col0, qb, kb, BQ, BK, causal))
    def _compute():
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse_col = lse_ref[0, :, sl][:, :1]
            delta_col = delta_ref[0, :, sl][:, :1]
            p = _probs(q, k, lse_col, row0, col0, qb, kb, BQ, BK, scale,
                       causal)
            dv_scr[:, sl] += jnp.dot(
                p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
            )
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = p * (dp - delta_col)
            dk_scr[:, sl] += jnp.dot(
                ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
            ) * scale

    @pl.when(qb == nq - 1)
    def _write():
        dk_ref[0] = dk_scr[...]
        dv_ref[0] = dv_scr[...]


def shard_dq(q, k, v, do, lse, delta, offs, H, D, causal, scale, interpret):
    """dq f32 [B,Sq,H*D] for this q-shard against one visiting kv shard."""
    B, Sq, HD = q.shape
    assert HD == H * D, (HD, H, D)
    Sk = k.shape[1]
    NG = HD // 128
    BQ = _tile_dim(Sq, interpret)
    BK = _tile_dim(Sk, interpret)
    kern = functools.partial(_dq_kernel, D=D, BQ=BQ, BK=BK,
                             scale=scale, causal=causal)
    return pl.pallas_call(
        kern,
        grid=(B, NG, Sq // BQ, Sk // BK),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _q_spec(BQ), _kv_spec(BK), _kv_spec(BK),
            _q_spec(BQ), _q_spec(BQ), _q_spec(BQ),
        ],
        out_specs=_q_spec(BQ),
        out_shape=jax.ShapeDtypeStruct((B, Sq, HD), jnp.float32),
        scratch_shapes=[pltpu.VMEM((BQ, 128), jnp.float32)],
        interpret=bool(interpret),
    )(offs, q, k, v, do, lse, delta)


def shard_dkv(q, k, v, do, lse, delta, offs, H, D, causal, scale, interpret):
    """(dk, dv) f32 [B,Sk,H*D] — the visiting shard's gradient
    contribution from THIS device's queries (accumulated around the ring
    by the caller)."""
    B, Sq, HD = q.shape
    assert HD == H * D, (HD, H, D)
    Sk = k.shape[1]
    NG = HD // 128
    BQ = _tile_dim(Sq, interpret)
    BK = _tile_dim(Sk, interpret)
    kern = functools.partial(_dkv_kernel, D=D, BQ=BQ, BK=BK,
                             scale=scale, causal=causal)
    kv_out = pl.BlockSpec((1, BK, 128), lambda b, g, kb, qb: (b, kb, g),
                          memory_space=pltpu.VMEM)
    q_in = pl.BlockSpec((1, BQ, 128), lambda b, g, kb, qb: (b, qb, g),
                        memory_space=pltpu.VMEM)
    kv_in = pl.BlockSpec((1, BK, 128), lambda b, g, kb, qb: (b, kb, g),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=(B, NG, Sk // BK, Sq // BQ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_in, kv_in, kv_in, q_in, q_in, q_in,
        ],
        out_specs=[kv_out, kv_out],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sk, HD), jnp.float32),
            jax.ShapeDtypeStruct((B, Sk, HD), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, 128), jnp.float32),
            pltpu.VMEM((BK, 128), jnp.float32),
        ],
        interpret=bool(interpret),
    )(offs, q, k, v, do, lse, delta)
