"""KV-block-tiled flash attention (online softmax) — removes the
whole-row kernel's MAX_SEQ cap (flash_attention.py keeps an [S, S] score
tile in VMEM; here VMEM holds one [BQ, BK] tile regardless of S).

Layout matches the packed-QKV kernels: qkv [B, S, 3*H*D] indexed in place,
one 128-lane head group (G = 128//D heads) per grid step, out [B, S, H*D].

Tile sizes adapt to S: the largest of 512/256/128 that divides S, so any
S % 128 == 0 works (the r3 kernel hard-required S % 512 == 0 — VERDICT r3
weak item 3).

Forward: grid (B, groups, S//BQ, S//BK), kv innermost. Scratch carries the
online-softmax state (running max m, running sum l, unnormalized
accumulator acc) across kv steps; the output block (indexed by q) is
written on the LAST kv step. The row logsumexp L = m + log(l) is saved for
the backward. Under causal masking, tiles strictly above the diagonal are
SKIPPED (pl.when over the whole head loop) — at S >> BQ that is ~half the
grid's MXU/VPU work; the block DMAs still run (static grid), which is why
the win tops out near 2x.

Backward: flash attention's standard two-kernel split (dq needs a sum over
kv, dk/dv over q — one grid cannot accumulate both):
  * dkv kernel: grid (..., KB, QB), q innermost; p recomputed per tile
    from the saved L (no renormalization pass), dk/dv accumulate in
    scratch, written on the last q step. Per-q-block partial dbias rows
    emit to a [QB, S] buffer summed outside.
  * dq kernel: grid (..., QB, KB), kv innermost; dq accumulates in
    scratch. Needs delta = rowsum(do * o), precomputed outside (cheap
    elementwise XLA pass, the FlashAttention-2 formulation).

Dropout regenerates per-tile masks from a seed mixed with
(batch, head, q-block, kv-block) — order-independent, so the three kernels
(fwd, dkv, dq) draw identical masks for the same tile regardless of their
different loop orders. Semantics match fluid dropout exactly as in
flash_attention.py.

Reference role: operators/fused/multihead_matmul_op.cu — but that kernel
is whole-row too; the tiled form is what long-context needs
(sequence-parallel ring attention composes on top, parallel/ring_attention.py).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _tile(seq_len: int) -> int:
    for b in (512, 256, 128):
        if seq_len % b == 0:
            return b
    return 0


def supports_tiled(seq_len: int, num_heads: int, head_dim: int, dtype):
    g = 128 // head_dim if head_dim and 128 % head_dim == 0 else 0
    return (
        g > 0
        and num_heads % g == 0
        and _tile(seq_len) > 0
        and jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.bfloat16))
    )


def _mix(*words):
    acc = jnp.uint32(0x9E3779B9)
    for w in words:
        acc = (acc ^ w.astype(jnp.uint32)) * jnp.uint32(0x85EBCA6B)
        acc = acc ^ (acc >> 13)
    return acc


def _seed_tile(seed_ref, head, qb, kb):
    b = pl.program_id(0).astype(jnp.uint32)
    s0 = seed_ref[0] + _mix(b, head, qb.astype(jnp.uint32))
    s1 = seed_ref[1] ^ _mix(kb.astype(jnp.uint32), head, b)
    pltpu.prng_seed(s0, s1)


# all three kernels (fwd, dkv, dq) draw the identical (BQ/BK-shaped) tile
# mask after the identical per-tile reseed, so masks agree regardless of
# loop order
from .prng_mask import keep_mask as _keep


def _tile_scores(q, k, bias_tile, scale, causal, qb, kb, BQ, BK):
    """[BQ, BK] fp32 scores for one head; causal mask in global coords.

    The mask applies unconditionally on live tiles: gating it on
    diagonal-straddling tiles via lax.cond was MEASURED SLOWER on chip
    (S=8192 GPT leg 44.9k -> 34.4k tok/s — the in-kernel cond defeats
    Mosaic's cross-iteration pipelining), so three flat VPU passes beat
    one branch."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    s = s + bias_tile
    if causal:
        row = qb * BQ + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = kb * BK + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col <= row, s, NEG_INF)
    return s


def _dropout_tile(e, rate, is_test, upscale, seed_ref, head, qb, kb):
    if rate == 0.0:
        return e
    if is_test:
        return e if upscale else e * (1.0 - rate)
    _seed_tile(seed_ref, head, qb, kb)
    keep = _keep(e.shape, rate)
    return jnp.where(keep, e / (1.0 - rate) if upscale else e, 0.0)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, D, BQ, BK, scale, rate, is_test, upscale, causal):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)
    G = 128 // D

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        bias_tile = bias_ref[0]  # [1, BK]
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            head = (pl.program_id(1) * G + i)
            s = _tile_scores(q, k, bias_tile, scale, causal, qb, kb, BQ, BK)
            m_prev = m_scr[:, sl][:, :1]  # [BQ, 1] (per-head col block)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)  # rescale of previous state
            # bf16 models run the [BQ, BK] exp/dropout tail in bf16 (see
            # flash_attention._probs_unnorm); running stats stay fp32
            edt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
            e = jnp.exp((s - m_new).astype(edt))
            l_prev = l_scr[:, sl][:, :1]
            l_new = l_prev * alpha + jnp.sum(e, axis=-1, keepdims=True,
                                             dtype=jnp.float32)
            ed = _dropout_tile(
                e, rate, is_test, upscale, seed_ref, head.astype(jnp.uint32),
                qb, kb,
            )
            pv = jnp.dot(ed.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
            acc_scr[:, sl] = acc_scr[:, sl] * alpha + pv
            m_scr[:, sl] = jnp.broadcast_to(m_new, (m_new.shape[0], D))
            l_scr[:, sl] = jnp.broadcast_to(l_new, (l_new.shape[0], D))

    if causal:
        # tiles strictly above the diagonal are all-masked: skip the MXU/
        # VPU work entirely (the scratch state is unchanged by a dead tile)
        @pl.when(kb * BK <= qb * BQ + (BQ - 1))
        def _live():
            _compute()
    else:
        _compute()

    @pl.when(kb == nk - 1)
    def _finalize():
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            l = l_scr[:, sl][:, :1]
            o_ref[0, :, sl] = (
                acc_scr[:, sl] / jnp.maximum(l, 1e-30)
            ).astype(o_ref.dtype)
            # row logsumexp for the backward: L = m + log(l)
            lse_ref[0, :, sl] = jnp.broadcast_to(
                m_scr[:, sl][:, :1] + jnp.log(jnp.maximum(l, 1e-30)),
                (l.shape[0], D),
            )


def _q_spec(section, num_groups, BQ):
    return pl.BlockSpec(
        (1, BQ, 128),
        lambda b, g, qb, kb: (b, qb, section * num_groups + g),
        memory_space=pltpu.VMEM,
    )


def _kv_spec(section, num_groups, BK):
    return pl.BlockSpec(
        (1, BK, 128),
        lambda b, g, qb, kb: (b, kb, section * num_groups + g),
        memory_space=pltpu.VMEM,
    )


def _bias_spec(BK):
    return pl.BlockSpec(
        (1, 1, BK), lambda b, g, qb, kb: (b, 0, kb),
        memory_space=pltpu.VMEM,
    )


def _out_spec(BQ):
    return pl.BlockSpec(
        (1, BQ, 128), lambda b, g, qb, kb: (b, qb, g),
        memory_space=pltpu.VMEM,
    )


def flash_tiled_fwd(qkv, bias, seed, H, D, statics, interpret=False):
    """qkv [B, S, 3*H*D]; bias [B, S] -> (out [B, S, H*D], lse [B, S, H*D])."""
    B, S, _ = qkv.shape
    G = H * D // 128
    BQ = BK = _tile(S)
    bias3 = bias.reshape(B, 1, S)
    kern = functools.partial(_fwd_kernel, D=D, BQ=BQ, BK=BK, **statics)
    out, lse = pl.pallas_call(
        kern,
        grid=(B, G, S // BQ, S // BK),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _q_spec(0, G, BQ),
            _kv_spec(1, G, BK),
            _kv_spec(2, G, BK),
            _bias_spec(BK),
        ],
        out_specs=[_out_spec(BQ), _out_spec(BQ)],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, S, H * D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, 128), jnp.float32),
            pltpu.VMEM((BQ, 128), jnp.float32),
            pltpu.VMEM((BQ, 128), jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, qkv, qkv, qkv, bias3)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _tile_probs_from_lse(q, k, bias_tile, lse_col, scale, causal, qb, kb,
                         BQ, BK):
    s = _tile_scores(q, k, bias_tile, scale, causal, qb, kb, BQ, BK)
    edt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
    return jnp.exp((s - lse_col).astype(edt))  # [BQ, BK] normalized probs


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dbias_ref, dk_scr, dv_scr,
                *, D, BQ, BK, scale, rate, is_test, upscale, causal):
    kb = pl.program_id(2)
    qb = pl.program_id(3)
    nq = pl.num_programs(3)
    G = 128 // D

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        bias_tile = bias_ref[0]
        db_rows = jnp.zeros((1, BK), jnp.float32)
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse_col = lse_ref[0, :, sl][:, :1]
            delta_col = delta_ref[0, :, sl][:, :1]
            head = pl.program_id(1) * G + i
            p = _tile_probs_from_lse(q, k, bias_tile, lse_col, scale,
                                     causal, qb, kb, BQ, BK)
            if rate > 0.0 and not is_test:
                _seed_tile(seed_ref, head.astype(jnp.uint32), qb, kb)
                keep = _keep(p.shape, rate)
                inv = 1.0 / (1.0 - rate) if upscale else 1.0
                pm = jnp.where(keep, p * inv, 0.0)
                dpm = jnp.dot(do.astype(v.dtype), v.T,
                              preferred_element_type=jnp.float32)
                dp = jnp.where(keep, dpm * inv, 0.0)
            else:
                ts = 1.0 if (rate == 0.0 or upscale) else 1.0 - rate
                pm = p * ts
                dp = jnp.dot(do.astype(v.dtype), v.T,
                             preferred_element_type=jnp.float32) * ts
            dv_scr[:, sl] += jnp.dot(
                pm.astype(v.dtype).T, do.astype(v.dtype),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_col)
            dsb = ds.astype(v.dtype)
            dk_scr[:, sl] += jnp.dot(
                dsb.T, q, preferred_element_type=jnp.float32
            ) * scale
            db_rows = db_rows + jnp.sum(ds, axis=0, keepdims=True)
        dbias_ref[0, 0] = db_rows

    if causal:
        live = qb * BQ + (BQ - 1) >= kb * BK

        @pl.when(live)
        def _live():
            _compute()

        @pl.when(jnp.logical_not(live))
        def _dead():
            # this (g, kb, qb) partial-dbias block is written exactly once;
            # a dead tile must still zero it
            dbias_ref[0, 0] = jnp.zeros((1, BK), jnp.float32)
    else:
        _compute()

    @pl.when(qb == nq - 1)
    def _write():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr,
               *, D, BQ, BK, scale, rate, is_test, upscale, causal):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)
    G = 128 // D

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute():
        bias_tile = bias_ref[0]
        for i in range(G):
            sl = slice(i * D, (i + 1) * D)
            q = q_ref[0, :, sl]
            k = k_ref[0, :, sl]
            v = v_ref[0, :, sl]
            do = do_ref[0, :, sl]
            lse_col = lse_ref[0, :, sl][:, :1]
            delta_col = delta_ref[0, :, sl][:, :1]
            head = pl.program_id(1) * G + i
            p = _tile_probs_from_lse(q, k, bias_tile, lse_col, scale,
                                     causal, qb, kb, BQ, BK)
            if rate > 0.0 and not is_test:
                _seed_tile(seed_ref, head.astype(jnp.uint32), qb, kb)
                keep = _keep(p.shape, rate)
                inv = 1.0 / (1.0 - rate) if upscale else 1.0
                dpm = jnp.dot(do.astype(v.dtype), v.T,
                              preferred_element_type=jnp.float32)
                dp = jnp.where(keep, dpm * inv, 0.0)
            else:
                ts = 1.0 if (rate == 0.0 or upscale) else 1.0 - rate
                dp = jnp.dot(do.astype(v.dtype), v.T,
                             preferred_element_type=jnp.float32) * ts
            ds = p * (dp - delta_col)
            dq_scr[:, sl] += jnp.dot(
                ds.astype(v.dtype), k, preferred_element_type=jnp.float32
            ) * scale

    if causal:
        @pl.when(kb * BK <= qb * BQ + (BQ - 1))
        def _live():
            _compute()
    else:
        _compute()

    @pl.when(kb == nk - 1)
    def _write():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def flash_tiled_bwd(qkv, bias, seed, do, out, lse, H, D, statics,
                    interpret=False):
    """-> (dqkv [B, S, 3HD], dbias [B, S])."""
    B, S, _ = qkv.shape
    G = H * D // 128
    BQ = BK = _tile(S)
    bias3 = bias.reshape(B, 1, S)
    # delta = rowsum(do * o) per head, broadcast to the lane layout
    do3 = do.reshape(B, S, H, D)
    o3 = out.reshape(B, S, H, D)
    delta = jnp.sum(
        do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1
    )  # [B, S, H]
    delta = jnp.repeat(delta, D, axis=-1)  # [B, S, H*D] column-replicated

    dkv_kern = functools.partial(_dkv_kernel, D=D, BQ=BQ, BK=BK, **statics)
    dk, dv, dbias_parts = pl.pallas_call(
        dkv_kern,
        grid=(B, G, S // BK, S // BQ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            # q-indexed operands use the INNER axis (qb = program_id(3))
            pl.BlockSpec((1, BQ, 128),
                         lambda b, g, kb, qb: (b, qb, 0 * G + g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BK, 128),
                         lambda b, g, kb, qb: (b, kb, 1 * G + g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BK, 128),
                         lambda b, g, kb, qb: (b, kb, 2 * G + g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, BK), lambda b, g, kb, qb: (b, 0, kb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BQ, 128), lambda b, g, kb, qb: (b, qb, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BQ, 128), lambda b, g, kb, qb: (b, qb, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BQ, 128), lambda b, g, kb, qb: (b, qb, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, 128), lambda b, g, kb, qb: (b, kb, g),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BK, 128), lambda b, g, kb, qb: (b, kb, g),
                         memory_space=pltpu.VMEM),
            # per-(g, kb, qb) partial bias rows; summed below
            pl.BlockSpec((1, 1, 1, BK),
                         lambda b, g, kb, qb: (b, g * (S // BQ) + qb, 0, kb),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, G * (S // BQ), 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, 128), jnp.float32),
            pltpu.VMEM((BK, 128), jnp.float32),
        ],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, qkv, qkv, qkv, bias3, do, lse, delta)

    dq_kern = functools.partial(_dq_kernel, D=D, BQ=BQ, BK=BK, **statics)
    dq = pl.pallas_call(
        dq_kern,
        grid=(B, G, S // BQ, S // BK),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _q_spec(0, G, BQ),
            _kv_spec(1, G, BK),
            _kv_spec(2, G, BK),
            _bias_spec(BK),
            _out_spec(BQ),
            _out_spec(BQ),
            _out_spec(BQ),
        ],
        out_specs=_out_spec(BQ),
        out_shape=jax.ShapeDtypeStruct((B, S, H * D), qkv.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, 128), jnp.float32)],
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seed, qkv, qkv, qkv, bias3, do, lse, delta)

    dbias = jnp.sum(dbias_parts, axis=1).reshape(B, S)
    dqkv = jnp.concatenate([dq, dk, dv], axis=-1)
    return dqkv, dbias


# ---------------------------------------------------------------------------
# custom-vjp wrapper (same contract as flash_attention._flash_qkv)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_tiled_outs(qkv, bias, seed, H, D, statics, interpret):
    """(out, lse): the row logsumexp is a SECOND output so the static
    graph can hand it to the dedicated grad op — without it the grad op
    must re-run the forward kernel to recover lse (XLA does not CSE
    custom calls), a full extra fwd per layer per step."""
    return flash_tiled_fwd(qkv, bias, seed, H, D, dict(statics), interpret)


def _flash_tiled_outs_fwd(qkv, bias, seed, H, D, statics, interpret):
    out, lse = flash_tiled_fwd(qkv, bias, seed, H, D, dict(statics),
                               interpret)
    return (out, lse), (qkv, bias, seed, out, lse)


def _flash_tiled_outs_bwd(H, D, statics, interpret, res, gs):
    qkv, bias, seed, out, lse = res
    g, _g_lse = gs  # lse is auxiliary: cotangents on it are discarded
    dqkv, dbias = flash_tiled_bwd(
        qkv, bias, seed, g, out, lse, H, D, dict(statics), interpret
    )
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dqkv, dbias, dseed


flash_tiled_outs.defvjp(_flash_tiled_outs_fwd, _flash_tiled_outs_bwd)


def flash_tiled(qkv, bias, seed, H, D, statics, interpret):
    """out-only wrapper: ONE vjp pair of record (flash_tiled_outs); the
    discarded lse costs nothing extra — the kernel always computes it."""
    from .. import observability as _obs

    _obs.add("kernels.flash_tiled")
    out, _ = flash_tiled_outs(qkv, bias, seed, H, D, statics, interpret)
    return out
