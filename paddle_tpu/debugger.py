"""Program graph visualization (reference
python/paddle/fluid/debugger.py:229 draw_block_graphviz): dump a Block as
a graphviz .dot file — ops as boxes, variables as ellipses, parameters
highlighted — so a user can see the graph a Program builds before the
whole block disappears into one XLA computation.
"""

from __future__ import annotations


def _esc(s):
    return str(s).replace('"', r"\"")


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write a graphviz dot rendering of `block` to `path` (same signature
    as the reference's debugger.draw_block_graphviz)."""
    highlights = set(highlights or ())
    lines = [
        "digraph G {",
        "  rankdir=TB;",
        '  node [fontsize=10, fontname="Helvetica"];',
    ]
    var_nodes = {}

    def var_node(name):
        if name in var_nodes:
            return var_nodes[name]
        nid = f"var_{len(var_nodes)}"
        var_nodes[name] = nid
        v = block._find_var_recursive(name)
        shape = tuple(v.shape or ()) if v is not None else "?"
        is_param = bool(v is not None and getattr(v, "persistable", False))
        style = "filled"
        color = "lightgrey" if is_param else "white"
        if name in highlights:
            color = "yellow"
        lines.append(
            f'  {nid} [label="{_esc(name)}\\n{_esc(shape)}", '
            f'shape=ellipse, style={style}, fillcolor={color}];'
        )
        return nid

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        lines.append(
            f'  {op_id} [label="{_esc(op.type)}", shape=box, '
            'style=filled, fillcolor=lightblue];'
        )
        for name in op.input_names():
            if name:
                lines.append(f"  {var_node(name)} -> {op_id};")
        for name in op.output_names():
            if name:
                lines.append(f"  {op_id} -> {var_node(name)};")
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path
