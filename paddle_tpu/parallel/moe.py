"""Mixture-of-Experts with expert parallelism (EP) — GShard-style.

Absent from the reference (SURVEY.md §2.3: "no MoE ops"); designed
TPU-first: experts shard over the "ep" mesh axis, token dispatch/return are
two lax.all_to_all exchanges over ICI, expert FFNs run as one batched
einsum on the MXU. Top-2 gating with capacity dropping + the standard
load-balancing auxiliary loss (mean(fraction * prob) * E).

Without a mesh (or no "ep" axis) the same math runs dense on one chip —
the dispatch einsums are identical, only the all_to_alls drop out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _top2_dispatch(gates, capacity):
    """gates [T, E] softmax-ed. Returns dispatch [T, E, C] (0/1), combine
    [T, E, C] (weights), aux load-balance loss (scalar)."""
    t, e = gates.shape
    c = int(capacity)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)  # [T,E]
    gates2 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)

    # aux loss on first-choice assignment (GShard eq. 4)
    density = mask1.mean(axis=0)  # fraction of tokens per expert
    density_proxy = gates.mean(axis=0)  # mean router prob per expert
    aux = (density * density_proxy).sum() * e

    # position of each token within its expert's capacity buffer
    pos1 = jnp.cumsum(mask1, axis=0) - mask1  # [T,E]
    keep1 = mask1 * (pos1 < c)
    # second choices queue behind ALL first choices of that expert
    count1 = mask1.sum(axis=0, keepdims=True)
    pos2 = jnp.cumsum(mask2, axis=0) - mask2 + count1
    keep2 = mask2 * (pos2 < c)

    g1 = (gates * keep1).sum(axis=-1, keepdims=True)
    g2 = (gates * keep2).sum(axis=-1, keepdims=True)
    denom = jnp.clip(g1 + g2, 1e-9, None)
    w1 = g1 / denom
    w2 = g2 / denom

    oh_pos1 = jax.nn.one_hot(
        (pos1 * mask1).sum(axis=-1).astype(jnp.int32), c, dtype=gates.dtype
    )  # [T,C]
    oh_pos2 = jax.nn.one_hot(
        (pos2 * mask2).sum(axis=-1).astype(jnp.int32), c, dtype=gates.dtype
    )
    dispatch = (
        keep1[:, :, None] * oh_pos1[:, None, :]
        + keep2[:, :, None] * oh_pos2[:, None, :]
    )
    combine = (
        (keep1 * w1)[:, :, None] * oh_pos1[:, None, :]
        + (keep2 * w2)[:, :, None] * oh_pos2[:, None, :]
    )
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w1, b1, w2, b2, axis_name=None, axis_size=1,
            capacity_factor=2.0, activation=jax.nn.gelu):
    """x [B, S, H] (local shard). w1 [E_local, H, F], w2 [E_local, F, H]
    (expert-sharded over `axis_name` when set; full E otherwise).
    Returns (y [B,S,H], aux_loss scalar)."""
    b, s, h = x.shape
    n = int(axis_size) if axis_name else 1
    e_local = w1.shape[0]
    e = e_local * n
    tokens = x.reshape(b * s, h)
    t = tokens.shape[0]
    capacity = max(1, int(capacity_factor * t * 2 / e))

    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)  # [T, E] over GLOBAL experts
    dispatch, combine, aux = _top2_dispatch(gates, capacity)

    expert_in = jnp.einsum(
        "tec,th->ech", dispatch.astype(x.dtype), tokens
    )  # [E, C, H]
    if n > 1:
        # token exchange: each device keeps its E_local experts' buffers and
        # receives the matching slices from every peer
        expert_in = lax.all_to_all(
            expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True
        )  # [E_local, n*C, H]
    hmid = activation(
        jnp.einsum("ekh,ehf->ekf", expert_in, w1) + b1[:, None, :]
    )
    expert_out = jnp.einsum("ekf,efh->ekh", hmid, w2) + b2[:, None, :]
    if n > 1:
        expert_out = lax.all_to_all(
            expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
        )  # [E, C, H]
    y = jnp.einsum("tec,ech->th", combine.astype(x.dtype), expert_out)
    return y.reshape(b, s, h), aux.astype(jnp.float32)


@register_op(
    "moe_ffn",
    inputs=["X", "GateW", "W1", "B1", "W2", "B2"],
    outputs=["Out", "AuxLoss"],
)
def _moe_ffn_op(ctx, op, ins):
    x, gate_w, w1, b1, w2, b2 = (
        ins[k][0] for k in ("X", "GateW", "W1", "B1", "W2", "B2")
    )
    axis = op.attr("axis_name", "ep")
    cf = op.attr("capacity_factor", 2.0)
    if axis in ctx.mesh_axes:
        y, aux = moe_ffn(
            x, gate_w, w1, b1, w2, b2, axis_name=axis,
            axis_size=ctx.axis_sizes[axis], capacity_factor=cf,
        )
    else:
        y, aux = moe_ffn(x, gate_w, w1, b1, w2, b2, capacity_factor=cf)
    return {"Out": [y], "AuxLoss": [aux.reshape([1])]}
