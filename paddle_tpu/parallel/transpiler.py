"""Collective program rewriters: DP grad-allreduce, ZeRO weight-update
sharding, LocalSGD.

TPU-native analog of the reference's transpiler/collective.py:
  * GradAllReduce (:178): scale loss grad by 1/nranks (:190) and insert
    `c_allreduce_sum` per gradient (:209). Here the collectives are emitted
    as ops whose emitters call lax.psum under shard_map — no c_gen_nccl_id /
    c_comm_init startup rewrite (:99-132) is needed: mesh construction
    replaces communicator bootstrap.
  * ShardedWeightUpdate: ZeRO-style cross-replica sharding of the weight
    update (arXiv:2004.13336) — per-grad allreduce becomes reduce-scatter,
    the optimizer update (moments, master shard) runs on each rank's 1/N
    flat shard only, and the updated parameters all-gather back. Optimizer
    state is genuinely 1/N per rank; wire bytes are unchanged in fp
    (reduce-scatter + all-gather = allreduce) and ~4x smaller with the
    opt-in int8 block-quantized collectives (arXiv:2506.17615, EQuARX).
  * LocalSGD (:270): periodic parameter averaging across the dp axis.

The reference also had to pin a deterministic allreduce order
(all_reduce_deps_pass.cc) and fuse gradient buffers
(coalesce_grad_tensor_pass.cc) by hand; XLA's latency-hiding scheduler and
collective combiner do both automatically.
"""

from __future__ import annotations

import math

from .mesh import DATA_AXIS


# AMP bookkeeping ops rewrite grads in place *after* they are produced; the
# DP allreduce must land before them so every shard's FoundInfinite /
# loss-scale state is computed from identical (globally reduced) gradients.
_AMP_CHECK_OPS = frozenset({"check_finite_and_unscale", "update_loss_scaling"})


def _insert_pos_after(block, names):
    """Index just after the last non-AMP op producing any of `names`."""
    pos = 0
    names = set(names)
    for i, op in enumerate(block.ops):
        if op.type in _AMP_CHECK_OPS:
            continue
        if names & set(op.output_names()):
            pos = i + 1
    return pos


def insert_grad_allreduce(block, grad, axis_name, scale=None):
    """Insert (optional scale +) c_allreduce_sum on a gradient, right after
    its producer and BEFORE any AMP bookkeeping ops (_insert_pos_after):
    FoundInfinite / loss-scale state must be computed from the globally
    reduced gradients or they diverge across ranks. One definition shared
    by the dp transpile, the pipeline optimizers, and leg builders."""
    gname = grad.name if hasattr(grad, "name") else str(grad)
    pos = _insert_pos_after(block, [gname])
    if scale is not None:
        block.append_op(
            "scale",
            inputs={"X": [gname]},
            outputs={"Out": [gname]},
            attrs={"scale": scale, "bias": 0.0},
            index=pos,
        )
        pos += 1
    block.append_op(
        "c_allreduce_sum",
        inputs={"X": [gname]},
        outputs={"Out": [gname]},
        attrs={"axis_name": axis_name},
        index=pos,
    )


class GradAllReduce:
    """Insert per-gradient allreduce into a trained program (DP mode)."""

    def __init__(self, nranks, axis_name=DATA_AXIS):
        self.nranks = nranks
        self.axis_name = axis_name

    def transpile(self, program, params_grads):
        block = program.global_block
        for _, g in params_grads:
            # mean-reduce: scale by 1/nranks then psum — identical math to
            # the reference's loss-grad scaling (transpiler/collective.py:190)
            insert_grad_allreduce(
                block, g, self.axis_name, scale=1.0 / self.nranks
            )
        return program


# the per-parameter update-op family the sharded rewrite understands
# (ops/optimizer_ops.py emitters are elementwise over Param/Grad/moments,
# which is exactly what makes the 1/N flat-shard rewrite sound)
UPDATE_OPS = frozenset({
    "sgd", "momentum", "adam", "adamw", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "adamax", "dpsgd", "proximal_gd",
    "proximal_adagrad",
})

# update ops whose math reads FULL-tensor reductions (LAMB's trust ratio,
# LARS's local lr are ||param||/||grad|| over the whole tensor): a
# shard-local norm silently changes the update, so these refuse to shard
_NORM_COUPLED_UPDATE_OPS = frozenset({"lamb", "lars_momentum",
                                      "dgc_momentum_step"})

_SHARD_SUFFIX = "@ZERO_SHARD"


class ShardedWeightUpdate:
    """Rewrite a trained DP program to the ZeRO-style sharded update.

    Runs AFTER ``apply_gradients`` (the update ops must exist). Per
    parameter:

    1. a ``zero_reduce_scatter`` (scale 1/N folded in) lands right after
       the grad producer and BEFORE the AMP bookkeeping ops, leaving each
       rank the globally averaged flat ``[pad/N]`` shard of its gradient;
    2. the optimizer update op is rewritten onto dp-sharded flat state:
       the param's master shard plus every same-shaped accumulator
       (moments, velocities) becomes a persistable ``[pad]`` var with
       sharding spec ``("dp",)`` — 1/N of it lives on each rank; the full
       accumulators are deleted from main AND startup (their startup init
       feeds a ``zero_pad_flatten`` into the shard instead, so the full
       tensor never reaches the scope);
    3. a ``zero_all_gather`` immediately after the update reassembles the
       full parameter (replicated) for the next forward.

    AMP programs get their ``check_finite_and_unscale`` /
    ``update_loss_scaling`` X lists rewritten onto the grad shards, with a
    ``c_allreduce_any`` on FoundInfinite so the loss-scale automaton stays
    rank-uniform.

    ``quant="int8"`` selects the block-quantized wire format for both
    collectives (``quant_block`` values per fp32 scale); padding is then
    aligned to ``nranks * quant_block`` so every shard quantizes in whole
    blocks.

    Not supported (raises ``NotImplementedError``): grad clipping and
    regularization (both read full-tensor gradients after the insertion
    point — a shard-local norm would silently change the math) and DGC
    (its fused op owns its own collective).
    """

    def __init__(self, nranks, axis_name=DATA_AXIS, quant=None,
                 quant_block=256):
        self.nranks = int(nranks)
        self.axis_name = axis_name
        self.quant = quant if quant not in (None, "", "none") else "none"
        if self.quant not in ("none", "int8"):
            # an unknown string must not silently select the int8 kernel
            # (and leak into collective.bytes.* counter names)
            raise ValueError(
                f"shard_weight_update: unknown collective quantization "
                f"{quant!r}; supported: None | 'int8'"
            )
        self.quant_block = int(quant_block)
        if self.quant_block < 1:
            raise ValueError(
                f"shard_weight_update: collective_quant_block must be a "
                f"positive element count, got {quant_block!r}"
            )

    # -- helpers -----------------------------------------------------------
    def _pad_len(self, numel):
        align = self.nranks * (
            self.quant_block if self.quant != "none" else 1
        )
        return int(math.ceil(numel / align) * align)

    @staticmethod
    def _itemsize(v):
        import numpy as np

        from ..core.dtypes import to_numpy_dtype

        try:
            return np.dtype(to_numpy_dtype(v.dtype or "float32")).itemsize
        except Exception:
            return 4

    def _zero_attrs(self, extra=None):
        attrs = {
            "axis_name": self.axis_name,
            "quant": self.quant,
            "quant_block": self.quant_block,
        }
        attrs.update(extra or {})
        return attrs

    def _make_shard_var(self, main, startup, src_var, pad_len,
                        init_from=None):
        """Create the persistable ``[pad_len]`` dp-sharded counterpart of
        `src_var` in main + startup, with its startup value derived from
        `init_from` (default: the source var itself) via zero_pad_flatten."""
        sname = src_var.name + _SHARD_SUFFIX
        blk = main.global_block
        sb = startup.global_block
        v = blk.create_var(
            name=sname, shape=[pad_len], dtype=src_var.dtype or "float32",
            persistable=True,
        )
        v.stop_gradient = True
        v._zero_shard_of = src_var.name
        sb.create_var(
            name=sname, shape=[pad_len], dtype=src_var.dtype or "float32",
            persistable=True,
        )
        sb.append_op(
            "zero_pad_flatten",
            {"X": [init_from or src_var.name]},
            {"Out": [sname]},
            {"pad_len": pad_len},
        )
        main._sharding[sname] = (self.axis_name,)
        return v

    @staticmethod
    def _find_update_op(block, pname):
        for i, op in enumerate(block.ops):
            if op.type in UPDATE_OPS and (
                op.inputs.get("Param") == [pname]
            ):
                grad = (op.inputs.get("Grad") or [""])[0]
                if not grad.endswith(_SHARD_SUFFIX):  # not rewritten yet
                    return i, op
        return None, None

    # -- the pass ----------------------------------------------------------
    def transpile(self, main, startup, params_grads):
        from .. import observability as _obs

        block = main.global_block
        for op in block.ops:
            if op.type in _NORM_COUPLED_UPDATE_OPS:
                raise NotImplementedError(
                    f"shard_weight_update: {op.type!r} reads full-tensor "
                    "state (trust-ratio norms / its own collective) and "
                    "cannot be flat-sharded; use adam/momentum/sgd-family "
                    "optimizers or disable sharding"
                )
        for p, _g in params_grads:
            if getattr(p, "regularizer", None) is not None:
                raise NotImplementedError(
                    f"shard_weight_update: parameter {p.name!r} carries a "
                    "per-param regularizer, which rewrites its gradient "
                    "after the reduce-scatter insertion point"
                )
        # mesh-sharded sparse embedding tables compose, not conflict: their
        # storage, grads and accumulators are already partitioned over the
        # "ps" axis (parallel/sparse.py) and their gradients are
        # dp-replicated (ids feed replicated), so the dense ZeRO rewrite
        # must SKIP them — flat-[pad] dp-sharding a ps-sharded table would
        # fight its row/column spec and its in-graph grad exchange
        from .sparse import sparse_table_names

        sparse = set(sparse_table_names(main))
        skipped_sparse = [p.name for p, _g in params_grads
                          if p.name in sparse]
        params_grads = [pg for pg in params_grads
                        if pg[0].name not in sparse]
        if skipped_sparse:
            _obs.add("collective.zero_sparse_tables_skipped",
                     len(skipped_sparse))

        per_rank = replicated = master = 0
        shard_names = []
        unshardable = []
        for p, _g in params_grads:
            stats = self._shard_one(main, startup, p, shard_names)
            if stats is None:
                # a param with no recognizable update op would be left
                # with NEITHER a reduce-scatter NOR an allreduce (the
                # fleet path skips GradAllReduce entirely in sharded
                # mode) — the replicas would silently diverge
                unshardable.append(p.name)
                continue
            pr, rep, ms = stats
            per_rank += pr
            replicated += rep
            master += ms
        if unshardable:
            raise NotImplementedError(
                "shard_weight_update: no supported update op found for "
                f"parameters {unshardable} (supported: "
                f"{sorted(UPDATE_OPS)}); their gradients would stay "
                "rank-local and the replicas would diverge"
            )
        self._rewrite_amp(block)
        main._zero_shard_vars = tuple(shard_names)
        main._zero_quant = self.quant
        main._bump()
        _obs.add("collective.zero_sharded_tensors", len(params_grads))
        _obs.set_gauge("collective.zero_dp_degree", self.nranks)
        _obs.set_gauge(
            "collective.zero_optimizer_state_bytes_per_rank", per_rank
        )
        _obs.set_gauge(
            "collective.zero_optimizer_state_bytes_full", replicated
        )
        _obs.set_gauge("collective.zero_master_shard_bytes_per_rank", master)
        return main

    def _shard_one(self, main, startup, p, shard_names):
        block = main.global_block
        idx, op = self._find_update_op(block, p.name)
        if op is None:
            return None
        numel = 1
        for d in p.shape:
            numel *= int(d)
        pad = self._pad_len(numel)
        shard_len = pad // self.nranks
        gname = op.inputs["Grad"][0]
        if "@CLIP" in gname:
            # every clip.py path (value / per-tensor norm / global norm)
            # hands the update op a "<grad>@CLIP*" rewrite; clipping by a
            # rank-LOCAL norm before the reduce-scatter is different math
            # from the allreduce baseline (which reduces first), so refuse
            # here too — not only in the fleet wrapper
            raise NotImplementedError(
                "shard_weight_update: gradient clipping rewrites "
                f"{gname!r} with rank-local norms before the "
                "reduce-scatter would land; clipping does not compose "
                "with the sharded update yet"
            )
        gvar = block._find_var_recursive(gname)

        # 1. reduce-scatter the gradient (mean: scale folded in), landing
        # before the AMP bookkeeping ops exactly like insert_grad_allreduce
        gshard = gname + _SHARD_SUFFIX
        gv = block.create_var(
            name=gshard, shape=[pad],
            dtype=(gvar.dtype if gvar is not None else None) or "float32",
        )
        gv.stop_gradient = True
        main._sharding[gshard] = (self.axis_name,)
        pos = _insert_pos_after(block, [gname])
        block.append_op(
            "zero_reduce_scatter",
            inputs={"X": [gname]},
            outputs={"Out": [gshard]},
            attrs=self._zero_attrs(
                {"scale": 1.0 / self.nranks, "pad_len": pad}
            ),
            index=pos,
        )

        # 2. rewrite the update op onto sharded flat state
        name_map = {gname: gshard}
        per_rank = replicated = 0
        pshape = tuple(int(d) for d in p.shape)
        for slot, names in op.inputs.items():
            for name in names:
                if name in name_map or name == gname:
                    continue
                v = block._find_var_recursive(name)
                if v is None:
                    continue
                is_param = name == p.name
                elementwise = getattr(v, "_accum_elementwise", None)
                if elementwise is None:  # untagged: fall back on shape
                    elementwise = (
                        tuple(int(d) for d in (v.shape or ())) == pshape
                    )
                is_accum = (
                    getattr(v, "_accum_of", None) == p.name and elementwise
                )
                if not (is_param or is_accum):
                    if getattr(v, "_accum_of", None) == p.name:
                        # replicated small state ([1] beta pows): counts
                        # toward per-rank AND full (it is not sharded)
                        b = self._numel(v) * self._itemsize(v)
                        per_rank += b
                        replicated += b
                    continue
                self._make_shard_var(main, startup, v, pad)
                name_map[name] = name + _SHARD_SUFFIX
                shard_names.append(name + _SHARD_SUFFIX)
                if is_accum:
                    b = self._itemsize(v)
                    per_rank += shard_len * b
                    replicated += numel * b
                    self._drop_full_accumulator(main, startup, name)
        master = shard_len * self._itemsize(p)
        for slot, names in list(op.inputs.items()):
            op.inputs[slot] = [name_map.get(n, n) for n in names]
        for slot, names in list(op.outputs.items()):
            op.outputs[slot] = [name_map.get(n, n) for n in names]

        # 3. all-gather the updated master shard back into the parameter
        upd_idx = next(i for i, o in enumerate(block.ops) if o is op)
        block.append_op(
            "zero_all_gather",
            inputs={"X": [p.name + _SHARD_SUFFIX]},
            outputs={"Out": [p.name]},
            attrs=self._zero_attrs(
                {"shape": list(pshape), "pad_len": pad}
            ),
            index=upd_idx + 1,
        )
        return per_rank, replicated, master

    @staticmethod
    def _numel(v):
        n = 1
        for d in v.shape or ():
            n *= int(d)
        return n

    @staticmethod
    def _drop_full_accumulator(main, startup, name):
        """The full-size accumulator must never materialize: delete its
        main-block declaration (the rewritten update op no longer reads
        it) and demote its startup var to non-persistable, so the init
        value feeds the shard's zero_pad_flatten and is then dropped
        instead of being written back to the scope full-size."""
        main.global_block.vars.pop(name, None)
        sv = startup.global_block.vars.get(name)
        if sv is not None:
            sv.persistable = False

    def _rewrite_amp(self, block):
        """Point the AMP bookkeeping ops at the grad shards and make their
        FoundInfinite rank-uniform (each rank now checks only its 1/N
        shard, so 'any rank overflowed' needs a collective)."""
        shard_map = {
            op.inputs["X"][0]: op.outputs["Out"][0]
            for op in block.ops
            if op.type == "zero_reduce_scatter"
        }
        inserts = []
        for i, op in enumerate(block.ops):
            if op.type not in _AMP_CHECK_OPS:
                continue
            for slot in ("X", "Out"):
                names = op.inputs.get(slot) if slot == "X" else \
                    op.outputs.get(slot)
                if not names:
                    continue
                rewritten = [shard_map.get(n, n) for n in names]
                if slot == "X":
                    op.inputs[slot] = rewritten
                else:
                    op.outputs[slot] = rewritten
            if op.type == "check_finite_and_unscale":
                found = op.outputs["FoundInfinite"][0]
                inserts.append((i + 1, found))
        for offset, (i, found) in enumerate(inserts):
            block.append_op(
                "c_allreduce_any",
                inputs={"X": [found]},
                outputs={"Out": [found]},
                attrs={"axis_name": self.axis_name},
                index=i + offset,
            )


class LocalSGD:
    """Periodic model averaging (transpiler/collective.py:270).

    Emits a `c_allreduce_sum` + scale over each parameter; the caller runs
    the returned averaging program every k steps.
    """

    def __init__(self, nranks, axis_name=DATA_AXIS):
        self.nranks = nranks
        self.axis_name = axis_name

    def build_average_program(self, main_program):
        from ..framework.program import Program

        avg = Program()
        avg._mesh = main_program._mesh
        avg._sharding = dict(main_program._sharding)
        block = avg.global_block
        for p in main_program.all_parameters():
            if not getattr(p, "trainable", False):
                continue
            block.create_var(
                name=p.name, shape=p.shape, dtype=p.dtype, persistable=True
            )
            block.append_op(
                "scale",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"scale": 1.0 / self.nranks, "bias": 0.0},
            )
            block.append_op(
                "c_allreduce_sum",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"axis_name": self.axis_name},
            )
        return avg
