"""Collective program rewriters: DP grad-allreduce, LocalSGD.

TPU-native analog of the reference's transpiler/collective.py:
  * GradAllReduce (:178): scale loss grad by 1/nranks (:190) and insert
    `c_allreduce_sum` per gradient (:209). Here the collectives are emitted
    as ops whose emitters call lax.psum under shard_map — no c_gen_nccl_id /
    c_comm_init startup rewrite (:99-132) is needed: mesh construction
    replaces communicator bootstrap.
  * LocalSGD (:270): periodic parameter averaging across the dp axis.

The reference also had to pin a deterministic allreduce order
(all_reduce_deps_pass.cc) and fuse gradient buffers
(coalesce_grad_tensor_pass.cc) by hand; XLA's latency-hiding scheduler and
collective combiner do both automatically.
"""

from __future__ import annotations

from .mesh import DATA_AXIS


# AMP bookkeeping ops rewrite grads in place *after* they are produced; the
# DP allreduce must land before them so every shard's FoundInfinite /
# loss-scale state is computed from identical (globally reduced) gradients.
_AMP_CHECK_OPS = frozenset({"check_finite_and_unscale", "update_loss_scaling"})


def _insert_pos_after(block, names):
    """Index just after the last non-AMP op producing any of `names`."""
    pos = 0
    names = set(names)
    for i, op in enumerate(block.ops):
        if op.type in _AMP_CHECK_OPS:
            continue
        if names & set(op.output_names()):
            pos = i + 1
    return pos


def insert_grad_allreduce(block, grad, axis_name, scale=None):
    """Insert (optional scale +) c_allreduce_sum on a gradient, right after
    its producer and BEFORE any AMP bookkeeping ops (_insert_pos_after):
    FoundInfinite / loss-scale state must be computed from the globally
    reduced gradients or they diverge across ranks. One definition shared
    by the dp transpile, the pipeline optimizers, and leg builders."""
    gname = grad.name if hasattr(grad, "name") else str(grad)
    pos = _insert_pos_after(block, [gname])
    if scale is not None:
        block.append_op(
            "scale",
            inputs={"X": [gname]},
            outputs={"Out": [gname]},
            attrs={"scale": scale, "bias": 0.0},
            index=pos,
        )
        pos += 1
    block.append_op(
        "c_allreduce_sum",
        inputs={"X": [gname]},
        outputs={"Out": [gname]},
        attrs={"axis_name": axis_name},
        index=pos,
    )


class GradAllReduce:
    """Insert per-gradient allreduce into a trained program (DP mode)."""

    def __init__(self, nranks, axis_name=DATA_AXIS):
        self.nranks = nranks
        self.axis_name = axis_name

    def transpile(self, program, params_grads):
        block = program.global_block
        for _, g in params_grads:
            # mean-reduce: scale by 1/nranks then psum — identical math to
            # the reference's loss-grad scaling (transpiler/collective.py:190)
            insert_grad_allreduce(
                block, g, self.axis_name, scale=1.0 / self.nranks
            )
        return program


class LocalSGD:
    """Periodic model averaging (transpiler/collective.py:270).

    Emits a `c_allreduce_sum` + scale over each parameter; the caller runs
    the returned averaging program every k steps.
    """

    def __init__(self, nranks, axis_name=DATA_AXIS):
        self.nranks = nranks
        self.axis_name = axis_name

    def build_average_program(self, main_program):
        from ..framework.program import Program

        avg = Program()
        avg._mesh = main_program._mesh
        avg._sharding = dict(main_program._sharding)
        block = avg.global_block
        for p in main_program.all_parameters():
            if not getattr(p, "trainable", False):
                continue
            block.create_var(
                name=p.name, shape=p.shape, dtype=p.dtype, persistable=True
            )
            block.append_op(
                "scale",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"scale": 1.0 / self.nranks, "bias": 0.0},
            )
            block.append_op(
                "c_allreduce_sum",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"axis_name": self.axis_name},
            )
        return avg
