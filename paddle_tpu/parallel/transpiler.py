"""Collective program rewriters: DP grad-allreduce, ZeRO weight-update
sharding, LocalSGD.

TPU-native analog of the reference's transpiler/collective.py:
  * GradAllReduce (:178): scale loss grad by 1/nranks (:190) and insert
    `c_allreduce_sum` per gradient (:209). Here the collectives are emitted
    as ops whose emitters call lax.psum under shard_map — no c_gen_nccl_id /
    c_comm_init startup rewrite (:99-132) is needed: mesh construction
    replaces communicator bootstrap.
  * ShardedWeightUpdate: ZeRO-style cross-replica sharding of the weight
    update (arXiv:2004.13336) — per-grad allreduce becomes reduce-scatter,
    the optimizer update (moments, master shard) runs on each rank's 1/N
    flat shard only, and the updated parameters all-gather back. Optimizer
    state is genuinely 1/N per rank; wire bytes are unchanged in fp
    (reduce-scatter + all-gather = allreduce) and ~4x smaller with the
    opt-in int8 block-quantized collectives (arXiv:2506.17615, EQuARX).
  * LocalSGD (:270): periodic parameter averaging across the dp axis.

The reference also had to pin a deterministic allreduce order
(all_reduce_deps_pass.cc) and fuse gradient buffers
(coalesce_grad_tensor_pass.cc) by hand; XLA's latency-hiding scheduler and
collective combiner do both automatically.
"""

from __future__ import annotations

import math

from .mesh import DATA_AXIS


# AMP bookkeeping ops rewrite grads in place *after* they are produced; the
# DP allreduce must land before them so every shard's FoundInfinite /
# loss-scale state is computed from identical (globally reduced) gradients.
_AMP_CHECK_OPS = frozenset({"check_finite_and_unscale", "update_loss_scaling"})


def _insert_pos_after(block, names):
    """Index just after the last non-AMP op producing any of `names`."""
    pos = 0
    names = set(names)
    for i, op in enumerate(block.ops):
        if op.type in _AMP_CHECK_OPS:
            continue
        if names & set(op.output_names()):
            pos = i + 1
    return pos


def plan_grad_buckets(block, entries, bucket_bytes):
    """Group gradients into size-targeted buckets in reverse-topological
    order — the order the backward PRODUCES them (last forward layer's
    grads first), which is ascending producer index in the block.

    `entries` is a list of dicts with at least ``name`` (grad var name),
    ``nbytes`` (payload size) and ``group`` (dtype key: members of one
    bucket concatenate, so they must share a dtype). A grad that would
    push a non-empty bucket past `bucket_bytes` CLOSES that bucket and
    starts the next one — straddling grads move whole, never split, so a
    single grad larger than the target gets a bucket of its own.

    Returns buckets ordered by firing position: each is a dict with
    ``members`` (entries, production order), ``nbytes``, and ``pos`` (the
    block index just after the LAST member's producer — the earliest
    point the whole bucket is ready to fire). Bucket membership and order
    are part of the cross-rank collective contract: every rank must plan
    the same buckets (analysis/collectives.py carries the membership in
    the site kind, so divergence is a build-time ERROR, not a pod hang).
    """
    bucket_bytes = int(bucket_bytes)
    if bucket_bytes <= 0:
        raise ValueError(
            f"plan_grad_buckets: bucket_bytes must be positive, got "
            f"{bucket_bytes!r}"
        )
    order = []
    for i, e in enumerate(entries):
        order.append((_insert_pos_after(block, [e["name"]]), i, e))
    order.sort(key=lambda t: (t[0], t[1]))
    buckets = []
    open_ = {}  # dtype group -> accumulating bucket
    for pos, _i, e in order:
        g = e.get("group")
        b = open_.get(g)
        if b is not None and b["nbytes"] + e["nbytes"] > bucket_bytes:
            buckets.append(open_.pop(g))
            b = None
        if b is None:
            b = {"members": [], "nbytes": 0, "pos": 0, "group": g}
            open_[g] = b
        b["members"].append(e)
        b["nbytes"] += int(e["nbytes"])
        b["pos"] = max(b["pos"], pos)
    buckets.extend(b for b in open_.values() if b["members"])
    buckets.sort(key=lambda b: b["pos"])
    return buckets


def _normalize_bucket_bytes(value):
    """None/0/"" -> None (per-grad schedule); a positive int enables
    bucketing; anything negative refuses, naming the knob."""
    if not value:
        return None
    value = int(value)
    if value < 0:
        raise ValueError(
            f"bucket_bytes must be a positive byte count (or 0/None for "
            f"the per-grad schedule), got {value!r}"
        )
    return value


def _hoist_grad_finalizers(block, names):
    """The backward appends each gradient's finalizing op (the
    ``@GRAD@RENAME -> @GRAD`` assign, or the multi-part sum) at the block
    TAIL in forward-parameter order — which would pin every bucket's
    firing position behind the entire backward. Hoist each grad's last
    producer to its dataflow frontier (right after the vjp that computed
    it) so bucket positions reflect the TRUE reverse-topological
    production order. Pure reordering via :func:`_hoist_earliest`."""
    for name in names:
        producer = None
        for op in block.ops:
            if op.type in _AMP_CHECK_OPS:
                continue
            if name in op.output_names():
                producer = op
        if producer is not None:
            _hoist_earliest(block, producer)


def _hoist_earliest(block, op):
    """Move `op` to the earliest block index its dataflow allows: after
    the last producer of anything it reads, after the last reader of
    anything it writes (write-after-read — e.g. a hoisted zero_all_gather
    rewrites the param the backward still reads), and after the last
    OTHER writer of anything it writes. Returns the new index. This is
    the prefetch pass's only primitive — it can only tighten the
    schedule, never change a value."""
    ops = block.ops
    i = ops.index(op)
    reads = set(op.input_names())
    writes = set(op.output_names())
    barrier = -1
    for j in range(i):
        o = ops[j]
        if (set(o.output_names()) & (reads | writes)) or (
            set(o.input_names()) & writes
        ):
            barrier = j
    if barrier + 1 >= i:
        return i
    ops.pop(i)
    ops.insert(barrier + 1, op)
    block.program._bump()
    return barrier + 1


def insert_grad_allreduce(block, grad, axis_name, scale=None):
    """Insert (optional scale +) c_allreduce_sum on a gradient, right after
    its producer and BEFORE any AMP bookkeeping ops (_insert_pos_after):
    FoundInfinite / loss-scale state must be computed from the globally
    reduced gradients or they diverge across ranks. One definition shared
    by the dp transpile, the pipeline optimizers, and leg builders."""
    gname = grad.name if hasattr(grad, "name") else str(grad)
    pos = _insert_pos_after(block, [gname])
    if scale is not None:
        block.append_op(
            "scale",
            inputs={"X": [gname]},
            outputs={"Out": [gname]},
            attrs={"scale": scale, "bias": 0.0},
            index=pos,
        )
        pos += 1
    block.append_op(
        "c_allreduce_sum",
        inputs={"X": [gname]},
        outputs={"Out": [gname]},
        attrs={"axis_name": axis_name},
        index=pos,
    )


class GradAllReduce:
    """Insert per-gradient allreduce into a trained program (DP mode).

    ``bucket_bytes`` switches the schedule to BUCKETED collectives: grads
    group into size-targeted buckets in reverse-topological (backward
    production) order and each bucket issues ONE ``c_bucket_allreduce_sum``
    as soon as its last member gradient is produced — early buckets' wire
    time hides behind the remaining backward compute, and the per-grad
    dispatch overhead collapses to one collective per bucket. The per-grad
    1/N scale stays a separate op (identical math), so the fp32 result is
    BITWISE the per-grad schedule's."""

    def __init__(self, nranks, axis_name=DATA_AXIS, bucket_bytes=None):
        self.nranks = nranks
        self.axis_name = axis_name
        self.bucket_bytes = _normalize_bucket_bytes(bucket_bytes)

    def transpile(self, program, params_grads):
        block = program.global_block
        if not self.bucket_bytes:
            for _, g in params_grads:
                # mean-reduce: scale by 1/nranks then psum — identical
                # math to the reference's loss-grad scaling
                # (transpiler/collective.py:190)
                insert_grad_allreduce(
                    block, g, self.axis_name, scale=1.0 / self.nranks
                )
            return program
        from .. import observability as _obs

        _hoist_grad_finalizers(
            block,
            [g.name if hasattr(g, "name") else str(g)
             for _, g in params_grads],
        )
        entries = []
        for _, g in params_grads:
            gname = g.name if hasattr(g, "name") else str(g)
            v = block._find_var_recursive(gname)
            numel = 1
            for d in (v.shape if v is not None else ()) or ():
                numel *= int(d)
            itemsize = ShardedWeightUpdate._itemsize(v) if v is not None \
                else 4
            entries.append({
                "name": gname, "numel": numel, "nbytes": numel * itemsize,
                "group": str((v.dtype if v is not None else None)
                             or "float32"),
            })
        buckets = plan_grad_buckets(block, entries, self.bucket_bytes)
        # insert back-to-front so earlier buckets' positions stay valid
        for b in reversed(buckets):
            names = [e["name"] for e in b["members"]]
            pos = b["pos"]
            for gname in names:  # per-grad mean scale, exactly like legacy
                block.append_op(
                    "scale",
                    inputs={"X": [gname]},
                    outputs={"Out": [gname]},
                    attrs={"scale": 1.0 / self.nranks, "bias": 0.0},
                    index=pos,
                )
                pos += 1
            block.append_op(
                "c_bucket_allreduce_sum",
                inputs={"X": names},
                outputs={"Out": names},
                attrs={
                    "axis_name": self.axis_name,
                    "bucket_numels": [
                        int(e["numel"]) for e in b["members"]
                    ],
                },
                index=pos,
            )
        program._overlap_schedule = True
        _obs.add("collective.bucketed_grad_tensors", len(entries))
        _obs.set_gauge("collective.bucket_count", len(buckets))
        return program


# the per-parameter update-op family the sharded rewrite understands
# (ops/optimizer_ops.py emitters are elementwise over Param/Grad/moments,
# which is exactly what makes the 1/N flat-shard rewrite sound)
UPDATE_OPS = frozenset({
    "sgd", "momentum", "adam", "adamw", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "ftrl", "adamax", "dpsgd", "proximal_gd",
    "proximal_adagrad",
})

# update ops whose math reads FULL-tensor reductions (LAMB's trust ratio,
# LARS's local lr are ||param||/||grad|| over the whole tensor): a
# shard-local norm silently changes the update, so these refuse to shard
_NORM_COUPLED_UPDATE_OPS = frozenset({"lamb", "lars_momentum",
                                      "dgc_momentum_step"})

_SHARD_SUFFIX = "@ZERO_SHARD"


class ShardedWeightUpdate:
    """Rewrite a trained DP program to the ZeRO-style sharded update.

    Runs AFTER ``apply_gradients`` (the update ops must exist). Per
    parameter:

    1. a ``zero_reduce_scatter`` (scale 1/N folded in) lands right after
       the grad producer and BEFORE the AMP bookkeeping ops, leaving each
       rank the globally averaged flat ``[pad/N]`` shard of its gradient;
    2. the optimizer update op is rewritten onto dp-sharded flat state:
       the param's master shard plus every same-shaped accumulator
       (moments, velocities) becomes a persistable ``[pad]`` var with
       sharding spec ``("dp",)`` — 1/N of it lives on each rank; the full
       accumulators are deleted from main AND startup (their startup init
       feeds a ``zero_pad_flatten`` into the shard instead, so the full
       tensor never reaches the scope);
    3. a ``zero_all_gather`` immediately after the update reassembles the
       full parameter (replicated) for the next forward.

    AMP programs get their ``check_finite_and_unscale`` /
    ``update_loss_scaling`` X lists rewritten onto the grad shards, with a
    ``c_allreduce_any`` on FoundInfinite so the loss-scale automaton stays
    rank-uniform.

    ``quant="int8"`` selects the block-quantized wire format for both
    collectives (``quant_block`` values per fp32 scale); padding is then
    aligned to ``nranks * quant_block`` so every shard quantizes in whole
    blocks.

    Overlap schedule (ROADMAP item 4): ``bucket_bytes`` groups the
    reduce-scatters into size-targeted buckets in reverse-topological
    order — one ``zero_bucket_reduce_scatter`` per bucket, fired as soon
    as the bucket's LAST member gradient is produced, so early buckets'
    wire time hides behind the remaining backward compute. ``prefetch``
    (default on) additionally hoists every shard update and its
    ``zero_all_gather`` to the earliest dataflow-legal position — the
    all-gather fires as soon as its shard update completes instead of
    sitting at the program tail, giving XLA's latency-hiding scheduler
    async-dispatch structure it can overlap with the remaining backward.
    Both are pure schedule transforms: fp32 results stay BITWISE equal to
    the serialized schedule, int8 stays bitwise equal to per-grad int8
    (member pads are block-aligned, so quant blocks never straddle
    members).

    Not supported (raises ``NotImplementedError``): grad clipping and
    regularization (both read full-tensor gradients after the insertion
    point — a shard-local norm would silently change the math) and DGC
    (its fused op owns its own collective).
    """

    def __init__(self, nranks, axis_name=DATA_AXIS, quant=None,
                 quant_block=256, bucket_bytes=None, prefetch=True):
        self.nranks = int(nranks)
        self.axis_name = axis_name
        self.quant = quant if quant not in (None, "", "none") else "none"
        if self.quant not in ("none", "int8"):
            # an unknown string must not silently select the int8 kernel
            # (and leak into collective.bytes.* counter names)
            raise ValueError(
                f"shard_weight_update: unknown collective quantization "
                f"{quant!r}; supported: None | 'int8'"
            )
        self.quant_block = int(quant_block)
        if self.quant_block < 1:
            raise ValueError(
                f"shard_weight_update: collective_quant_block must be a "
                f"positive element count, got {quant_block!r}"
            )
        self.bucket_bytes = _normalize_bucket_bytes(bucket_bytes)
        self.prefetch = bool(prefetch)

    # -- helpers -----------------------------------------------------------
    def _pad_len(self, numel):
        align = self.nranks * (
            self.quant_block if self.quant != "none" else 1
        )
        return int(math.ceil(numel / align) * align)

    @staticmethod
    def _itemsize(v):
        import numpy as np

        from ..core.dtypes import to_numpy_dtype

        try:
            return np.dtype(to_numpy_dtype(v.dtype or "float32")).itemsize
        except Exception:
            return 4

    def _zero_attrs(self, extra=None):
        attrs = {
            "axis_name": self.axis_name,
            "quant": self.quant,
            "quant_block": self.quant_block,
        }
        attrs.update(extra or {})
        return attrs

    def _make_shard_var(self, main, startup, src_var, pad_len,
                        init_from=None):
        """Create the persistable ``[pad_len]`` dp-sharded counterpart of
        `src_var` in main + startup, with its startup value derived from
        `init_from` (default: the source var itself) via zero_pad_flatten."""
        sname = src_var.name + _SHARD_SUFFIX
        blk = main.global_block
        sb = startup.global_block
        v = blk.create_var(
            name=sname, shape=[pad_len], dtype=src_var.dtype or "float32",
            persistable=True,
        )
        v.stop_gradient = True
        v._zero_shard_of = src_var.name
        sb.create_var(
            name=sname, shape=[pad_len], dtype=src_var.dtype or "float32",
            persistable=True,
        )
        sb.append_op(
            "zero_pad_flatten",
            {"X": [init_from or src_var.name]},
            {"Out": [sname]},
            {"pad_len": pad_len},
        )
        main._sharding[sname] = (self.axis_name,)
        return v

    @staticmethod
    def _find_update_op(block, pname):
        for i, op in enumerate(block.ops):
            if op.type in UPDATE_OPS and (
                op.inputs.get("Param") == [pname]
            ):
                grad = (op.inputs.get("Grad") or [""])[0]
                if not grad.endswith(_SHARD_SUFFIX):  # not rewritten yet
                    return i, op
        return None, None

    # -- the pass ----------------------------------------------------------
    def transpile(self, main, startup, params_grads):
        from .. import observability as _obs

        block = main.global_block
        for op in block.ops:
            if op.type in _NORM_COUPLED_UPDATE_OPS:
                raise NotImplementedError(
                    f"shard_weight_update: {op.type!r} reads full-tensor "
                    "state (trust-ratio norms / its own collective) and "
                    "cannot be flat-sharded; use adam/momentum/sgd-family "
                    "optimizers or disable sharding"
                )
        for p, _g in params_grads:
            if getattr(p, "regularizer", None) is not None:
                raise NotImplementedError(
                    f"shard_weight_update: parameter {p.name!r} carries a "
                    "per-param regularizer, which rewrites its gradient "
                    "after the reduce-scatter insertion point"
                )
        # mesh-sharded sparse embedding tables compose, not conflict: their
        # storage, grads and accumulators are already partitioned over the
        # "ps" axis (parallel/sparse.py) and their gradients are
        # dp-replicated (ids feed replicated), so the dense ZeRO rewrite
        # must SKIP them — flat-[pad] dp-sharding a ps-sharded table would
        # fight its row/column spec and its in-graph grad exchange
        from .sparse import sparse_table_names

        sparse = set(sparse_table_names(main))
        skipped_sparse = [p.name for p, _g in params_grads
                          if p.name in sparse]
        params_grads = [pg for pg in params_grads
                        if pg[0].name not in sparse]
        if skipped_sparse:
            _obs.add("collective.zero_sparse_tables_skipped",
                     len(skipped_sparse))

        # plan first: every param's update op, grad name, and pad — the
        # bucketed path needs the full grad set before any insertion
        plans = []
        unshardable = []
        for p, _g in params_grads:
            _idx, op = self._find_update_op(block, p.name)
            if op is None:
                # a param with no recognizable update op would be left
                # with NEITHER a reduce-scatter NOR an allreduce (the
                # fleet path skips GradAllReduce entirely in sharded
                # mode) — the replicas would silently diverge
                unshardable.append(p.name)
                continue
            gname = op.inputs["Grad"][0]
            if "@CLIP" in gname:
                # every clip.py path (value / per-tensor norm / global
                # norm) hands the update op a "<grad>@CLIP*" rewrite;
                # clipping by a rank-LOCAL norm before the reduce-scatter
                # is different math from the allreduce baseline (which
                # reduces first), so refuse here too — not only in the
                # fleet wrapper
                raise NotImplementedError(
                    "shard_weight_update: gradient clipping rewrites "
                    f"{gname!r} with rank-local norms before the "
                    "reduce-scatter would land; clipping does not compose "
                    "with the sharded update yet"
                )
            numel = 1
            for d in p.shape:
                numel *= int(d)
            plans.append((p, op, gname, numel, self._pad_len(numel)))
        if unshardable:
            raise NotImplementedError(
                "shard_weight_update: no supported update op found for "
                f"parameters {unshardable} (supported: "
                f"{sorted(UPDATE_OPS)}); their gradients would stay "
                "rank-local and the replicas would diverge"
            )
        if self.bucket_bytes:
            gshards = self._insert_bucketed_reduce_scatters(main, plans)
        else:
            gshards = self._insert_reduce_scatters(main, plans)
        per_rank = replicated = master = 0
        shard_names = []
        for p, op, gname, numel, pad in plans:
            pr, rep, ms = self._rewrite_update(
                main, startup, p, op, gname, gshards[gname], numel, pad,
                shard_names,
            )
            per_rank += pr
            replicated += rep
            master += ms
        self._rewrite_amp(block)
        moved = 0
        if self.prefetch:
            moved = self._prefetch_all_gathers(block)
            if moved:
                _obs.add("collective.zero_prefetched_gathers", moved)
        if self.bucket_bytes or moved:
            # the cost model's scheduled (overlap-aware) step estimate
            # applies only to programs whose collective schedule was
            # actually restructured for overlap
            main._overlap_schedule = True
        main._zero_shard_vars = tuple(shard_names)
        main._zero_quant = self.quant
        main._bump()
        _obs.add("collective.zero_sharded_tensors", len(params_grads))
        _obs.set_gauge("collective.zero_dp_degree", self.nranks)
        _obs.set_gauge(
            "collective.zero_optimizer_state_bytes_per_rank", per_rank
        )
        _obs.set_gauge(
            "collective.zero_optimizer_state_bytes_full", replicated
        )
        _obs.set_gauge("collective.zero_master_shard_bytes_per_rank", master)
        return main

    def _make_grad_shard(self, main, gname, pad):
        """Declare the flat dp-sharded [pad] counterpart of gradient
        `gname` (the reduce-scatter's output)."""
        block = main.global_block
        gvar = block._find_var_recursive(gname)
        gshard = gname + _SHARD_SUFFIX
        gv = block.create_var(
            name=gshard, shape=[pad],
            dtype=(gvar.dtype if gvar is not None else None) or "float32",
        )
        gv.stop_gradient = True
        main._sharding[gshard] = (self.axis_name,)
        return gshard, str((gvar.dtype if gvar is not None else None)
                           or "float32")

    def _insert_reduce_scatters(self, main, plans):
        """Per-grad schedule: one zero_reduce_scatter per gradient (mean
        scale folded in), landing right after the grad's producer and
        before the AMP bookkeeping ops, exactly like
        insert_grad_allreduce. Returns {grad name: shard name}."""
        block = main.global_block
        if self.prefetch:
            # the overlap schedule wants each reduce-scatter at its
            # grad's TRUE production point (see _hoist_grad_finalizers),
            # so the hoisted updates/all-gathers can interleave with the
            # remaining backward
            _hoist_grad_finalizers(block, [pl[2] for pl in plans])
        gshards = {}
        for _p, _op, gname, _numel, pad in plans:
            gshard, _dtype = self._make_grad_shard(main, gname, pad)
            gshards[gname] = gshard
            pos = _insert_pos_after(block, [gname])
            block.append_op(
                "zero_reduce_scatter",
                inputs={"X": [gname]},
                outputs={"Out": [gshard]},
                attrs=self._zero_attrs(
                    {"scale": 1.0 / self.nranks, "pad_len": pad}
                ),
                index=pos,
            )
        return gshards

    def _insert_bucketed_reduce_scatters(self, main, plans):
        """Bucketed schedule: grads group into size-targeted buckets in
        reverse-topological (backward production) order; each bucket
        issues ONE zero_bucket_reduce_scatter as soon as its last member
        gradient is produced. Returns {grad name: shard name}."""
        from .. import observability as _obs

        block = main.global_block
        _hoist_grad_finalizers(block, [pl[2] for pl in plans])
        entries = []
        by_name = {}
        for _p, _op, gname, numel, pad in plans:
            gvar = block._find_var_recursive(gname)
            itemsize = self._itemsize(gvar) if gvar is not None else 4
            e = {
                "name": gname, "numel": numel, "pad": pad,
                "nbytes": numel * itemsize,
                "group": str((gvar.dtype if gvar is not None else None)
                             or "float32"),
            }
            entries.append(e)
            by_name[gname] = e
        buckets = plan_grad_buckets(block, entries, self.bucket_bytes)
        gshards = {}
        # insert back-to-front so earlier buckets' positions stay valid
        for b in reversed(buckets):
            names = [e["name"] for e in b["members"]]
            outs = []
            for e in b["members"]:
                gshard, _dtype = self._make_grad_shard(
                    main, e["name"], e["pad"]
                )
                gshards[e["name"]] = gshard
                outs.append(gshard)
            block.append_op(
                "zero_bucket_reduce_scatter",
                inputs={"X": names},
                outputs={"Out": outs},
                attrs=self._zero_attrs({
                    "scale": 1.0 / self.nranks,
                    "pad_lens": [int(e["pad"]) for e in b["members"]],
                }),
                index=b["pos"],
            )
        _obs.set_gauge("collective.bucket_count", len(buckets))
        return gshards

    def _prefetch_all_gathers(self, block):
        """Hoist every rewritten shard update and its zero_all_gather to
        the earliest dataflow-legal position: the update fires as soon as
        its grad shard (and, under AMP, the loss-scale bookkeeping that
        rewrites it) is ready, and the param's all-gather launches
        immediately after — in flight while the remaining backward
        computes, instead of queued at the program tail. Pure reordering
        under `_hoist_earliest`'s hazard barriers (the all-gather rewrites
        the param, so it can never cross a backward op still reading it).
        Returns how many ops actually moved."""
        moved = 0
        updates = [
            op for op in list(block.ops)
            if op.type in UPDATE_OPS
            and (op.inputs.get("Grad") or [""])[0].endswith(_SHARD_SUFFIX)
        ]
        gathers = {
            op.inputs["X"][0]: op
            for op in block.ops if op.type == "zero_all_gather"
        }
        for upd in updates:
            before = block.ops.index(upd)
            if _hoist_earliest(block, upd) != before:
                moved += 1
            gather = gathers.get(upd.inputs["Param"][0])
            if gather is not None:
                before = block.ops.index(gather)
                if _hoist_earliest(block, gather) != before:
                    moved += 1
        return moved

    def _rewrite_update(self, main, startup, p, op, gname, gshard, numel,
                        pad, shard_names):
        block = main.global_block
        shard_len = pad // self.nranks

        # 2. rewrite the update op onto sharded flat state
        name_map = {gname: gshard}
        per_rank = replicated = 0
        pshape = tuple(int(d) for d in p.shape)
        for slot, names in op.inputs.items():
            for name in names:
                if name in name_map or name == gname:
                    continue
                v = block._find_var_recursive(name)
                if v is None:
                    continue
                is_param = name == p.name
                elementwise = getattr(v, "_accum_elementwise", None)
                if elementwise is None:  # untagged: fall back on shape
                    elementwise = (
                        tuple(int(d) for d in (v.shape or ())) == pshape
                    )
                is_accum = (
                    getattr(v, "_accum_of", None) == p.name and elementwise
                )
                if not (is_param or is_accum):
                    if getattr(v, "_accum_of", None) == p.name:
                        # replicated small state ([1] beta pows): counts
                        # toward per-rank AND full (it is not sharded)
                        b = self._numel(v) * self._itemsize(v)
                        per_rank += b
                        replicated += b
                    continue
                self._make_shard_var(main, startup, v, pad)
                name_map[name] = name + _SHARD_SUFFIX
                shard_names.append(name + _SHARD_SUFFIX)
                if is_accum:
                    b = self._itemsize(v)
                    per_rank += shard_len * b
                    replicated += numel * b
                    self._drop_full_accumulator(main, startup, name)
        master = shard_len * self._itemsize(p)
        for slot, names in list(op.inputs.items()):
            op.inputs[slot] = [name_map.get(n, n) for n in names]
        for slot, names in list(op.outputs.items()):
            op.outputs[slot] = [name_map.get(n, n) for n in names]

        # 3. all-gather the updated master shard back into the parameter
        upd_idx = next(i for i, o in enumerate(block.ops) if o is op)
        block.append_op(
            "zero_all_gather",
            inputs={"X": [p.name + _SHARD_SUFFIX]},
            outputs={"Out": [p.name]},
            attrs=self._zero_attrs(
                {"shape": list(pshape), "pad_len": pad}
            ),
            index=upd_idx + 1,
        )
        return per_rank, replicated, master

    @staticmethod
    def _numel(v):
        n = 1
        for d in v.shape or ():
            n *= int(d)
        return n

    @staticmethod
    def _drop_full_accumulator(main, startup, name):
        """The full-size accumulator must never materialize: delete its
        main-block declaration (the rewritten update op no longer reads
        it) and demote its startup var to non-persistable, so the init
        value feeds the shard's zero_pad_flatten and is then dropped
        instead of being written back to the scope full-size."""
        main.global_block.vars.pop(name, None)
        sv = startup.global_block.vars.get(name)
        if sv is not None:
            sv.persistable = False

    def _rewrite_amp(self, block):
        """Point the AMP bookkeeping ops at the grad shards and make their
        FoundInfinite rank-uniform (each rank now checks only its 1/N
        shard, so 'any rank overflowed' needs a collective)."""
        shard_map = {}
        for op in block.ops:
            if op.type == "zero_reduce_scatter":
                shard_map[op.inputs["X"][0]] = op.outputs["Out"][0]
            elif op.type == "zero_bucket_reduce_scatter":
                # bucket members map pairwise: X[i]'s shard is Out[i]
                shard_map.update(zip(op.inputs["X"], op.outputs["Out"]))
        inserts = []
        for i, op in enumerate(block.ops):
            if op.type not in _AMP_CHECK_OPS:
                continue
            for slot in ("X", "Out"):
                names = op.inputs.get(slot) if slot == "X" else \
                    op.outputs.get(slot)
                if not names:
                    continue
                rewritten = [shard_map.get(n, n) for n in names]
                if slot == "X":
                    op.inputs[slot] = rewritten
                else:
                    op.outputs[slot] = rewritten
            if op.type == "check_finite_and_unscale":
                found = op.outputs["FoundInfinite"][0]
                inserts.append((i + 1, found))
        for offset, (i, found) in enumerate(inserts):
            block.append_op(
                "c_allreduce_any",
                inputs={"X": [found]},
                outputs={"Out": [found]},
                attrs={"axis_name": self.axis_name},
                index=i + offset,
            )


class LocalSGD:
    """Periodic model averaging (transpiler/collective.py:270).

    Emits a `c_allreduce_sum` + scale over each parameter; the caller runs
    the returned averaging program every k steps.
    """

    def __init__(self, nranks, axis_name=DATA_AXIS):
        self.nranks = nranks
        self.axis_name = axis_name

    def build_average_program(self, main_program):
        from ..framework.program import Program

        avg = Program()
        avg._mesh = main_program._mesh
        avg._sharding = dict(main_program._sharding)
        block = avg.global_block
        for p in main_program.all_parameters():
            if not getattr(p, "trainable", False):
                continue
            block.create_var(
                name=p.name, shape=p.shape, dtype=p.dtype, persistable=True
            )
            block.append_op(
                "scale",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"scale": 1.0 / self.nranks, "bias": 0.0},
            )
            block.append_op(
                "c_allreduce_sum",
                inputs={"X": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"axis_name": self.axis_name},
            )
        return avg
