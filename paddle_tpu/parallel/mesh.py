"""Device-mesh management: the TPU-native replacement for communicator rings.

The reference manages NCCL communicators keyed by (ring_id, device)
(platform/collective_helper.h:62, nccl_helper.h:91) and builds flat +
hierarchical rings by hand (nccl_helper.h:180). On TPU, topology is the
compiler's job: the framework only names logical mesh axes ("dp", "mp",
"pp", "sp", "ep") over `jax.sharding.Mesh`, and XLA lays collectives onto
ICI (intra-slice) / DCN (inter-slice) links itself.

A named axis replaces a ring_id; `replica_groups` are derived from the mesh
by XLA. Hierarchical allreduce (build_strategy.h:135) needs no framework
code at all — a 2D (dcn, ici) mesh expresses it.
"""

from __future__ import annotations

import contextlib

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec

# Canonical axis names used across the framework.
DATA_AXIS = "dp"  # data parallel (batch sharding, grad allreduce)
MODEL_AXIS = "mp"  # tensor/model parallel (weight sharding)
PIPE_AXIS = "pp"  # pipeline stages
SEQ_AXIS = "sp"  # sequence/context parallel (ring attention)
EXPERT_AXIS = "ep"  # expert parallel (MoE all_to_all)

_current_mesh = None


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the device
    count; an axis size of -1 absorbs the remainder (like a reshape -1).

    make_mesh() with no args -> 1-axis "dp" mesh over all devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {DATA_AXIS: n}
    names, sizes = list(axes.keys()), [int(s) for s in axes.values()]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"cannot infer -1 axis: {n} devices, known {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}"
        )
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def current_mesh():
    return _current_mesh


@contextlib.contextmanager
def mesh_guard(mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = old


def set_global_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh


def spec(*axes) -> PartitionSpec:
    """PartitionSpec shorthand: spec("dp") == P("dp"); spec() == replicated."""
    return PartitionSpec(*axes)
