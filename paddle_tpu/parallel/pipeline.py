"""Pipeline parallelism (reference PipelineOptimizer optimizer.py:3556 +
SectionWorker section_worker.cc:142).

Reference design: the program is cut into sections by `op_device`
annotations; each section runs in its own C++ SectionWorker thread on its
device, passing Scopes through blocking queues, microbatch by microbatch.

TPU-native re-design — the pipeline is ONE functional program:
  * stages are sub-blocks; every device traces ALL stages but executes only
    its own via lax.switch on lax.axis_index("pp");
  * the GPipe microbatch schedule is a lax.scan over M + K - 1 ticks whose
    carry is the boundary activation, moved stage-to-stage by
    lax.ppermute — ICI neighbor traffic, no host queues;
  * the BACKWARD pipeline is not hand-built: append_backward's generic
    __vjp__ differentiates the whole emitter, and JAX's reverse-mode
    transposes the scan (reverse ticks) and the ppermute (reverse ring),
    yielding the mirror-image backward schedule the reference implemented
    manually in SectionWorker;
  * each device ends up with nonzero grads only for its own stage's
    parameters, so PipelineOptimizer inserts one c_allreduce_sum over "pp"
    per grad before the update ops (keeps replicated optimizer state
    identical on all devices).

Constraints (checked at build time): adjacent stages communicate through
exactly ONE boundary var, all boundary vars share one shape/dtype, the
global batch divides evenly into num_microbatches, and data feeds flow in
through stage-local slicing (every device holds the replicated feed and
slices its current microbatch index).

Objective semantics: the step loss is the UNIFORM MEAN of per-microbatch
losses — the reference PipelineOptimizer/SectionWorker accumulate exactly
the same way. For losses that normalize per batch (e.g. a masked mean whose
denominator varies by sample), this weights microbatches equally rather
than weighting by denominator, so it matches the unpipelined objective only
when microbatch denominators are equal (plain batch means always are).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op, run_op


def _stage_of(op, prev_stage):
    dev = op.attr("op_device")
    if dev is None:
        return prev_stage
    if not str(dev).startswith("pipeline:"):
        raise ValueError(
            f"op_device {dev!r} is not a pipeline stage annotation "
            "(expected 'pipeline:<k>')"
        )
    return int(str(dev).split(":", 1)[1])


def _pipeline_infer(block, inputs, attrs):
    return {"Loss": [((1,), "float32")]}


@register_op(
    "pipeline_block",
    inputs=["Feeds", "Extern"],
    outputs=["Loss"],
    infer_shape=_pipeline_infer,
)
def _pipeline_block(ctx, op, ins):
    prog = ctx.program
    stage_blocks = op.attr("stage_blocks")
    K = len(stage_blocks)
    M = op.attr("num_microbatches")
    feed_names = op.attr("feed_names")
    extern_names = op.attr("extern_names")
    boundary = op.attr("boundary_names")  # len K-1, in-producing-stage name
    loss_name = op.attr("loss_name")
    axis = op.attr("axis_name", "pp")

    feeds = dict(zip(feed_names, ins.get("Feeds", [])))
    extern = dict(zip(extern_names, ins.get("Extern", [])))

    from .. import observability as _obs

    _obs.add("collective.pipeline_microbatches", M)
    if axis not in ctx.mesh_axes:
        # single-device degrade: run the stages sequentially per microbatch
        # (identical numerics, no pipeline) — reference nranks==1 behavior
        base_key = (
            ctx.step_key if ctx.step_key is not None else jax.random.key(0)
        )
        total = 0.0
        for m in range(M):
            env = dict(extern)
            for nm, v in feeds.items():
                mb = v.reshape((M, v.shape[0] // M) + v.shape[1:])
                env[nm] = mb[m]
            # distinct RNG per microbatch (mirrors the mesh path's per-tick
            # fold-in; otherwise all M dropout masks repeat)
            sub_ctx = ctx.with_key(
                jax.random.fold_in(base_key, m)
            ).with_batch_divisor(M)
            for bi in stage_blocks:
                blk = prog.blocks[bi]
                for sub_op in blk.ops:
                    run_op(sub_ctx, sub_op, env)
            total = total + env[loss_name].reshape(())
        return {"Loss": [(total / M).reshape([1])]}

    K_mesh = ctx.axis_sizes[axis]
    if K_mesh != K:
        raise ValueError(
            f"pipeline has {K} stages but mesh axis {axis!r} has size {K_mesh}"
        )
    stage_id = lax.axis_index(axis)

    # microbatched feed views: [B, ...] -> [M, B//M, ...]
    mb_feeds = {}
    for nm, v in feeds.items():
        if v.shape[0] % M:
            raise ValueError(
                f"feed {nm!r} batch {v.shape[0]} not divisible by "
                f"num_microbatches={M}"
            )
        mb_feeds[nm] = v.reshape((M, v.shape[0] // M) + v.shape[1:])

    # boundary template: all cuts share one shape/dtype (build-time checked).
    # Recorded shapes are full-batch; activations flowing between stages are
    # microbatches, so the leading (batch) dim shrinks by M
    full = tuple(op.attr("boundary_shape"))
    if not full or full[0] % M:
        raise ValueError(
            f"pipeline boundary shape {full} must be batch-major with a "
            f"leading dim divisible by num_microbatches={M}"
        )
    b_shape = (full[0] // M,) + full[1:]
    b_dtype = np.dtype(op.attr("boundary_dtype"))

    # per-step ICI boundary traffic: one ppermute of the microbatch
    # activation per schedule tick (trace-time count, like ops/collective.py)
    ticks = M + K - 1
    _obs.add("collective.pipeline_ppermute", ticks)
    _obs.add(
        "collective.pipeline_ppermute.bytes",
        ticks * int(np.prod(b_shape)) * b_dtype.itemsize,
    )

    def make_stage_fn(k):
        blk = prog.blocks[stage_blocks[k]]
        in_boundary = boundary[k - 1] if k > 0 else None
        out_boundary = boundary[k] if k < K - 1 else None

        def fn(act_in, mb_idx, tick_key):
            env = dict(extern)
            idx = jnp.clip(mb_idx, 0, M - 1)
            for nm, v in mb_feeds.items():
                env[nm] = lax.dynamic_index_in_dim(v, idx, 0, keepdims=False)
            if in_boundary is not None:
                env[in_boundary] = act_in
            sub_ctx = ctx.with_key(tick_key).with_batch_divisor(M)
            for sub_op in blk.ops:
                run_op(sub_ctx, sub_op, env)
            act_out = (
                env[out_boundary]
                if out_boundary is not None
                else jnp.zeros(b_shape, b_dtype)
            )
            loss = (
                env[loss_name].reshape(()).astype(jnp.float32)
                if k == K - 1
                else jnp.zeros((), jnp.float32)
            )
            return act_out.astype(b_dtype), loss

        return fn

    stage_fns = [make_stage_fn(k) for k in range(K)]
    fwd_perm = [(i, (i + 1) % K) for i in range(K)]

    base_key = (
        ctx.step_key if ctx.step_key is not None else jax.random.key(0)
    )

    def tick(carry, t):
        send_buf, loss_acc = carry
        recv = lax.ppermute(send_buf, axis, fwd_perm)
        mb_idx = t - stage_id
        key = jax.random.fold_in(base_key, t)
        act_out, loss_mb = lax.switch(
            stage_id, stage_fns, recv, mb_idx, key
        )
        valid = jnp.logical_and(mb_idx >= 0, mb_idx < M)
        loss_acc = loss_acc + jnp.where(valid, loss_mb, 0.0)
        return (act_out, loss_acc), None

    init = (jnp.zeros(b_shape, b_dtype), jnp.zeros((), jnp.float32))
    (final_act, loss_acc), _ = lax.scan(
        tick, init, jnp.arange(M + K - 1, dtype=jnp.int32)
    )
    # only the last stage accumulated loss; replicate via psum
    total = lax.psum(loss_acc, axis)
    # The psum replicates the loss K times and each replica receives a unit
    # cotangent from append_backward's seed, so reverse-mode scales every
    # gradient by K (psum transposes to psum under shard_map). Rescale the
    # GRADIENT only: value is total, cotangent shrinks by 1/K.
    total = total / K + lax.stop_gradient(total * (K - 1) / K)
    return {"Loss": [(total / M).reshape([1])]}


def slice_program_into_stages(program, loss):
    """Rewrite the main block: forward ops -> per-stage sub-blocks + one
    pipeline_block op. Returns (num_stages, pipeline_op)."""
    block = program.global_block
    fwd_ops = list(block.ops)

    stages = []
    cur = 0
    for op in fwd_ops:
        cur = _stage_of(op, cur)
        stages.append(cur)
    K = max(stages) + 1
    if sorted(set(stages)) != list(range(K)):
        raise ValueError(f"pipeline stages must be contiguous 0..{K - 1}")
    for a, b in zip(stages, stages[1:]):
        if b < a:
            raise ValueError(
                "pipeline stage annotations must be non-decreasing in "
                "program order"
            )

    produced_by = {}
    for op, st in zip(fwd_ops, stages):
        for n in op.output_names():
            produced_by[n] = st

    # classify inputs: feeds (is_data), extern (params/persistables or
    # pre-existing scope vars), boundaries (cross-stage dataflow)
    feed_names, extern_names = [], []
    boundary = [None] * (K - 1)
    for op, st in zip(fwd_ops, stages):
        for n in op.input_names():
            if not n:
                continue
            src = produced_by.get(n)
            if src is None:
                v = block._find_var_recursive(n)
                if v is not None and v.is_data:
                    if n not in feed_names:
                        feed_names.append(n)
                elif n not in extern_names:
                    extern_names.append(n)
            elif src != st:
                if src != st - 1:
                    raise ValueError(
                        f"var {n!r} produced in stage {src} consumed in "
                        f"stage {st}: only adjacent-stage dataflow is "
                        "supported (re-forward it or move the op)"
                    )
                if boundary[src] is not None and boundary[src] != n:
                    raise ValueError(
                        f"stages {src}->{st} communicate through more than "
                        f"one var ({boundary[src]!r}, {n!r}); cut the "
                        "program so one activation crosses each boundary"
                    )
                boundary[src] = n
    for k, b in enumerate(boundary):
        if b is None:
            raise ValueError(f"no dataflow crosses the {k}->{k + 1} boundary")
    b_vars = [block.var(n) for n in boundary]
    b_shape = tuple(b_vars[0].shape or ())
    b_dtype = b_vars[0].dtype
    for v in b_vars[1:]:
        if tuple(v.shape or ()) != b_shape or v.dtype != b_dtype:
            raise ValueError(
                "all pipeline boundary vars must share one shape/dtype "
                f"({boundary[0]}:{b_shape}/{b_dtype} vs "
                f"{v.name}:{v.shape}/{v.dtype})"
            )

    # move forward ops into per-stage sub-blocks
    stage_blocks = []
    for k in range(K):
        sub = program.create_block()
        program.rollback()
        sub.ops = [op for op, st in zip(fwd_ops, stages) if st == k]
        stage_blocks.append(sub.idx)

    block.ops = []  # forward ops now live in the stage sub-blocks
    pipe_op = block.append_op(
        "pipeline_block",
        {"Feeds": list(feed_names), "Extern": list(extern_names)},
        {"Loss": [loss.name]},
        {
            "stage_blocks": stage_blocks,
            "num_microbatches": program._pipeline["num_microbatches"],
            "feed_names": list(feed_names),
            "extern_names": list(extern_names),
            "boundary_names": list(boundary),
            "boundary_shape": list(b_shape),
            "boundary_dtype": b_dtype,
            "loss_name": loss.name,
            "axis_name": program._pipeline.get("axis_name", "pp"),
        },
    )
    # the loss var keeps its name but is now produced by pipeline_block with
    # shape [1] (mean over microbatches)
    loss.shape = (1,)
    return K, pipe_op


class PipelineOptimizer:
    """reference PipelineOptimizer parity (optimizer.py:3556): wrap an inner
    optimizer; cut the forward program at device_guard("pipeline:k")
    annotations; train with M microbatches per step.

        with fluid.device_guard("pipeline:0"):
            h = encoder_first_half(x)
        with fluid.device_guard("pipeline:1"):
            loss = head(encoder_second_half(h), y)
        opt = PipelineOptimizer(fluid.optimizer.Adam(1e-3), num_microbatches=4)
        opt.minimize(loss)
        shard_program(main, Mesh(devices, ("pp",)))
    """

    def __init__(self, optimizer, num_microbatches=1, axis_name="pp",
                 start_cpu_core_id=0):
        self._inner = optimizer
        self._m = int(num_microbatches)
        self._axis = axis_name

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        program._pipeline = {
            "num_microbatches": self._m,
            "axis_name": self._axis,
        }
        K, _ = slice_program_into_stages(program, loss)

        from ..framework.program import program_guard, default_startup_program

        with program_guard(
            program, startup_program or default_startup_program()
        ):
            params_grads = self._inner.backward(
                loss, startup_program, parameter_list, no_grad_set
            )
            blk = program.global_block
            # each grad is nonzero only on its stage's device: allreduce
            # over the pp axis so every device applies identical updates
            # (inserted before AMP bookkeeping — see insert_grad_allreduce)
            from .transpiler import insert_grad_allreduce

            for _, g in params_grads:
                insert_grad_allreduce(blk, g, self._axis)
            ops = self._inner.apply_gradients(params_grads)
        return ops, params_grads
